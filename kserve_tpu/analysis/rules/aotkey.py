"""Rule ``aot-cache-key-drift``: engine-config reads inside compiled-
program construction that the AOT cache-key digest does not cover.

The persistent AOT executable cache (engine/aot_cache.py,
docs/coldstart.md) keys executables by a digest of
``AOT_KEY_ENGINE_FIELDS`` — the EngineConfig fields that determine the
compiled artifact.  If ``build_compiled`` starts reading a NEW config
field (a new dtype knob, a kernel-selection flag) without that field
joining the digest list, two deployments differing only in that field
silently SHARE executables: the stale-executable hazard, which on a real
fleet surfaces as wrong numerics or shape crashes on warm starts only —
the worst kind of heisenbug.  This rule pins the two in lockstep: every
``<engine-config>.field`` read (attribute or ``getattr``) inside a
compiled-program builder — a function named ``build_compiled`` or
``program_defs`` (the extracted definition table both dispatch modes and
the hlo_oracle build from) — must appear in ``AOT_KEY_ENGINE_FIELDS``.

The allowlist is resolved from the linted source itself when it defines
``AOT_KEY_ENGINE_FIELDS`` (test fixtures), else from the sibling
``aot_cache.py`` next to the linted file (the real tree layout).  The
model config and mesh are digested WHOLE by aot_cache_key, so only the
engine-config parameter needs field-level tracking.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, Optional, Set

from ..core import FileContext, Finding, Rule, register

#: names the engine-config parameter (and its aliases) goes by in
#: compiled-program builders
_CONFIG_PARAM_NAMES = {"engine_config", "cfg"}

#: the functions whose engine-config reads this rule audits.  program_defs
#: is the extracted definition table (engine/compiled.py) — moving reads
#: there must NOT escape the audit.
_BUILDER_NAMES = {"build_compiled", "program_defs"}

_LIST_NAME = "AOT_KEY_ENGINE_FIELDS"


def _fields_from_tree(tree: ast.Module) -> Optional[Set[str]]:
    """The AOT_KEY_ENGINE_FIELDS literal tuple/list in a module, if any."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == _LIST_NAME
            for t in node.targets
        ):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            fields = set()
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    fields.add(elt.value)
            return fields
    return None


def _sibling_fields(path: str) -> Optional[Set[str]]:
    """AOT_KEY_ENGINE_FIELDS from aot_cache.py next to the linted file."""
    sibling = os.path.join(os.path.dirname(path), "aot_cache.py")
    try:
        with open(sibling, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=sibling)
    except (OSError, SyntaxError):
        return None
    return _fields_from_tree(tree)


def _config_aliases(fn: ast.FunctionDef) -> Set[str]:
    """The engine-config parameter name plus simple `x = cfg` aliases."""
    names = {
        a.arg for a in fn.args.args if a.arg in _CONFIG_PARAM_NAMES
    }
    if not names:
        return names
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Name)
            and node.value.id in names
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


@register
class AOTCacheKeyDrift(Rule):
    id = "aot-cache-key-drift"
    description = (
        "engine-config field read inside build_compiled/program_defs but "
        "missing from AOT_KEY_ENGINE_FIELDS: configs differing in that "
        "field would silently share stale AOT-cached executables"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        builders = [
            node for node in ast.walk(ctx.tree)
            if isinstance(node, ast.FunctionDef)
            and node.name in _BUILDER_NAMES
        ]
        if not builders:
            return
        fields = _fields_from_tree(ctx.tree)
        if fields is None:
            fields = _sibling_fields(ctx.path)
        if fields is None:
            for fn in builders:
                yield self.finding(
                    ctx, fn,
                    f"{fn.name} found but no AOT_KEY_ENGINE_FIELDS "
                    "literal is resolvable (in this file or a sibling "
                    "aot_cache.py): the cache-key digest cannot be "
                    "audited against the fields this builder reads",
                )
            return
        for fn in builders:
            aliases = _config_aliases(fn)
            if not aliases:
                continue
            for node in ast.walk(fn):
                # cfg.field (attribute read, incl. cfg.field(...) calls)
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in aliases
                    and node.attr not in fields
                ):
                    yield self.finding(
                        ctx, node,
                        f"{node.value.id}.{node.attr} read during "
                        "compiled-program construction is not in "
                        "AOT_KEY_ENGINE_FIELDS — configs differing in "
                        f"{node.attr!r} would share stale AOT executables",
                    )
                # getattr(cfg, "field", ...) spelling
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "getattr"
                    and len(node.args) >= 2
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in aliases
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)
                    and node.args[1].value not in fields
                ):
                    yield self.finding(
                        ctx, node,
                        f"getattr({node.args[0].id}, "
                        f"{node.args[1].value!r}) during compiled-program "
                        "construction is not in AOT_KEY_ENGINE_FIELDS — "
                        "configs differing in that field would share "
                        "stale AOT executables",
                    )
