"""Rule ``ragged-metadata-host-sync``: host reads of ragged packing
metadata inside jit-traced code.

The unified ragged program (docs/kernels.md) threads per-sequence packing
metadata — q_start / q_len / kv_start, the per-token token_seq /
token_pos, and the kernel's block_seq / block_qoff — through traced code
as device arrays.  Calling ``.item()`` / ``int()`` / ``float()`` on them
(or ``.tolist()``, which the generic host-sync rule already flags) forces
a device->host sync per dispatch, serializing the TPU against the Python
thread exactly where the mixed program is hottest.  Derive per-token
views ON DEVICE (ops/attention.ragged_token_metadata) and keep the host
copy of the metadata in the numpy planning arrays the engine builds
before dispatch.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule, register

#: metadata names covered by the ragged packing contract (docs/kernels.md)
RAGGED_METADATA_NAMES = {
    "q_start", "q_len", "kv_start", "token_seq", "token_pos",
    "block_seq", "block_qoff", "last_idx",
}

_SCALAR_CASTS = {"int", "float", "bool"}


def _base_name(node: ast.AST):
    """The identifier a metadata access hangs off: `q_start`,
    `meta.q_start`, `q_start[i]` all resolve to "q_start"."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


@register
class RaggedMetadataHostSync(Rule):
    id = "ragged-metadata-host-sync"
    description = (
        ".item()/int()/float() on ragged packing metadata inside a "
        "jit-traced function: a per-dispatch device->host sync on the "
        "mixed program's hot path"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ctx.traced_functions():
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for root in body:
                for node in ast.walk(root):
                    if not isinstance(node, ast.Call):
                        continue
                    # <metadata>.item()
                    if (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"
                        and not node.args
                        and _base_name(node.func.value)
                        in RAGGED_METADATA_NAMES
                    ):
                        yield self.finding(
                            ctx, node,
                            f"{_base_name(node.func.value)}.item() inside "
                            "a jit-traced function syncs ragged packing "
                            "metadata to the host; keep it on device "
                            "(ops/attention.ragged_token_metadata)",
                        )
                        continue
                    # int(<metadata>) / float(<metadata>) / bool(...)
                    if (
                        isinstance(node.func, ast.Name)
                        and node.func.id in _SCALAR_CASTS
                        and len(node.args) == 1
                        and _base_name(node.args[0])
                        in RAGGED_METADATA_NAMES
                    ):
                        yield self.finding(
                            ctx, node,
                            f"{node.func.id}() on ragged packing metadata "
                            "inside a jit-traced function is a "
                            "device->host sync; plan on the host (numpy) "
                            "or derive on device",
                        )
