"""Rules ``ragged-metadata-host-sync`` and ``spec-accept-host-sync``:
host reads of ragged packing / speculative-acceptance metadata inside
jit-traced code.

The unified ragged program (docs/kernels.md) threads per-sequence packing
metadata — q_start / q_len / kv_start, the per-token token_seq /
token_pos, and the kernel's block_seq / block_qoff — through traced code
as device arrays.  Calling ``.item()`` / ``int()`` / ``float()`` on them
(or ``.tolist()``, which the generic host-sync rule already flags) forces
a device->host sync per dispatch, serializing the TPU against the Python
thread exactly where the mixed program is hottest.  Derive per-token
views ON DEVICE (ops/attention.ragged_token_metadata) and keep the host
copy of the metadata in the numpy planning arrays the engine builds
before dispatch.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule, register

#: metadata names covered by the ragged packing contract (docs/kernels.md)
RAGGED_METADATA_NAMES = {
    "q_start", "q_len", "kv_start", "token_seq", "token_pos",
    "block_seq", "block_qoff", "last_idx",
}

#: speculative-decoding acceptance/rollback metadata (docs/kernels.md):
#: per-lane accepted-prefix lengths, emit counts, drafts and the bigram
#: draft table.  A host cast on any of these inside traced code would
#: sync the device PER VERIFY ROUND — the accept path must stay
#: vectorized on device, with the host reading only the once-per-dispatch
#: fetched (toks, n) outputs.
SPEC_ACCEPT_NAMES = {
    "acc", "acc_len", "n_emit", "drafts", "draft_table",
    "spec_toks", "spec_n",
}

_SCALAR_CASTS = {"int", "float", "bool"}


def _base_name(node: ast.AST):
    """The identifier a metadata access hangs off: `q_start`,
    `meta.q_start`, `q_start[i]` all resolve to "q_start"."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class _MetadataHostSync(Rule):
    """Shared detector: ``.item()`` / scalar casts on a named metadata
    set inside jit-traced functions."""

    names: frozenset = frozenset()
    what: str = "metadata"
    hint: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ctx.traced_functions():
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for root in body:
                for node in ast.walk(root):
                    if not isinstance(node, ast.Call):
                        continue
                    # <metadata>.item()
                    if (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"
                        and not node.args
                        and _base_name(node.func.value) in self.names
                    ):
                        yield self.finding(
                            ctx, node,
                            f"{_base_name(node.func.value)}.item() inside "
                            f"a jit-traced function syncs {self.what} to "
                            f"the host; {self.hint}",
                        )
                        continue
                    # int(<metadata>) / float(<metadata>) / bool(...)
                    if (
                        isinstance(node.func, ast.Name)
                        and node.func.id in _SCALAR_CASTS
                        and len(node.args) == 1
                        and _base_name(node.args[0]) in self.names
                    ):
                        yield self.finding(
                            ctx, node,
                            f"{node.func.id}() on {self.what} inside a "
                            "jit-traced function is a device->host sync; "
                            f"{self.hint}",
                        )


@register
class RaggedMetadataHostSync(_MetadataHostSync):
    id = "ragged-metadata-host-sync"
    description = (
        ".item()/int()/float() on ragged packing metadata inside a "
        "jit-traced function: a per-dispatch device->host sync on the "
        "mixed program's hot path"
    )
    names = frozenset(RAGGED_METADATA_NAMES)
    what = "ragged packing metadata"
    hint = ("keep it on device (ops/attention.ragged_token_metadata) or "
            "plan on the host (numpy)")


@register
class SpecAcceptHostSync(_MetadataHostSync):
    id = "spec-accept-host-sync"
    description = (
        ".item()/int()/float() on speculative acceptance/rollback "
        "metadata inside a jit-traced function: a per-verify-round "
        "device->host sync on the mixed_decode hot path"
    )
    names = frozenset(SPEC_ACCEPT_NAMES)
    what = "speculative acceptance metadata"
    hint = ("compute the accepted-prefix/rollback entirely on device "
            "(engine/compiled.py mixed_decode) — the host reads only the "
            "once-per-dispatch fetched outputs")
