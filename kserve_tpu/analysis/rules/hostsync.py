"""Rule ``host-sync``: implicit device-to-host transfers inside
jit-traced code.  ``np.asarray(x)`` / ``np.array(x)`` / ``x.tolist()`` /
``jax.device_get(x)`` on a traced value pulls the array to the host —
inside the decode/prefill step functions that is a per-token sync that
serializes the TPU against the Python thread and destroys decode
throughput.  Keep the math in jnp; convert on the host *after* the step
returns.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule, register
from ..jaxutil import dotted_name

_TRANSFER_CALLS = {
    "np.asarray", "np.array", "np.copy", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array", "jax.device_get", "device_get",
}
_TRANSFER_METHODS = {"tolist", "to_py"}


@register
class HostSyncInTracedCode(Rule):
    id = "host-sync"
    description = (
        "np.asarray/.tolist()/device_get inside a jit-traced function: a "
        "device-to-host transfer on the hot path"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ctx.traced_functions():
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for root in body:
                for node in ast.walk(root):
                    if not isinstance(node, ast.Call):
                        continue
                    name = dotted_name(node.func)
                    if name in _TRANSFER_CALLS:
                        yield self.finding(
                            ctx,
                            node,
                            f"{name}() inside a jit-traced function is a "
                            "device-to-host transfer; use jnp and convert "
                            "after the step returns",
                        )
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in _TRANSFER_METHODS
                        and not node.args
                    ):
                        yield self.finding(
                            ctx,
                            node,
                            f".{node.func.attr}() inside a jit-traced "
                            "function syncs device to host on the hot path",
                        )
