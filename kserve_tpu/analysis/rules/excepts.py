"""Rule ``swallowed-exception``: a bare/broad ``except`` that neither
logs, re-raises, nor converts to a typed ``kserve_tpu.errors`` error.

In a serving stack a swallowed exception is a wrong answer served with a
200: the reconciler that silently skips an object, the storage download
whose failure surfaces three layers later as "model not ready".  Broad
catches are legitimate at daemon/loop boundaries — but only when they
*log with context* or translate to a typed error; anything else must
narrow the exception type or carry a justified suppression.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule, register
from ..jaxutil import dotted_name

_BROAD = {"Exception", "BaseException"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    name = dotted_name(handler.type)
    return name is not None and name.split(".")[-1] in _BROAD


def _handler_disposes(handler: ast.ExceptHandler) -> bool:
    """True when the handler raises, logs, warns, or relays the exception
    to a waiter via ``fut.set_exception(exc)`` somewhere in its body
    (nested defs excluded — a callback defined in the handler does not
    handle this exception)."""
    stack = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name in ("warnings.warn", "traceback.print_exc"):
                return True
            if isinstance(node.func, ast.Attribute) and (
                node.func.attr in _LOG_METHODS
                or node.func.attr == "set_exception"
            ):
                return True
        stack.extend(ast.iter_child_nodes(node))
    return False


@register
class SwallowedException(Rule):
    id = "swallowed-exception"
    description = (
        "broad 'except Exception' that neither logs, re-raises, nor "
        "converts to a typed kserve_tpu.errors error"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node) and not _handler_disposes(node):
                what = (
                    "bare except" if node.type is None
                    else f"except {dotted_name(node.type)}"
                )
                yield self.finding(
                    ctx,
                    node,
                    f"{what} swallows the error: narrow the type, log with "
                    "context, or re-raise as a typed kserve_tpu.errors error",
                )
