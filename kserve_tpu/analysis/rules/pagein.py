"""Rule ``pagein-host-sync``: blocking device syncs inside the
hierarchical KV store's page-in/upload path.

The async prefix page-in (docs/kv_hierarchy.md, engine._page_in) is
overlap-or-nothing: the tier/disk read rides the fetch worker
(``fetch_async`` — the PR 5 seam) and the device upload is a
DISPATCH-ONLY inject scatter, so decode lanes keep advancing under the
whole promotion.  One synchronous fetch on that path — a direct
``fetch()``/``_fetch()`` call, ``.block_until_ready()``,
``jax.device_get`` or an ``.item()``/``.tolist()`` read of the inject's
result — silently serializes the upload against the engine loop and the
overlap the subsystem exists for is gone (it still *works*, which is why
a linter has to catch it).

Scope: functions whose name contains ``page_in``/``pagein`` (the
engine's ``_page_in``/``_maybe_page_in`` and any future kvstore upload
helper) plus the peer-fetch family (``fetch_page``/``fetch_from``/
``peer_fetch`` — kvstore/peer.py's verified cross-replica leg, which
rides the same dispatch-only upload and additionally must never block
the event loop, so ``time.sleep`` is flagged there too; waits go
through the injected clock).  The blocking work belongs inside the
thunk handed to ``fetch_async`` — which runs on the worker — not in
the coroutine body.  This is the upload-path extension of the
``host-sync`` / ``ragged-metadata-host-sync`` family.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..core import FileContext, Finding, Rule, register
from ..jaxutil import dotted_name

_PAGEIN_NAME = re.compile(
    r"page_?in|fetch_page|fetch_from|peer_fetch", re.IGNORECASE)

#: attribute calls that block the caller on the device
_BLOCKING_METHODS = {"block_until_ready", "item", "tolist", "to_py"}
#: sync fetch entry points (the async spelling, fetch_async, is the
#: REQUIRED one on this path and is not flagged)
_SYNC_FETCH_ATTRS = {"fetch", "_fetch"}
_TRANSFER_CALLS = {"jax.device_get", "device_get"}
#: wall-clock blocking inside the (async) peer-fetch path — waits there
#: must ride the injected clock (clock.sleep), never the thread
_WALL_SLEEP_CALLS = {"time.sleep"}


@register
class PageInHostSync(Rule):
    id = "pagein-host-sync"
    description = (
        "blocking fetch/.block_until_ready()/.item() inside a KV page-in "
        "function: the async upload path must stay dispatch-only so it "
        "overlaps decode"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _PAGEIN_NAME.search(node.name):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                name = dotted_name(sub.func)
                if name in _WALL_SLEEP_CALLS:
                    yield self.finding(
                        ctx, sub,
                        f"{name}() inside {node.name}(): wall-clock "
                        "sleep blocks the event loop on the page-in/"
                        "peer-fetch path; await the injected "
                        "clock.sleep() instead",
                    )
                    continue
                if name in _TRANSFER_CALLS:
                    yield self.finding(
                        ctx, sub,
                        f"{name}() inside {node.name}(): a blocking "
                        "device->host transfer on the page-in path; move "
                        "it into the fetch_async thunk",
                    )
                    continue
                if not isinstance(sub.func, ast.Attribute):
                    continue
                attr = sub.func.attr
                if attr in _SYNC_FETCH_ATTRS:
                    yield self.finding(
                        ctx, sub,
                        f".{attr}() inside {node.name}(): synchronous "
                        "fetch on the page-in path serializes the upload "
                        "against decode; use fetch_async",
                    )
                elif attr in _BLOCKING_METHODS and not sub.args:
                    yield self.finding(
                        ctx, sub,
                        f".{attr}() inside {node.name}(): blocks on the "
                        "device result — the page-in upload must stay "
                        "dispatch-only",
                    )
