"""jaxlint rule modules — importing this package registers every rule.

Add a new rule by dropping a module here that subclasses
:class:`kserve_tpu.analysis.core.Rule` and decorating it with
:func:`kserve_tpu.analysis.core.register`, then importing it below.
"""

from . import (  # noqa: F401
    aotkey,
    blocking,
    donation,
    excepts,
    hostsync,
    pagein,
    pspec,
    ragged,
    recompile,
    taskleak,
)
