"""Rule ``recompile-hazard``: host-side concretization inside jit-traced
code.  ``bool(x)`` / ``float(x)`` / ``int(x)`` on a traced value either
raises a ConcretizationTypeError at trace time or — when the value happens
to be a weakly-typed Python scalar that changed — silently retraces and
recompiles the whole program, which is the classic cause of multi-second
tail-latency spikes in a serving step loop.  ``.item()`` is the same
hazard spelled as a method.

Static shapes are fine: casts whose argument goes through ``.shape``,
``.ndim``, ``.size`` or ``len(...)`` are not flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule, register
from ..jaxutil import dotted_name

_CASTS = {"bool", "float", "int"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}


def _is_static_expr(node: ast.AST) -> bool:
    """True when the cast argument is trace-time static: literals, shape /
    ndim / dtype attribute chains, len() calls, or arithmetic over those."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS or _is_static_expr(node.value)
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.Call):
        return dotted_name(node.func) == "len"
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    return False


@register
class RecompileHazard(Rule):
    id = "recompile-hazard"
    description = (
        "bool()/int()/float()/.item() on a traced value inside jit forces "
        "concretization: a trace-time error or a silent recompile"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ctx.traced_functions():
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for root in body:
                for node in ast.walk(root):
                    if not isinstance(node, ast.Call):
                        continue
                    name = dotted_name(node.func)
                    if (
                        name in _CASTS
                        and len(node.args) == 1
                        and not node.keywords
                        and not _is_static_expr(node.args[0])
                    ):
                        yield self.finding(
                            ctx,
                            node,
                            f"{name}() on a traced value inside a jit-compiled "
                            "function concretizes it (trace error or silent "
                            "recompile); use jnp ops or mark the argument "
                            "static",
                        )
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"
                        and not node.args
                    ):
                        yield self.finding(
                            ctx,
                            node,
                            ".item() inside a jit-compiled function forces a "
                            "device-to-host transfer per step; keep the value "
                            "on device",
                        )
