"""Rule ``task-leak``: an ``asyncio`` task created and dropped.

``create_task(...)`` whose result is discarded — a bare expression
statement, neither assigned, appended to a registry, passed onward, nor
awaited — is a double hazard in this codebase:

1. the event loop holds tasks only WEAKLY: a dropped Task can be
   garbage-collected mid-flight and silently never finish (the EPP's
   endpoint rediscovery loop was exactly this shape);
2. an orphan task can never be cancelled at ``stop()`` and is invisible
   to the engine watchdog's task-stall accounting
   (engine/watchdog.py) — the gray-failure defense only reaps tasks it
   can enumerate.

Keep a strong reference (assign it, add it to a tracked set with a
done-callback, or use a helper like ``engine._track_task``).  Genuine
fire-and-forget is rare enough to justify per-line suppression.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule, register


def _is_create_task(call: ast.Call) -> bool:
    """Matches ``asyncio.create_task(...)``, ``loop.create_task(...)``
    and ``asyncio.get_running_loop().create_task(...)`` (any attribute
    spelling), plus a bare ``create_task(...)`` import."""
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr == "create_task"
    if isinstance(func, ast.Name):
        return func.id == "create_task"
    return False


@register
class TaskLeak(Rule):
    id = "task-leak"
    description = (
        "create_task(...) result dropped: the loop holds tasks weakly "
        "(GC can kill it mid-flight), stop() cannot cancel it, and the "
        "watchdog's stall accounting cannot see it"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            # only a bare expression statement drops the Task; any other
            # position (assignment, argument, await, return, append)
            # keeps a reference the caller can manage
            if not isinstance(node, ast.Expr):
                continue
            call = node.value
            if isinstance(call, ast.Call) and _is_create_task(call):
                yield self.finding(
                    ctx, call,
                    "create_task(...) result dropped — keep a strong "
                    "reference (assign / track in a registry with a "
                    "done-callback) so GC, stop() and the watchdog can "
                    "all see the task",
                )
