"""Rule ``donated-buffer-reuse``: a buffer passed through a
``donate_argnums``/``donate_argnames`` position is dead after the call —
XLA may have aliased its memory to the output.  Reading it afterwards
returns garbage (or raises on TPU), and it does so *silently* on CPU test
runs, which is exactly why a static pass has to catch it.

Ground truth for the donation-site shapes this rule understands: the six
``jax.jit(..., donate_argnums=...)`` sites in engine/compiled.py — name
bindings, keyword-constructor bindings, and immediately-invoked jits.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import FileContext, Finding, Rule, register
from ..jaxutil import JIT_NAMES, dotted_name


def _donation_spec(call: ast.Call) -> Optional[Tuple[Set[int], Set[str]]]:
    """(argnums, argnames) if ``call`` is a jit call that donates, else
    None.  Handles ``jax.jit(f, donate_argnums=(3,))`` and single-int
    forms."""
    if dotted_name(call.func) not in JIT_NAMES:
        return None
    argnums: Set[int] = set()
    argnames: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            argnums |= _int_literals(kw.value)
        elif kw.arg == "donate_argnames":
            argnames |= _str_literals(kw.value)
    if argnums or argnames:
        return argnums, argnames
    return None


def _int_literals(node: ast.AST) -> Set[int]:
    out: Set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.add(elt.value)
    return out


def _str_literals(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
    return out


def _collect_donating_callables(tree: ast.Module) -> Dict[str, Tuple[Set[int], Set[str]]]:
    """Names bound (anywhere in the file) to a donating jit: covers
    ``f = jax.jit(g, donate_argnums=...)`` and attribute bindings like
    ``self.decode = jax.jit(...)`` (keyed by the full dotted target)."""
    out: Dict[str, Tuple[Set[int], Set[str]]] = {}
    for node in ast.walk(tree):
        value = None
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value, targets = node.value, [node.target]
        if not isinstance(value, ast.Call):
            continue
        spec = _donation_spec(value)
        if spec is None:
            continue
        for target in targets:
            name = dotted_name(target)
            if name:
                out[name] = spec
    return out


@register
class DonatedBufferReuse(Rule):
    id = "donated-buffer-reuse"
    description = (
        "an array passed at a donate_argnums/donate_argnames position is "
        "invalidated by the call; any later read sees aliased memory"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        donating = _collect_donating_callables(ctx.tree)
        scopes: List[List[ast.stmt]] = [ctx.tree.body]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node.body)
        for body in scopes:
            yield from self._scan_block(ctx, body, donating, {})

    # ---- linear dataflow over one statement block ----

    def _scan_block(self, ctx, stmts, donating, dead: Dict[str, int]):
        """``dead`` maps variable name -> line where it was donated.
        Branches recurse with a copy of ``dead``: a donation inside one
        branch does not poison code after the branch (conservative — no
        false positives from paths that may not execute)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.If, ast.While)):
                yield from self._scan_exprs(ctx, [stmt.test], donating, dead)
                for branch in (stmt.body, stmt.orelse):
                    yield from self._scan_block(ctx, branch, donating, dict(dead))
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                yield from self._scan_exprs(ctx, [stmt.iter], donating, dead)
                dead.pop(getattr(stmt.target, "id", None), None)
                for branch in (stmt.body, stmt.orelse):
                    yield from self._scan_block(ctx, branch, donating, dict(dead))
                continue
            if isinstance(stmt, ast.Try):
                for branch in (stmt.body, stmt.orelse, stmt.finalbody):
                    yield from self._scan_block(ctx, branch, donating, dict(dead))
                for handler in stmt.handlers:
                    yield from self._scan_block(ctx, handler.body, donating, dict(dead))
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._scan_exprs(
                    ctx, [i.context_expr for i in stmt.items], donating, dead
                )
                # with-bodies execute unconditionally: propagate, don't copy
                yield from self._scan_block(ctx, stmt.body, donating, dead)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # separate scope — check() scans each on its own

            # linear statement: reads of dead names, then rebinds, then
            # new donations
            yield from self._scan_exprs(ctx, [stmt], donating, dead, collect=False)
            rebound = self._bound_names(stmt)
            for name in rebound:
                dead.pop(name, None)
            for call in (n for n in ast.walk(stmt) if isinstance(n, ast.Call)):
                for name, line in self._donated_args(call, donating):
                    # `kv = f(kv)` rebinds to the result — the correct idiom
                    if name not in rebound:
                        dead[name] = line

    def _scan_exprs(self, ctx, nodes, donating, dead, collect: bool = True):
        """Flag reads of dead names inside ``nodes``; with ``collect``,
        also record donations made by calls there."""
        for root in nodes:
            if root is None:
                continue
            for node in ast.walk(root):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in dead
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f"'{node.id}' was donated to a jit-compiled call on "
                        f"line {dead[node.id]} and must not be read afterwards "
                        "(its buffer may be aliased to the output)",
                    )
                    dead.pop(node.id, None)  # report once per name
                if collect and isinstance(node, ast.Call):
                    for name, line in self._donated_args(node, donating):
                        dead[name] = line

    @staticmethod
    def _bound_names(stmt: ast.stmt) -> Set[str]:
        out: Set[str] = set()
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                for node in ast.walk(t):
                    if isinstance(node, ast.Name):
                        out.add(node.id)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(stmt.target, ast.Name):
                out.add(stmt.target.id)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        return out

    @staticmethod
    def _donated_args(call: ast.Call, donating) -> Iterator[Tuple[str, int]]:
        spec = None
        name = dotted_name(call.func)
        if name is not None and name in donating:
            spec = donating[name]
        elif isinstance(call.func, ast.Call):
            # immediately-invoked: jax.jit(f, donate_argnums=(0,))(x)
            spec = _donation_spec(call.func)
        if spec is None:
            return
        argnums, argnames = spec
        for i, arg in enumerate(call.args):
            if i in argnums and isinstance(arg, ast.Name):
                yield arg.id, call.lineno
        for kw in call.keywords:
            if kw.arg in argnames and isinstance(kw.value, ast.Name):
                yield kw.value.id, call.lineno
