"""Rule ``blocking-async``: blocking calls on the event loop.

The data plane (REST/gRPC/OpenAI protocol servers, the engine submit path,
the scheduler) is async; one ``time.sleep`` or sync HTTP call inside an
``async def`` stalls *every* in-flight request on that loop — the serving
papers' "hidden host sync" applied to the request path.  Flagged inside
``async def`` bodies:

- ``time.sleep``
- sync HTTP: module-level ``requests.*`` / ``httpx.*`` verbs,
  ``urllib.request.urlopen``
- ``subprocess.run/call/check_call/check_output``, ``os.system``
- blocking file IO via bare ``open(...)``
- ``<x>.block_until_ready()`` (host-device sync)

``time.sleep`` is additionally flagged *anywhere*: in this codebase a
sleep should be ``asyncio.sleep`` (async), a stop-responsive
``Event.wait`` (thread loops), or carry a justified suppression
(dedicated daemon poll loops).

Sync helpers *defined inside* an async def (e.g. thunks handed to
``run_in_executor``) are exempt — nested non-async defs are skipped.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import FileContext, Finding, Rule, register
from ..jaxutil import dotted_name, walk_function_body

_HTTP_VERBS = {"get", "post", "put", "delete", "head", "options", "patch",
               "request", "stream", "send"}
_SUBPROCESS = {"subprocess.run", "subprocess.call", "subprocess.check_call",
               "subprocess.check_output", "os.system"}


def _blocking_reason(node: ast.Call) -> Optional[str]:
    name = dotted_name(node.func)
    if name == "time.sleep":
        return "time.sleep blocks the event loop; use asyncio.sleep"
    if name == "urllib.request.urlopen" or name == "urlopen":
        return "urllib.request.urlopen is synchronous; use aiohttp/httpx.AsyncClient"
    if name in _SUBPROCESS:
        return f"{name} blocks; use asyncio.create_subprocess_* or a thread"
    if name == "open":
        return "blocking file IO on the event loop; use a thread executor"
    if name is not None and "." in name:
        base, attr = name.split(".", 1)
        if base in ("requests", "httpx") and attr in _HTTP_VERBS:
            return (f"{name} is a synchronous HTTP call; use aiohttp or "
                    "httpx.AsyncClient")
    if isinstance(node.func, ast.Attribute) and node.func.attr == "block_until_ready":
        return ("block_until_ready is a host-device sync; await an executor "
                "or restructure the step")
    return None


@register
class BlockingInAsync(Rule):
    id = "blocking-async"
    description = (
        "blocking call (time.sleep, sync HTTP, blocking IO, "
        "block_until_ready) inside an async def — stalls the event loop"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        handled = set()
        executor_thunks = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for sub in walk_function_body(node, skip_nested_defs=True):
                if isinstance(sub, ast.FunctionDef):
                    # a sync helper defined inside an async def is an
                    # executor-destined thunk: exempt from both passes
                    executor_thunks.add(sub)
                if not isinstance(sub, ast.Call):
                    continue
                reason = _blocking_reason(sub)
                if reason:
                    handled.add(sub)
                    yield self.finding(
                        ctx, sub, f"in 'async def {node.name}': {reason}"
                    )
        for thunk in executor_thunks:
            handled.update(
                n for n in ast.walk(thunk) if isinstance(n, ast.Call)
            )
        # time.sleep is a hazard even in sync code here: thread loops
        # should use a stop-responsive Event.wait, clients asyncio.sleep
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Call)
                and node not in handled
                and dotted_name(node.func) == "time.sleep"
            ):
                yield self.finding(
                    ctx,
                    node,
                    "time.sleep in server code: use asyncio.sleep (async), "
                    "a stop-responsive Event.wait (thread loops), or "
                    "suppress with justification (dedicated daemons)",
                )
