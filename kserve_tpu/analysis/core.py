"""jaxlint core: rule registry, suppression handling, and the file runner.

The linter is pure-AST and imports nothing heavy (no jax, no numpy), so it
can run in CI images that lack the accelerator stack.  Each rule is a
subclass of :class:`Rule` registered via :func:`register`; a rule receives a
:class:`FileContext` (source + parsed tree + shared per-file analyses) and
yields :class:`Finding`s.

Suppression syntax (checked by tests/test_jaxlint.py):

- ``# jaxlint: disable=<rule>[,<rule>...]`` trailing on the flagged line
  suppresses those rules for that line only.
- ``# jaxlint: disable-file=<rule>[,<rule>...]`` anywhere in the file
  suppresses those rules for the whole file.
- The rule name ``all`` suppresses every rule.

Every suppression in the real tree must carry a justification in the same
comment (enforced by convention + review, counted in tests).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Set, Type

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)


@dataclass(frozen=True)
class Finding:
    """One lint hit: ``path:line:col  rule  message``."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


@dataclass
class Suppressions:
    file_rules: Set[str] = field(default_factory=set)
    line_rules: Dict[int, Set[str]] = field(default_factory=dict)

    def hides(self, finding: Finding) -> bool:
        if "all" in self.file_rules or finding.rule in self.file_rules:
            return True
        rules = self.line_rules.get(finding.line, ())
        return "all" in rules or finding.rule in rules


def parse_suppressions(source: str) -> Suppressions:
    """Directives are read from real COMMENT tokens only — a directive
    inside a string literal (e.g. a lint-test fixture) must not suppress
    anything in the file that contains it."""
    import io
    import tokenize

    sup = Suppressions()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return sup  # unparseable source is reported as syntax-error anyway
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        if m.group("scope"):
            sup.file_rules |= rules
        else:
            sup.line_rules.setdefault(tok.start[0], set()).update(rules)
    return sup


class FileContext:
    """Everything a rule needs about one file, computed once."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.suppressions = parse_suppressions(source)
        self._traced: Optional[set] = None  # filled lazily by jaxutil

    def traced_functions(self) -> set:
        """Set of FunctionDef/AsyncFunctionDef/Lambda nodes whose bodies
        run under jax tracing (see jaxutil.traced_function_nodes)."""
        if self._traced is None:
            from .jaxutil import traced_function_nodes

            self._traced = traced_function_nodes(self.tree)
        return self._traced


class Rule:
    """Base class for jaxlint rules.  Subclasses set ``id`` (the name used
    in suppression comments) and ``description``, and implement
    :meth:`check`."""

    id: str = ""
    description: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.id,
            path=ctx.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    # importing the rules package populates the registry
    from . import rules  # noqa: F401

    return dict(_REGISTRY)


def lint_source(
    source: str,
    path: str = "<string>",
    select: Optional[Iterable[str]] = None,
    ignore: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Lint one source string; returns unsuppressed findings sorted by
    position.  ``select``/``ignore`` filter by rule id."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [
            Finding(
                rule="syntax-error",
                path=path,
                line=exc.lineno or 1,
                col=exc.offset or 0,
                message=f"file does not parse: {exc.msg}",
            )
        ]
    ctx = FileContext(path, source, tree)
    wanted = all_rules()
    if select:
        keep = set(select)
        wanted = {rid: r for rid, r in wanted.items() if rid in keep}
    if ignore:
        drop = set(ignore)
        wanted = {rid: r for rid, r in wanted.items() if rid not in drop}
    findings: List[Finding] = []
    for rule_cls in wanted.values():
        for finding in rule_cls().check(ctx):
            if not ctx.suppressions.hides(finding):
                findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_file(path, select=None, ignore=None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return lint_source(source, path=str(path), select=select, ignore=ignore)


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    import os

    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".venv", "node_modules")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)
        elif p.endswith(".py"):
            yield p


def lint_paths(paths: Iterable[str], select=None, ignore=None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(lint_file(path, select=select, ignore=ignore))
    return findings
