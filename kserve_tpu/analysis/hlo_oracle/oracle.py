"""Collection: lower + compile every budgeted program and extract its
metrics entry.

The variant matrix covers the program set `build_compiled` produces in
production shapes that matter structurally: the tp=1 full set (both
prefill buckets), the speculative mixed_decode at K=2 and the K=0
dense-packing degenerate, the quantized-cache inject, and a tp=2 mesh
slice whose collective inventory pins the model-axis communication
pattern.  Compiles run on CPU with jax's persistent compilation cache
(the CLI and conftest share /tmp/kserve-tpu-compile-cache), so warm
re-runs cost milliseconds per program.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import jax

from . import extract, signatures

logger = logging.getLogger(__name__)

#: current baseline schema; bump on layout changes so a stale committed
#: perf_budgets.json asks for `update` instead of mis-diffing
SCHEMA_VERSION = 1

#: programs whose costs scale with the prefill length bucket: one entry
#: per configured bucket
_BUCKETED = ("prefill", "prefill_chunk")

#: (variant name, ProgramSet kwargs, program names) — None = every
#: program the variant's defs table builds
VARIANTS: List[Tuple[str, dict, Optional[Tuple[str, ...]]]] = [
    ("tp1", dict(tp=1), None),
    ("tp1_spec", dict(tp=1, spec_k=2), ("mixed_decode",)),
    ("tp1_spec0", dict(tp=1, spec_k=0), ("mixed_decode",)),
    ("tp1_q", dict(tp=1, kv_quant="int8"), ("inject_q",)),
    ("tp2", dict(tp=2),
     ("prefill", "prefill_chunk", "decode", "inject", "mixed")),
    ("tp2_spec", dict(tp=2, spec_k=2), ("mixed_decode",)),
]


def program_keys(variant: str, name: str, ps) -> List[Tuple[str, Optional[int]]]:
    """Budget keys (and their bucket arg) for one program under one
    variant: bucketed programs fan out per prefill bucket, mixed_decode
    is keyed by its K."""
    if name in _BUCKETED:
        return [(f"{variant}/{name}/b{b}", b)
                for b in ps.cfg.prefill_buckets]
    if name == "mixed_decode":
        return [(f"{variant}/{name}/k{ps.spec_k or 0}", None)]
    return [(f"{variant}/{name}", None)]


def extract_program(fn, args, donate_argnums, norm=None) -> dict:
    """Lower + compile one program and extract its entry.

    keep_unused=True is load-bearing: jit's default prunes unused args
    and renumbers HLO parameters, which would break the donated-arg ->
    parameter-index mapping the alias check depends on.  Cost metrics
    are unaffected (the kept params are inputs, not compute)."""
    jitted = jax.jit(fn, donate_argnums=donate_argnums, keep_unused=True)
    compiled = jitted.lower(*args).compile()
    return extract.compiled_report(
        compiled, args=args, donate_argnums=donate_argnums, norm=norm)


def collect(only: Optional[str] = None,
            defs_override=None) -> Dict[str, dict]:
    """The full {program key: metrics entry} map.  `only` substring-
    filters program keys (fast dev/test iteration); `defs_override`
    swaps the program_defs table builder (the seeded-mutation test's
    hook)."""
    out: Dict[str, dict] = {}
    for variant, ps_kwargs, names in VARIANTS:
        ps = None  # built lazily: an `only` filter skips whole variants
        for name, key, bucket in _variant_programs(
                variant, ps_kwargs, names, only):
            if ps is None:
                ps = signatures.build_program_set(**ps_kwargs)
                if defs_override is not None:
                    ps.defs = defs_override(
                        ps.mc, ps.cfg, ps.mesh, spec_k=ps.spec_k)
            if name not in ps.defs:
                logger.warning("oracle: %s has no %s program; skipped",
                               variant, name)
                continue
            fn, donate = ps.defs[name]
            args, norm = signatures.args_for(ps, name, bucket=bucket)
            logger.info("oracle: compiling %s", key)
            out[key] = extract_program(fn, args, donate, norm=norm)
    return out


def _variant_programs(variant: str, ps_kwargs: dict, names, only):
    """(name, key, bucket) triples for one variant, pre-filtered by
    `only` WITHOUT building the program set (key shapes depend only on
    the config, so a filtered run skips whole variants for free)."""
    cfg = signatures.tiny_engine_config(
        **{k: v for k, v in ps_kwargs.items() if k != "spec_k"})
    spec_k = ps_kwargs.get("spec_k")

    class _KeyShim:
        pass

    shim = _KeyShim()
    shim.cfg = cfg
    shim.spec_k = spec_k
    if names is None:
        names = _default_program_names(cfg, spec_k)
    for name in names:
        for key, bucket in program_keys(variant, name, shim):
            if only and only not in key:
                continue
            yield name, key, bucket


def _default_program_names(cfg, spec_k) -> Tuple[str, ...]:
    """The program names program_defs builds for this config, WITHOUT
    tracing anything: mirrors the defs-table gating in compiled.py
    (kept trivially in sync by test_hlo_oracle's key-coverage test)."""
    names = [
        "prefill", "prefill_lp", "prefill_chunk",
        "sample_first", "sample_first_lp",
        "decode", "decode_lp", "decode_penalized", "decode_penalized_lp",
        "inject", "inject_q",
    ]
    if cfg.pp == 1:
        names.append("mixed")
        if spec_k is not None:
            names.append("mixed_decode")
    if cfg.kv_quant != "int8":
        # inject_q's signature needs the quantized cache; the tp1_q
        # variant budgets it, every other variant skips it
        names.remove("inject_q")
    return tuple(names)


def environment_stamp() -> dict:
    import jaxlib

    return {
        "schema_version": SCHEMA_VERSION,
        "jax": jax.__version__,
        "jaxlib": getattr(jaxlib, "__version__", "unknown"),
        "backend": jax.default_backend(),
    }
