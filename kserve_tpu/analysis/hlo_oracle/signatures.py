"""Canonical program signatures: the tiny-model engine state plus the
exact per-program dispatch arguments the serving loop builds.

Each `args_for` branch mirrors one engine.py dispatch site (the arg
order, dtypes, page-table bucketing, and device commitment of
_dispatch_chunk / _step_mixed / _dispatch_dense / the inject paths), so
what the oracle lowers is signature-identical to what the engine
dispatches under the same config.  The model is LlamaConfig.tiny on the
tests' engine config (tests/test_engine.py:make_engine) — budgets track
RATIOS and structure, which the tiny model preserves, not absolute
chip-seconds.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...engine.compiled import program_defs
from ...engine.kvcache import KVCacheConfig, init_kv_pages, init_kv_scales
from ...engine.sampling import SamplingState
from ...engine.types import EngineConfig
from ...models import llama
from ...parallel import sharding as shd

#: prefill rows per batched dispatch (the engine pads the admission
#: batch to a power of two; 4 is the tiny config's max_batch_size)
PREFILL_ROWS = 4

#: pages per inject dispatch before page_bucket padding (a mid-size
#: P/D / tier-store payload)
INJECT_PAGES = 4


def tiny_model_config():
    return llama.LlamaConfig.tiny(dtype="float32")


def tiny_engine_config(**overrides) -> EngineConfig:
    base = dict(
        max_batch_size=4,
        page_size=8,
        num_pages=64,
        max_pages_per_seq=8,
        max_prefill_len=32,
        prefill_buckets=(16, 32),
        tp=1,
        dtype="float32",
        use_pallas=False,
    )
    base.update(overrides)
    return EngineConfig(**base)


@dataclass
class ProgramSet:
    """One (model, engine config, mesh) worth of compiled-program
    definitions plus the sharded state their dispatches close over."""

    mc: object
    cfg: EngineConfig
    mesh: object
    params: dict
    kv_pages: list
    defs: dict  # name -> (python fn, donate_argnums)
    spec_k: Optional[int] = None


def build_program_set(tp: int = 1, spec_k: Optional[int] = None,
                      **cfg_overrides) -> ProgramSet:
    """Engine-equivalent state without an engine: mesh, sharded params,
    sharded kv cache, and the program_defs table — everything needed to
    lower every program exactly as LLMEngine._build_compiled would."""
    mc = tiny_model_config()
    cfg = tiny_engine_config(tp=tp, **cfg_overrides)
    mesh = shd.create_mesh(tp=cfg.tp, dp=1, sp=cfg.sp, pp=cfg.pp)
    params = llama.init_params(mc, jax.random.PRNGKey(1))
    params = shd.shard_params(params, mc, mesh)
    cache_cfg = KVCacheConfig(
        n_layers=mc.n_layers,
        n_kv_heads=mc.n_kv_heads,
        head_dim=mc.head_dim,
        page_size=cfg.page_size,
        num_pages=cfg.num_pages,
        max_pages_per_seq=cfg.max_pages_per_seq,
        dtype=cfg.dtype,
    )
    if cfg.kv_quant == "int8":
        pages = shd.shard_kv_pages(
            init_kv_pages(dataclasses.replace(cache_cfg, dtype="int8")),
            mesh)
        scale_sharding = shd.named_canonical(
            mesh,
            jax.sharding.PartitionSpec(None, None, shd.MODEL_AXIS, None))
        scales = init_kv_scales(cache_cfg, scale_sharding)
        kv_pages = list(zip(pages, scales))
    else:
        kv_pages = shd.shard_kv_pages(init_kv_pages(cache_cfg), mesh)
    defs = program_defs(mc, cfg, mesh, spec_k=spec_k)
    return ProgramSet(mc=mc, cfg=cfg, mesh=mesh, params=params,
                      kv_pages=kv_pages, defs=defs, spec_k=spec_k)


def _kv_payload_shapes(ps: ProgramSet, n_pages: int):
    mc, cfg = ps.mc, ps.cfg
    return (mc.n_layers, n_pages, 2, mc.n_kv_heads, cfg.page_size,
            mc.head_dim)


def args_for(ps: ProgramSet, name: str,
             bucket: Optional[int] = None) -> Tuple[tuple, dict]:
    """(dispatch args, norm metadata) for one program.  `bucket` selects
    the prefill length bucket for the bucketed programs (defaults to the
    largest)."""
    mc, cfg = ps.mc, ps.cfg
    B = cfg.max_batch_size
    V = mc.vocab_size
    Bp = PREFILL_ROWS
    bucket = bucket or cfg.prefill_buckets[-1]
    width = cfg.page_bucket(cfg.max_pages_per_seq)
    rng = jax.random.PRNGKey(0)
    steps = cfg.steps_per_sync

    def i32(*shape, fill=0):
        return jnp.full(shape, fill, jnp.int32)

    if name in ("prefill", "prefill_lp"):
        args = (
            ps.params,
            i32(Bp, bucket),
            i32(Bp),
            ps.kv_pages,
            i32(Bp, cfg.max_pages_per_seq),
            SamplingState.defaults(Bp),
            rng,
            i32(Bp, fill=-1),
        )
        return args, {"batch": Bp, "tokens": Bp * bucket, "steps": 1}
    if name == "prefill_chunk":
        args = (
            ps.params,
            i32(Bp, bucket),
            i32(Bp),
            i32(Bp),
            ps.kv_pages,
            i32(Bp, cfg.max_pages_per_seq),
            i32(Bp, fill=-1),
        )
        return args, {"batch": Bp, "tokens": Bp * bucket, "steps": 1}
    if name in ("sample_first", "sample_first_lp"):
        args = (
            jnp.zeros((Bp, V), jnp.float32),
            SamplingState.defaults(Bp),
            rng,
            jnp.zeros((Bp, V), bool),
        )
        return args, {"batch": Bp, "tokens": Bp, "steps": 1}
    if name in ("decode", "decode_lp", "decode_penalized",
                "decode_penalized_lp"):
        args = (
            ps.params,
            i32(B),
            i32(B),
            ps.kv_pages,
            i32(B, width),
            jnp.ones((B,), bool),
            i32(B, fill=cfg.max_pages_per_seq * cfg.page_size),
            i32(B),
            SamplingState.defaults(B),
            rng,
            i32(B, fill=-1),
        )
        if name.startswith("decode_penalized"):
            args = args + (jnp.zeros((B, V), bool), i32(B, V))
        return args, {"batch": B, "tokens": B * steps, "steps": steps}
    if name == "inject":
        nb = cfg.page_bucket(INJECT_PAGES)
        args = (
            ps.kv_pages,
            jnp.zeros(_kv_payload_shapes(ps, nb), jnp.dtype(cfg.dtype)),
            i32(nb),
        )
        return args, {"pages": nb, "steps": 1}
    if name == "inject_q":
        nb = cfg.page_bucket(INJECT_PAGES)
        args = (
            ps.kv_pages,
            jnp.zeros(_kv_payload_shapes(ps, nb), jnp.int8),
            jnp.zeros(_kv_payload_shapes(ps, nb)[:-1], jnp.float32),
            i32(nb),
        )
        return args, {"pages": nb, "steps": 1}
    if name == "mixed":
        # _plan_ragged: packed buffer sized to the largest prefill
        # bucket (align=1 on the XLA reference path)
        T = cfg.prefill_buckets[-1]
        args = (
            ps.params,
            i32(T),              # q_tokens
            i32(T, fill=-1),     # token_seq
            i32(T),              # token_pos
            i32(B),              # q_start
            i32(B),              # q_len
            i32(B),              # kv_start
            i32(B),              # last_idx
            ps.kv_pages,
            i32(B, width),       # page_table
            jnp.ones((B,), bool),  # joins
            i32(B, fill=-1),     # scan_tok0
            i32(B),              # scan_pos0
            i32(B),              # step0_emits
            i32(B, fill=cfg.max_pages_per_seq * cfg.page_size),  # capacity
            i32(B),              # counters
            SamplingState.defaults(B),
            rng,
            i32(B, fill=-1),     # adapters
        )
        return args, {"batch": B, "tokens": T + (steps - 1) * B,
                      "steps": steps}
    if name == "mixed_decode":
        k = ps.spec_k or 0
        # _dispatch_dense commits the chained carries to the replicated
        # spelling and the draft table to draft_table_pspec — committed
        # inputs are part of the jit signature, so the oracle must match
        rep = shd.named(ps.mesh, jax.sharding.PartitionSpec())
        table_s = shd.named(ps.mesh, shd.draft_table_pspec())
        table_cols = V if k > 0 else 1
        args = (
            ps.params,
            jax.device_put(i32(B), rep),   # tokens (device carry)
            jax.device_put(i32(B), rep),   # pos
            ps.kv_pages,
            i32(B, width),                 # page_table
            jnp.ones((B,), bool),          # live
            i32(B, fill=cfg.max_pages_per_seq * cfg.page_size),  # capacity
            jax.device_put(i32(B), rep),   # counters
            jax.device_put(i32(B, table_cols, fill=-1), table_s),
            SamplingState.defaults(B),
            rng,
            i32(B, fill=-1),               # adapters
        )
        return args, {"batch": B, "tokens": B * (k + 1) * steps,
                      "steps": steps, "k": k}
    raise KeyError(f"no signature for program {name!r}")
