"""CLI: ``python -m kserve_tpu.analysis.hlo_oracle check|update|diff``.

check   compile the canonical program set, compare against the committed
        perf_budgets.json; exit 1 with a per-program delta report on any
        budget violation.  Degrades to a SKIP (exit 0, warning printed)
        when jax is unavailable, the backend differs from the baseline's,
        or this jax reports no cost_analysis fields — the gate must
        never block on backend drift.
update  re-collect and overwrite perf_budgets.json (commit the result).
diff    print the full delta table without gating.

The jax environment is pinned BEFORE jax imports — CPU backend, 8
virtual devices, the shared persistent compilation cache — so the CLI,
the test suite, and the AOT seam all hit the same compile cache and the
oracle re-run cost is milliseconds per warm program.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

_log = logging.getLogger(__name__)


def _pin_jax_env() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    if "--xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")


def _init_jax() -> bool:
    try:
        import jax
    except Exception as exc:  # jax-less envs skip, not fail
        _log.debug("jax import failed", exc_info=True)
        print(f"hlo_oracle: SKIP — jax unavailable ({exc})")
        return False
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("KSERVE_TPU_COMPILE_CACHE",
                           "/tmp/kserve-tpu-compile-cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # older jax without these knobs: just slower
        _log.debug("compile-cache config knobs unavailable", exc_info=True)
    return True


def _print_report(cmp, verbose: bool) -> None:
    if verbose or not cmp.ok:
        for line in cmp.deltas:
            print(f"  {line}")
    for w in cmp.warnings:
        print(f"hlo_oracle: WARNING {w}")
    for v in cmp.violations:
        print(f"hlo_oracle: VIOLATION {v}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kserve_tpu.analysis.hlo_oracle",
        description="HLO perf oracle: per-program FLOP/byte, "
        "donation-alias, and collective budgets",
    )
    parser.add_argument("command", choices=("check", "update", "diff"))
    parser.add_argument(
        "--budgets", default=None,
        help="baseline path (default: repo-root perf_budgets.json)")
    parser.add_argument(
        "--only", default=None,
        help="substring filter on program keys (fast partial runs; "
        "check compares only the matching baseline entries)")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="print the full delta table even when clean")
    args = parser.parse_args(argv)

    _pin_jax_env()
    if not _init_jax():
        return 0

    from . import budgets, oracle

    path = args.budgets or budgets.DEFAULT_BUDGETS_PATH
    stamp = oracle.environment_stamp()

    if args.command == "update":
        programs = oracle.collect(only=args.only)
        if args.only:
            # partial update: merge into the existing baseline so an
            # `--only` iteration never drops the other budgets
            doc = budgets.load_budgets(path)
            merged = dict(doc.get("programs", {})) if doc else {}
            merged.update(programs)
            programs = merged
        budgets.write_budgets(programs, stamp, path=path)
        print(f"hlo_oracle: wrote {len(programs)} program budgets to "
              f"{path} (jax {stamp['jax']}, backend {stamp['backend']})")
        return 0

    baseline = budgets.load_budgets(path)
    if baseline is None:
        print(f"hlo_oracle: no baseline at {path} — run "
              "`python -m kserve_tpu.analysis.hlo_oracle update` and "
              "commit it")
        return 1
    if baseline.get("schema_version") != oracle.SCHEMA_VERSION:
        print(
            f"hlo_oracle: baseline schema_version="
            f"{baseline.get('schema_version')} != {oracle.SCHEMA_VERSION} "
            "— run update and commit the regenerated perf_budgets.json")
        return 1
    if baseline.get("backend") != stamp["backend"]:
        print(
            f"hlo_oracle: SKIP — baseline was built on backend="
            f"{baseline.get('backend')!r}, this env is "
            f"{stamp['backend']!r}; budgets only compare like-for-like")
        return 0
    if baseline.get("jax") != stamp["jax"]:
        print(
            f"hlo_oracle: note — baseline jax {baseline.get('jax')} vs "
            f"installed {stamp['jax']}; version-drift deltas within "
            "tolerance are absorbed, run update to re-stamp")

    programs = oracle.collect(only=args.only)
    if not any("flops" in entry for entry in programs.values()):
        print(
            "hlo_oracle: SKIP — this jax reports no cost_analysis "
            "fields; FLOP/byte budgets cannot be checked here "
            f"(jax {stamp['jax']}, backend {stamp['backend']})")
        return 0
    cmp = budgets.compare(baseline, programs, only=args.only)

    if args.command == "diff":
        _print_report(cmp, verbose=True)
        print(f"hlo_oracle: {len(cmp.violations)} violation(s), "
              f"{len(cmp.warnings)} warning(s) across "
              f"{len(programs)} program(s)")
        return 0

    _print_report(cmp, verbose=args.verbose)
    if cmp.ok:
        print(f"hlo_oracle: clean — {len(programs)} program(s) within "
              "budget")
        return 0
    print(f"hlo_oracle: {len(cmp.violations)} budget violation(s)")
    return 1


if __name__ == "__main__":
    sys.exit(main())
