"""Extraction: one compiled jax program -> a normalized metrics entry.

Everything in here reads ONLY the compiled artifact (cost_analysis /
memory_analysis / optimized HLO text) plus the dispatch args' pytree
structure — no engine imports, so the AOTProgram compile seam can call
it without a circular dependency.

Field availability varies across jax/jaxlib versions and backends:
every accessor degrades to None/empty rather than raising, and the CLI
turns an all-None collection into a skip-with-warning (the gate must
never block on backend drift, ISSUE satellite 6).
"""

from __future__ import annotations

import logging
import re
from typing import Dict, List, Optional, Tuple

_log = logging.getLogger(__name__)

#: bytes per element for the HLO shape spellings that appear in engine
#: programs (unknown dtypes fall back to 4 — collective byte volumes are
#: budget anchors, not allocator truth)
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

#: HLO collective op kinds (async `-start` spellings count once; their
#: `-done` halves are skipped so a collective is never double-counted)
_COLLECTIVE_KINDS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
)

#: host-transfer op kinds (structural invariant: the serving-loop
#: programs must stay device-resident; an infeed/outfeed showing up is a
#: host sync the AST host-sync rules cannot see post-lowering)
_HOST_TRANSFER_KINDS = ("infeed", "outfeed", "send", "recv")

_RNG_KINDS = ("rng", "rng-bit-generator", "rng-get-and-update-state")

#: one HLO instruction line: `[ROOT] %name = <shape> op-name(...)`
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(?P<shape>\(.*?\)|\S+)\s+"
    r"(?P<op>[a-z][a-z0-9\-]*)\("
)

#: one entry of the module header's input_output_alias map:
#: `{1}: (28, {}, may-alias)` — matched globally so nested braces in the
#: surrounding header never truncate the scan
_ALIAS_ENTRY_RE = re.compile(
    r"\{(?P<out>[\d,\s]*)\}:\s*\((?P<param>\d+),\s*\{[\d,\s]*\},\s*"
    r"(?P<kind>may-alias|must-alias)\)"
)

_SHAPE_TOKEN_RE = re.compile(r"([a-z]\w*)\[([0-9,]*)\]")


def shape_bytes(shape_str: str) -> int:
    """Total byte size of every `dtype[dims]` token in an HLO shape
    spelling (tuples sum their elements)."""
    total = 0
    for dtype, dims in _SHAPE_TOKEN_RE.findall(shape_str):
        if dtype == "token":
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def cost_metrics(compiled) -> Optional[Dict[str, float]]:
    """flops / bytes accessed / transcendentals from cost_analysis(),
    None when this jax/backend does not report them (skip-with-warning
    upstream).  Newer jax returns the dict directly, older wraps it in a
    one-element list."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        _log.debug("cost_analysis unavailable", exc_info=True)
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or "flops" not in ca:
        return None
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "transcendentals": float(ca.get("transcendentals", 0.0)),
    }


def memory_metrics(compiled) -> Optional[Dict[str, int]]:
    """Peak-memory accounting from memory_analysis(); None when the
    backend does not implement it."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        _log.debug("memory_analysis unavailable", exc_info=True)
        return None
    if ma is None:
        return None
    out = {}
    for key, attr in (
        ("argument_bytes", "argument_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("alias_bytes", "alias_size_in_bytes"),
        ("temp_bytes", "temp_size_in_bytes"),
        ("generated_code_bytes", "generated_code_size_in_bytes"),
    ):
        v = getattr(ma, attr, None)
        if v is not None:
            out[key] = int(v)
    return out or None


def hlo_text(compiled) -> Optional[str]:
    try:
        return compiled.as_text()
    except Exception:
        _log.debug("compiled.as_text unavailable", exc_info=True)
        return None


def alias_table(hlo: str) -> List[Tuple[str, int, str]]:
    """The executable's buffer-donation table parsed from the HloModule
    header: [(output_index, param_index, may|must-alias), ...].  This is
    what XLA actually honored — a donate_argnums entry the compiler
    could not alias simply has no entry here."""
    header = hlo.split("\n", 1)[0]
    if "input_output_alias=" not in header:
        return []
    return [
        (m.group("out").replace(" ", ""), int(m.group("param")),
         m.group("kind"))
        for m in _ALIAS_ENTRY_RE.finditer(header)
    ]


def _instructions(hlo: str):
    for line in hlo.splitlines():
        m = _INSTR_RE.match(line)
        if m:
            yield m.group("shape"), m.group("op")


def collective_inventory(hlo: str) -> Dict[str, Dict[str, int]]:
    """{collective kind: {count, bytes}} over the optimized module.
    Byte volume is the op's output shape size — a stable proxy for wire
    volume that moves whenever the sharded tensor or mesh factor does."""
    out: Dict[str, Dict[str, int]] = {}
    for shape, op in _instructions(hlo):
        if op.endswith("-done"):
            continue
        base = op[:-6] if op.endswith("-start") else op
        if base not in _COLLECTIVE_KINDS:
            continue
        slot = out.setdefault(base, {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += shape_bytes(shape)
    return out


def op_counts(hlo: str) -> Dict[str, int]:
    """Structural-invariant op tallies: host transfers must stay absent
    from serving-loop programs, rng/convert growth flags a numerics or
    sampling change riding an unrelated diff."""
    rng = convert = host = 0
    for _, op in _instructions(hlo):
        if op.endswith("-done"):
            continue
        base = op[:-6] if op.endswith("-start") else op
        if base in _RNG_KINDS:
            rng += 1
        elif base == "convert":
            convert += 1
        elif base in _HOST_TRANSFER_KINDS:
            host += 1
    return {"rng": rng, "convert": convert, "host_transfer": host}


def donation_report(args: Tuple, donate_argnums: Tuple[int, ...],
                    hlo: str) -> Dict[str, Dict[str, int]]:
    """Per donated arg: how many of its flattened leaves the executable
    actually aliased.  Leaf->HLO-parameter mapping assumes the jit kept
    every argument (the oracle lowers with keep_unused=True so flattened
    leaf ranges match HLO parameter numbers exactly); aliased < leaves
    is the dropped-donation signal the budget check fails on."""
    import jax

    aliased_params = {param for _, param, _ in alias_table(hlo)}
    start = 0
    ranges = {}
    for i, arg in enumerate(args):
        n = len(jax.tree_util.tree_leaves(arg))
        ranges[i] = (start, start + n)
        start += n
    out = {}
    for i in donate_argnums:
        if i not in ranges:
            continue
        lo, hi = ranges[i]
        out[str(i)] = {
            "leaves": hi - lo,
            "aliased": sum(1 for p in range(lo, hi) if p in aliased_params),
        }
    return out


def compiled_report(compiled, *, args: Optional[Tuple] = None,
                    donate_argnums: Tuple[int, ...] = (),
                    norm: Optional[dict] = None) -> dict:
    """Assemble one program's full budget entry from its compiled
    artifact.  `args` (the dispatch args the program was lowered from)
    enables the donation check; `norm` carries workload normalization
    (tokens/steps per dispatch) so sim costs can be derived from the
    entry (StubCosts.from_oracle)."""
    entry: dict = {}
    cost = cost_metrics(compiled)
    if cost is not None:
        entry.update(cost)
    mem = memory_metrics(compiled)
    if mem is not None:
        entry["memory"] = mem
    hlo = hlo_text(compiled)
    if hlo is not None:
        entry["collectives"] = collective_inventory(hlo)
        entry["ops"] = op_counts(hlo)
        if args is not None and donate_argnums:
            entry["donation"] = donation_report(args, donate_argnums, hlo)
    if norm:
        entry["norm"] = dict(norm)
    return entry
