"""HLO perf oracle: artifact-level static analysis of the engine's
compiled device programs (docs/static_analysis.md, "HLO oracle").

The 11 jaxlint rules audit SOURCE for JAX-serving hazards; this package
audits the ARTIFACTS XLA actually produced.  For every program
`engine/compiled.py:program_defs` builds (mixed, mixed_decode across K,
inject/inject_q, per-bucket prefill/prefill_chunk, the legacy set —
under tp=1 and a tp=2 CPU mesh) it lowers and compiles the canonical
tiny-model signature on CPU and extracts:

- FLOP / bytes-accessed / peak-memory accounting
  (``compiled.cost_analysis()`` + ``memory_analysis()``);
- the donation-alias table from the executable's input_output_alias
  header, verifying every arg the program table marks donated is
  ACTUALLY aliased (a silently dropped donation is a 2x HBM copy the
  AST lint cannot see);
- a collective inventory (op kind, count, byte volume) pinning the
  expected tp communication pattern;
- structural invariants (host transfers, rng/convert op counts).

Costs normalize into the committed baseline ``perf_budgets.json``;
``python -m kserve_tpu.analysis.hlo_oracle check|update|diff`` compares
against it, and tier-1 (tests/test_hlo_oracle.py) plus scripts/lint.sh
fail on >10% FLOP/byte growth, any lost alias, or any new collective.
"""

from .budgets import compare, load_budgets, write_budgets  # noqa: F401
from .extract import compiled_report  # noqa: F401


def collect(*args, **kwargs):
    """Lazy alias for oracle.collect: importing this package must not
    import jax (the CLI pins the jax environment BEFORE jax loads, and
    jaxlint consumers stay jax-free)."""
    from .oracle import collect as _collect

    return _collect(*args, **kwargs)
