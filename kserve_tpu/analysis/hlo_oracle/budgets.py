"""Baseline IO + the budget comparison the CI gate enforces.

The committed baseline (repo-root ``perf_budgets.json``) pins, per
program: FLOPs, bytes accessed, the honored donation-alias counts, the
collective inventory, and the structural op tallies.  `compare` turns
(baseline, current) into violations — the hard failures — plus a full
delta table for the human reading the CI log.

Violation semantics (ISSUE 18 acceptance):
- flops / bytes_accessed growth beyond the tolerance (default +10%);
- any donated arg whose aliased-leaf count dropped (vs baseline, AND vs
  its own leaf count when the baseline had full coverage) — the
  silently-dropped-donation 2x-HBM-copy hazard;
- any NEW collective kind, or a count/byte increase in an existing one;
- host transfers appearing, rng count growth, convert count growth
  beyond tolerance (+2 absolute slack: tiny counts make percentages
  meaningless);
- a program present now but missing from the baseline (run `update` —
  new programs must be budgeted deliberately, in the PR that adds
  them).  A baseline program missing from the current build is a
  warning, not a violation: `only`-filtered runs and config-gated
  programs must not fail the gate.

Shrinking costs never fail: `update` re-baselines wins.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

#: default relative growth tolerance for flops / bytes_accessed / convert
DEFAULT_TOLERANCE = 0.10

#: repo-root baseline, resolved relative to this package so the CLI and
#: tests agree regardless of cwd
DEFAULT_BUDGETS_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))),
    "perf_budgets.json",
)


@dataclass
class Comparison:
    violations: List[str] = field(default_factory=list)
    warnings: List[str] = field(default_factory=list)
    deltas: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


def load_budgets(path: str = DEFAULT_BUDGETS_PATH) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def write_budgets(programs: Dict[str, dict], stamp: dict,
                  path: str = DEFAULT_BUDGETS_PATH,
                  tolerance: float = DEFAULT_TOLERANCE) -> dict:
    doc = dict(stamp)
    doc["tolerance"] = tolerance
    doc["programs"] = {k: programs[k] for k in sorted(programs)}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return doc


def _pct(base: float, cur: float) -> float:
    if base == 0:
        return float("inf") if cur > 0 else 0.0
    return (cur - base) / base * 100.0


def _check_scalar(cmp: Comparison, key: str, metric: str, base: float,
                  cur: float, tol: float) -> None:
    pct = _pct(base, cur)
    cmp.deltas.append(
        f"{key:<28s} {metric:<14s} {base:>12.4g} -> {cur:>12.4g} "
        f"({pct:+.1f}%)")
    if cur > base * (1.0 + tol):
        cmp.violations.append(
            f"{key}: {metric} grew {pct:+.1f}% "
            f"({base:.4g} -> {cur:.4g}), tolerance is +{tol * 100:.0f}%")


def _check_donation(cmp: Comparison, key: str, base: dict,
                    cur: dict) -> None:
    for arg, b in base.items():
        c = cur.get(arg)
        if c is None:
            cmp.violations.append(
                f"{key}: donated arg {arg} is no longer donated "
                f"(baseline aliased {b.get('aliased', 0)}/"
                f"{b.get('leaves', 0)} leaves)")
            continue
        b_aliased = int(b.get("aliased", 0))
        c_aliased = int(c.get("aliased", 0))
        if c_aliased < b_aliased:
            cmp.violations.append(
                f"{key}: donation alias dropped on arg {arg} — "
                f"{c_aliased}/{c.get('leaves', 0)} leaves aliased "
                f"(baseline {b_aliased}/{b.get('leaves', 0)}): each lost "
                "alias is a full extra buffer copy per dispatch")
    for arg, c in cur.items():
        # a donated arg the executable does not fully alias is suspect
        # even without baseline drift — flag when the intent says all
        # leaves should alias and none historically failed to
        if arg in base:
            continue
        if int(c.get("aliased", 0)) < int(c.get("leaves", 0)):
            cmp.warnings.append(
                f"{key}: new donated arg {arg} only aliases "
                f"{c.get('aliased', 0)}/{c.get('leaves', 0)} leaves")


def _check_collectives(cmp: Comparison, key: str, base: dict,
                       cur: dict) -> None:
    for kind, c in cur.items():
        b = base.get(kind)
        if b is None:
            cmp.violations.append(
                f"{key}: NEW collective {kind} (count={c.get('count')}, "
                f"bytes={c.get('bytes')}) not in baseline — the tp "
                "communication pattern changed")
            continue
        if int(c.get("count", 0)) > int(b.get("count", 0)):
            cmp.violations.append(
                f"{key}: collective {kind} count grew "
                f"{b.get('count')} -> {c.get('count')}")
        elif int(c.get("bytes", 0)) > int(b.get("bytes", 0)):
            cmp.violations.append(
                f"{key}: collective {kind} byte volume grew "
                f"{b.get('bytes')} -> {c.get('bytes')}")
    for kind in base:
        if kind not in cur:
            cmp.warnings.append(
                f"{key}: collective {kind} disappeared (baseline had "
                f"{base[kind].get('count')}) — run update to re-baseline "
                "the win")


def _check_ops(cmp: Comparison, key: str, base: dict, cur: dict,
               tol: float) -> None:
    b_host = int(base.get("host_transfer", 0))
    c_host = int(cur.get("host_transfer", 0))
    if c_host > b_host:
        cmp.violations.append(
            f"{key}: host-transfer ops appeared ({b_host} -> {c_host}) — "
            "a serving-loop program must stay device-resident")
    b_rng = int(base.get("rng", 0))
    c_rng = int(cur.get("rng", 0))
    if c_rng > b_rng:
        cmp.violations.append(
            f"{key}: rng op count grew {b_rng} -> {c_rng}")
    b_cv = int(base.get("convert", 0))
    c_cv = int(cur.get("convert", 0))
    if c_cv > int(b_cv * (1.0 + tol)) + 2:
        cmp.violations.append(
            f"{key}: convert op count grew {b_cv} -> {c_cv} "
            f"(beyond +{tol * 100:.0f}% +2) — a dtype wobble is riding "
            "this change")


def compare(baseline: dict, current: Dict[str, dict],
            only: Optional[str] = None) -> Comparison:
    """Compare a collected {program key: entry} map against the loaded
    baseline document.  `only` restricts the comparison domain the same
    way it restricted collection, so a filtered check never reports the
    unfiltered programs as missing."""
    cmp = Comparison()
    tol = float(baseline.get("tolerance", DEFAULT_TOLERANCE))
    base_programs = baseline.get("programs", {})
    if only:
        base_programs = {k: v for k, v in base_programs.items()
                         if only in k}
    for key in sorted(set(base_programs) | set(current)):
        b, c = base_programs.get(key), current.get(key)
        if c is None:
            cmp.warnings.append(
                f"{key}: in baseline but not in this build (config-gated "
                "or filtered); run update if it was removed on purpose")
            continue
        if b is None:
            cmp.violations.append(
                f"{key}: not in baseline — new programs must be budgeted "
                "deliberately (run `python -m kserve_tpu.analysis."
                "hlo_oracle update` and commit perf_budgets.json)")
            continue
        for metric in ("flops", "bytes_accessed"):
            if metric in b and metric in c:
                _check_scalar(cmp, key, metric, float(b[metric]),
                              float(c[metric]), tol)
        _check_donation(cmp, key, b.get("donation", {}),
                        c.get("donation", {}))
        _check_collectives(cmp, key, b.get("collectives", {}),
                           c.get("collectives", {}))
        _check_ops(cmp, key, b.get("ops", {}), c.get("ops", {}), tol)
    return cmp
