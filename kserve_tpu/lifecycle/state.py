"""Replica lifecycle state machine: STARTING -> READY -> DRAINING ->
TERMINATING.

One object per serving process, owned by the ModelServer and consulted by
the REST admission middleware, the readiness probe, and the EPP state
endpoint.  The contract (docs/lifecycle.md):

- READY        accepting traffic; readiness green.
- DRAINING     SIGTERM (or POST /admin/drain) arrived: readiness goes red
               so the endpoint controller stops routing here, liveness
               stays green so kubelet does not kill the drain, admission
               rejects NEW inference with 503 + Retry-After, and in-flight
               requests get the drain budget to finish.
- TERMINATING  the budget expired (leftover generations were checkpointed)
               or a second signal escalated; the process is exiting.

Transitions are forward-only and idempotent — a second drain request
returns the budget already running rather than restarting it, and a
second SIGTERM escalates by EXPIRING that budget (`escalate()`), which
every drain loop polls, so escalation cuts a drain short deterministically
under the injected clock.
"""

from __future__ import annotations

import math
import os
from typing import Callable, Optional

from ..metrics import DRAIN_DURATION, set_lifecycle_state
from ..resilience import MONOTONIC, Clock, Deadline

STARTING = "STARTING"
READY = "READY"
DRAINING = "DRAINING"
TERMINATING = "TERMINATING"
STATES = (STARTING, READY, DRAINING, TERMINATING)

# env knob for the drain budget (seconds an in-flight generation may keep
# decoding after SIGTERM before it is checkpointed); the LLMISVC reconciler
# sets it alongside the pod's terminationGracePeriodSeconds so kubelet never
# SIGKILLs a drain that is still inside its budget
DRAIN_GRACE_ENV = "KSERVE_TPU_DRAIN_GRACE"
DEFAULT_DRAIN_GRACE_S = 30.0


def normalize_drain_grace(value) -> Optional[float]:
    """Parse one candidate drain-grace value (env string, k8s env entry);
    None when it must not be used.  Shared by the runtime and the LLMISVC
    reconciler so the synthesized terminationGracePeriodSeconds can never
    drift from the budget the runtime actually grants.  float() accepts
    'inf'/'nan' without raising, but a non-finite or negative budget is a
    Deadline that never expires: in-flight generations would never be
    checkpointed and kubelet SIGKILLs them at the grace period."""
    try:
        grace = float(value)
    except (TypeError, ValueError):
        return None
    if not math.isfinite(grace) or grace < 0:
        return None
    return grace


def drain_grace_from_env(env=None) -> float:
    env = os.environ if env is None else env
    grace = normalize_drain_grace(env.get(DRAIN_GRACE_ENV, DEFAULT_DRAIN_GRACE_S))
    return DEFAULT_DRAIN_GRACE_S if grace is None else grace


class ReplicaDrainingError(RuntimeError):
    """New work refused because this replica is draining/terminating.
    Maps to 503 + Retry-After at the protocol layer — the client's retry
    (or the EPP) re-seats the request on a healthy replica."""

    def __init__(self, detail: str = "replica is draining; retry another replica",
                 retry_after_s: float = 1.0):
        super().__init__(detail)
        self.retry_after_s = retry_after_s


class ReplicaLifecycle:
    """The replica's lifecycle state + the drain budget, clock-injectable
    so chaos tests drive drains on a FakeClock with zero real sleeps."""

    def __init__(
        self,
        clock: Clock = MONOTONIC,
        drain_grace_s: Optional[float] = None,
        on_transition: Optional[Callable[[str], None]] = None,
    ):
        self.clock = clock
        self.drain_grace_s = (
            drain_grace_from_env() if drain_grace_s is None else float(drain_grace_s)
        )
        self.on_transition = on_transition
        self._state = STARTING
        self._drain_deadline: Optional[Deadline] = None
        self._drain_started: Optional[float] = None
        set_lifecycle_state(STARTING)

    # ---------------- observation ----------------

    @property
    def state(self) -> str:
        return self._state

    @property
    def ready(self) -> bool:
        """Readiness-probe view: red unless fully READY (a DRAINING replica
        must drop out of the endpoint set while its liveness stays green)."""
        return self._state == READY

    @property
    def accepting(self) -> bool:
        """Admission view: new inference is rejected once draining begins.
        STARTING still admits — model readiness gates that phase already."""
        return self._state in (STARTING, READY)

    @property
    def drain_deadline(self) -> Optional[Deadline]:
        """The running drain budget (None before a drain starts)."""
        return self._drain_deadline

    # ---------------- transitions (forward-only) ----------------

    def _to(self, state: str) -> None:
        if STATES.index(state) <= STATES.index(self._state):
            return  # forward-only, idempotent
        self._state = state
        set_lifecycle_state(state)
        if self.on_transition is not None:
            self.on_transition(state)

    def mark_ready(self) -> None:
        self._to(READY)

    def begin_drain(self, grace_s: Optional[float] = None) -> Deadline:
        """Flip to DRAINING and start the drain budget; idempotent (a
        concurrent SIGTERM and /admin/drain share one budget).  Returns the
        budget Deadline every engine drain loop should honor."""
        if self._drain_deadline is not None:
            self._to(DRAINING)
            return self._drain_deadline
        grace = self.drain_grace_s if grace_s is None else float(grace_s)
        self._drain_started = self.clock.now()
        self._drain_deadline = Deadline.after(grace, self.clock)
        self._to(DRAINING)
        return self._drain_deadline

    def escalate(self) -> None:
        """Second SIGTERM: expire the drain budget (every drain loop polls
        it, so in-flight generations checkpoint on their next iteration)
        and jump to TERMINATING."""
        if self._drain_deadline is not None:
            self._drain_deadline.expires_at = self.clock.now()
        else:
            self._drain_deadline = Deadline.after(0.0, self.clock)
        self._to(TERMINATING)

    def finish_drain(self) -> None:
        """Drain complete (all in-flight finished or checkpointed): record
        the drain duration and settle into TERMINATING."""
        if self._drain_started is not None:
            DRAIN_DURATION.observe(max(self.clock.now() - self._drain_started, 0.0))
            self._drain_started = None
        self._to(TERMINATING)
