"""Replica lifecycle layer: graceful drain + preemption-safe resumable
generation (docs/lifecycle.md).

- `ReplicaLifecycle`: the STARTING -> READY -> DRAINING -> TERMINATING
  state machine every serving process owns (state.py).
- `GenerationCheckpoint` / `GenerationPreempted`: the portable snapshot a
  draining engine hands each live request so a healthy replica resumes it
  with zero lost or duplicated tokens (checkpoint.py).
- `lifecycle_middleware` / `register_admin_routes`: the REST-layer
  admission gate, readiness override, and `POST /admin/drain` preStop
  entrypoint (middleware.py).
"""

from .checkpoint import (  # noqa: F401
    CHECKPOINT_FIELD_SIZE_LIMIT,
    CHECKPOINT_HEADER,
    CHECKPOINT_HEADER_MAX_BYTES,
    CHECKPOINT_HEADER_SAFE_BYTES,
    GenerationCheckpoint,
    GenerationPreempted,
)
from .middleware import lifecycle_middleware, register_admin_routes  # noqa: F401
from .state import (  # noqa: F401
    DEFAULT_DRAIN_GRACE_S,
    DRAIN_GRACE_ENV,
    DRAINING,
    READY,
    STARTING,
    STATES,
    TERMINATING,
    ReplicaDrainingError,
    ReplicaLifecycle,
    drain_grace_from_env,
    normalize_drain_grace,
)
