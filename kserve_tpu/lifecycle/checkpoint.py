"""Portable generation checkpoints: the unit of preemption-safe resume.

When a drain budget expires (or KV-pressure preemption would otherwise
kill a live sequence during a drain), the engine snapshots each affected
request into a `GenerationCheckpoint` — prompt token ids, every token
decoded so far, the sampling params (including the seed, so seeded lanes
stay reproducible), the LoRA adapter, and the remaining request deadline.
The checkpoint travels to the caller as a `GenerationPreempted` exception
through the stream queue; the protocol layer serializes it into the
`x-generation-checkpoint` response header/body, and a healthy replica
resumes it with `engine.resume_generation(checkpoint)` — a prefill of
prompt+generated (cheap under the prefix cache) after which decoding
continues at the next token: zero tokens lost, zero duplicated.

This module stays import-light (no jax) so the EPP/scheduler side can
parse checkpoints without pulling the engine stack.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import operator
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

CHECKPOINT_HEADER = "x-generation-checkpoint"
# the header form grows with prompt+generated length (~8 b64 bytes/token);
# servers raise their header-field limit to accept up to this much, and
# clients drop a larger checkpoint from the retry (restarting from the
# prompt beats a retry the server rejects with 400 before any handler)
CHECKPOINT_HEADER_MAX_BYTES = 1 << 20
# the aiohttp max_field_size/max_line_size every hop that carries the
# checkpoint header must use (replica REST server, EPP proxy client and
# server): one constant so the limits cannot drift out of lockstep —
# a request that fits one hop must fit them all
CHECKPOINT_FIELD_SIZE_LIMIT = CHECKPOINT_HEADER_MAX_BYTES + 8190
# RESPONSE headers cross parsers we don't control (httpx/h11 refuses
# header lines around ~100 KiB; stock aiohttp clients stop at 8190 bytes
# per header FIELD — the tightest limit in the fleet): above this size the
# 503 carries the checkpoint in its JSON body only, and clients fall back
# to reading it from there.  Sized under aiohttp's 8190 with margin for
# the header name + separator so a stock client never sees LineTooLong.
CHECKPOINT_HEADER_SAFE_BYTES = 8000


@dataclass
class GenerationCheckpoint:
    request_id: str
    prompt_ids: List[int]
    generated: List[int] = field(default_factory=list)
    # dataclasses.asdict(SamplingParams) — plain JSON types only
    sampling: Dict[str, Any] = field(default_factory=dict)
    adapter: Optional[str] = None
    model_name: Optional[str] = None
    # remaining request-deadline budget at snapshot time (None = unbounded);
    # relative seconds, same contract as the x-request-deadline header
    deadline_remaining_s: Optional[float] = None
    # drain (lifecycle drain) | preempt (KV pressure) | stall (watchdog
    # self-drain) | hedge (client-side stall-triggered migration)
    reason: str = "drain"

    @classmethod
    def capture(
        cls,
        request_id: str,
        prompt_ids: List[int],
        generated: List[int],
        params,  # engine.sampling.SamplingParams
        adapter: Optional[str] = None,
        model_name: Optional[str] = None,
        deadline=None,  # resilience.Deadline
        reason: str = "drain",
    ) -> "GenerationCheckpoint":
        return cls(
            request_id=request_id,
            prompt_ids=[int(t) for t in prompt_ids],
            generated=[int(t) for t in generated],
            sampling=dataclasses.asdict(params),
            adapter=adapter,
            model_name=model_name,
            deadline_remaining_s=(
                max(deadline.remaining(), 0.0) if deadline is not None else None
            ),
            reason=reason,
        )

    # engine.sampling.SamplingParams wire schema (hardcoded: this module
    # must not import jax via sampling.py; tests/test_lifecycle.py pins it
    # against dataclasses.fields(SamplingParams) so drift fails loudly)
    _SAMPLING_FLOATS = ("temperature", "top_p", "min_p", "repetition_penalty",
                        "frequency_penalty", "presence_penalty")
    _SAMPLING_INTS = ("top_k", "max_tokens", "min_tokens")
    _SAMPLING_OPT_INTS = ("seed", "logprobs")

    def validate(self, vocab_size: Optional[int] = None) -> "GenerationCheckpoint":
        """Normalize and bounds-check a wire-sourced checkpoint before it
        is admitted into an engine.  Checkpoints arrive in client-supplied
        headers, so every field is untrusted: a non-integer or out-of-vocab
        token id, or a non-numeric sampling value, must raise ValueError to
        THIS caller — admitted raw, it would crash the shared run loop and
        kill every other in-flight generation on the replica.  Mutates the
        checkpoint in place (ids coerced to int, unknown sampling keys
        dropped for rollout forward-compatibility) and returns self."""
        self.prompt_ids = self._int_ids("prompt_ids", self.prompt_ids, vocab_size)
        if not self.prompt_ids:
            raise ValueError("invalid checkpoint: empty prompt_ids")
        self.generated = self._int_ids("generated", self.generated, vocab_size)
        if not isinstance(self.sampling, dict):
            raise ValueError("invalid checkpoint: sampling must be an object")
        sampling: Dict[str, Any] = {}
        try:
            for key in self._SAMPLING_FLOATS:
                if key in self.sampling:
                    sampling[key] = float(self.sampling[key])
            for key in self._SAMPLING_INTS:
                if key in self.sampling:
                    sampling[key] = self._bounded_int(key, self.sampling[key])
            for key in self._SAMPLING_OPT_INTS:
                value = self.sampling.get(key)
                if key in self.sampling:
                    sampling[key] = (
                        None if value is None else self._bounded_int(key, value)
                    )
        except (TypeError, ValueError) as exc:
            raise ValueError(f"invalid checkpoint: bad sampling value ({exc})") from exc
        if "ignore_eos" in self.sampling:
            sampling["ignore_eos"] = bool(self.sampling["ignore_eos"])
        stop = self.sampling.get("stop")
        if stop is not None:
            if not isinstance(stop, list) or any(not isinstance(s, str) for s in stop):
                raise ValueError("invalid checkpoint: stop must be a list of strings")
            sampling["stop"] = stop
        elif "stop" in self.sampling:
            sampling["stop"] = None
        # anything else is silently dropped: a newer replica's checkpoint
        # resuming here mid-rollout must not fail on fields it added
        self.sampling = sampling
        return self

    @staticmethod
    def _bounded_int(field_name: str, value) -> int:
        """Coerce an untrusted sampling int and bound it to int32 — these
        values reach jnp.asarray(..., jnp.int32) inside the shared run
        loop, where an out-of-range Python int raises OverflowError and
        kills every in-flight generation on the replica."""
        out = operator.index(value)
        if not -(2 ** 31) <= out < 2 ** 31:
            raise ValueError(f"{field_name} {out} outside int32 range")
        return out

    @staticmethod
    def _int_ids(field_name: str, values, vocab_size: Optional[int]) -> List[int]:
        try:
            ids = [operator.index(t) for t in values]
        except TypeError as exc:
            raise ValueError(
                f"invalid checkpoint: {field_name} must be integer token ids"
            ) from exc
        if vocab_size is not None:
            for t in ids:
                if not 0 <= t < vocab_size:
                    raise ValueError(
                        f"invalid checkpoint: {field_name} id {t} outside "
                        f"vocab [0, {vocab_size})"
                    )
        return ids

    @property
    def tokens_salvaged(self) -> int:
        return len(self.generated)

    def sampling_params(self):
        """Rebuild the engine SamplingParams (lazy import: this module must
        not pull jax into scheduler-side consumers)."""
        from ..engine.sampling import SamplingParams

        return SamplingParams(**self.sampling)

    # ---------------- wire forms ----------------

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, data: dict) -> "GenerationCheckpoint":
        if not isinstance(data, dict):
            raise ValueError(f"checkpoint must be an object, got {type(data).__name__}")
        known = {f.name for f in dataclasses.fields(cls)}
        # tolerate unknown keys so a newer replica's checkpoint resumes on
        # an older one during a rollout (forward compatibility)
        return cls(**{k: v for k, v in data.items() if k in known})

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), separators=(",", ":"))

    @classmethod
    def from_json(cls, raw: str) -> "GenerationCheckpoint":
        return cls.from_dict(json.loads(raw))

    def to_header(self) -> str:
        """Base64 wire form for the x-generation-checkpoint header (token
        id lists are header-hostile as raw JSON)."""
        return base64.b64encode(self.to_json().encode("utf-8")).decode("ascii")

    @classmethod
    def from_header(cls, value: Optional[str]) -> Optional["GenerationCheckpoint"]:
        """Parse the header form; malformed values return None — a
        checkpoint is a resume optimization, not an input schema."""
        if not value:
            return None
        try:
            return cls.from_json(base64.b64decode(value).decode("utf-8"))
        except (ValueError, TypeError, KeyError):
            return None


class GenerationPreempted(Exception):
    """Raised into a generation stream when this replica checkpointed it
    (drain budget expired / escalated shutdown / KV-pressure kill).  The
    protocol layer maps it to 503 + checkpoint header/body; clients (or
    the EPP) re-seat the checkpoint on a healthy replica."""

    def __init__(self, checkpoint: GenerationCheckpoint, reason: Optional[str] = None):
        self.checkpoint = checkpoint
        self.reason = reason or checkpoint.reason
        super().__init__(
            f"generation {checkpoint.request_id} preempted ({self.reason}); "
            f"{checkpoint.tokens_salvaged} decoded tokens checkpointed for resume"
        )
