"""aiohttp integration for the replica lifecycle.

`lifecycle_middleware` sits directly inside error mapping on the REST
server:

- readiness (`/v2/health/ready`) answers 503 the moment the replica
  leaves READY, so the endpoint controller/EPP stops routing here —
  while liveness keeps answering 200 (kubelet must not kill a drain);
- new inference POSTs are refused 503 + `Retry-After` once draining
  begins (same path predicate as load shedding: admin/observability
  routes always pass — an operator must be able to watch a drain).

`register_admin_routes` adds `POST /admin/drain`, the preStop-hook /
operator entrypoint that starts a drain without a signal.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from aiohttp import web

from ..logging import logger
from ..resilience.shedding import is_inference_path
from .state import READY, ReplicaLifecycle

READINESS_PATHS = ("/v2/health/ready",)


def lifecycle_middleware(lifecycle: ReplicaLifecycle):
    @web.middleware
    async def middleware(request: web.Request, handler):
        if request.path in READINESS_PATHS and not lifecycle.ready:
            return web.json_response(
                {"ready": lifecycle.state == READY, "lifecycle": lifecycle.state},
                status=503,
            )
        if (
            request.method == "POST"
            and is_inference_path(request.path)
            and not lifecycle.accepting
        ):
            return web.json_response(
                {
                    "error": "replica is draining; retry another replica",
                    "lifecycle": lifecycle.state,
                },
                status=503,
                headers={"Retry-After": "1"},
            )
        return await handler(request)

    return middleware


def register_admin_routes(
    app: web.Application,
    lifecycle: ReplicaLifecycle,
    on_drain: Optional[Callable] = None,
) -> None:
    """POST /admin/drain: flip to DRAINING (idempotent) and kick the async
    drain callback; responds immediately with the state + remaining budget
    so a preStop hook returns fast while the drain proceeds."""
    # strong reference to the running drain task: a bare create_task result
    # is weakly held by the loop and the drain could be GC'd unrun
    drain_tasks: list = []

    async def drain_handler(request: web.Request) -> web.Response:
        first = lifecycle.drain_deadline is None
        deadline = lifecycle.begin_drain()
        if on_drain is not None and first:
            drain_tasks.append(
                asyncio.get_running_loop().create_task(_run_drain(on_drain))
            )
        return web.json_response({
            "lifecycle": lifecycle.state,
            "drain_remaining_s": max(deadline.remaining(), 0.0),
        })

    async def drain_get_handler(request: web.Request) -> web.Response:
        # kubelet lifecycle httpGet handlers issue GET — a POST-only route
        # would 405 the synthesized preStop hook (controlplane
        # ensure_drain_lifecycle) and the drain-before-SIGTERM window
        # would silently never exist.  But the state machine is forward-
        # only, so a BARE GET (scanner, browser prefetch, misaimed probe)
        # must not retire a healthy replica: only the ?source=prestop
        # marker the control plane synthesizes mutates; anything else
        # reads the drain status
        if request.query.get("source") == "prestop":
            return await drain_handler(request)
        deadline = lifecycle.drain_deadline
        return web.json_response({
            "lifecycle": lifecycle.state,
            "drain_remaining_s": (
                max(deadline.remaining(), 0.0) if deadline is not None else None
            ),
            "hint": "GET is read-only; drain via POST or GET ?source=prestop",
        })

    app.router.add_post("/admin/drain", drain_handler)
    app.router.add_get("/admin/drain", drain_get_handler)


async def _run_drain(on_drain: Callable) -> None:
    try:
        await on_drain()
    except Exception:  # noqa: BLE001 — a failed drain must be loud, not lost
        logger.exception("graceful drain failed")
