"""Protocol-agnostic inference request/response tensor types.

`InferInput` / `RequestedOutput` / `InferRequest` / `InferResponse` are the
single in-memory representation that every protocol head (V1 JSON, V2 JSON,
V2 binary-tensor extension, gRPC OIP) encodes to and decodes from.

Parity: reference python/kserve/kserve/infer_type.py (1.6k LoC); rebuilt
around a numpy-first core so model `predict()` gets contiguous arrays that
feed `jax.device_put` without copies.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .errors import InvalidInput
from .utils.numpy_codec import (
    deserialize_bytes_tensor,
    from_np_dtype,
    serialize_byte_tensor,
    to_np_dtype,
)

Parameters = Dict[str, Union[str, bool, int, float]]

# datatype -> InferTensorContents field name (gRPC typed contents)
_CONTENTS_FIELD = {
    "BOOL": "bool_contents",
    "INT8": "int_contents",
    "INT16": "int_contents",
    "INT32": "int_contents",
    "INT64": "int64_contents",
    "UINT8": "uint_contents",
    "UINT16": "uint_contents",
    "UINT32": "uint_contents",
    "UINT64": "uint64_contents",
    "FP32": "fp32_contents",
    "FP64": "fp64_contents",
    "BYTES": "bytes_contents",
    # FP16/BF16 have no typed contents field in the protocol; raw bytes only.
}


def _grpc_pb():
    # Deferred import so pure-REST users never pay for protobuf.
    from .protocol.grpc import open_inference_pb2 as pb

    return pb


def _param_to_pb(value, pb):
    p = pb.InferParameter()
    if isinstance(value, bool):
        p.bool_param = value
    elif isinstance(value, int):
        p.int64_param = value
    elif isinstance(value, float):
        p.double_param = value
    else:
        p.string_param = str(value)
    return p


def _param_from_pb(p) -> Union[str, bool, int, float]:
    which = p.WhichOneof("parameter_choice")
    return getattr(p, which) if which else ""


def _params_to_pb_map(params: Optional[Parameters], pb_map, pb) -> None:
    for k, v in (params or {}).items():
        pb_map[k].CopyFrom(_param_to_pb(v, pb))


def _params_from_pb_map(pb_map) -> Parameters:
    return {k: _param_from_pb(v) for k, v in pb_map.items()}


def _flatten_data(datatype: str, array: np.ndarray) -> list:
    flat = array.flatten()
    if datatype == "BYTES":
        out = []
        for el in flat:
            if isinstance(el, bytes):
                try:
                    out.append(el.decode("utf-8"))
                except UnicodeDecodeError:
                    out.append(list(el))
            else:
                out.append(str(el))
        return out
    return flat.tolist()


class InferInput:
    """One named input tensor."""

    def __init__(
        self,
        name: str,
        shape: List[int],
        datatype: str,
        data: Union[List, np.ndarray, None] = None,
        parameters: Optional[Parameters] = None,
    ):
        self._name = name
        self._shape = list(shape)
        self._datatype = datatype.upper()
        self._parameters = dict(parameters or {})
        self._data: Optional[list] = None
        self._raw_data: Optional[bytes] = None
        if isinstance(data, np.ndarray):
            self.set_data_from_numpy(data, binary_data=False)
        elif data is not None:
            self._data = data

    @property
    def name(self) -> str:
        return self._name

    @property
    def shape(self) -> List[int]:
        return self._shape

    @shape.setter
    def shape(self, shape: List[int]):
        self._shape = list(shape)

    @property
    def datatype(self) -> str:
        return self._datatype

    @property
    def parameters(self) -> Parameters:
        return self._parameters

    @parameters.setter
    def parameters(self, params: Parameters):
        self._parameters = dict(params or {})

    @property
    def data(self) -> Optional[list]:
        return self._data

    @data.setter
    def data(self, data: list):
        self._data = data

    @property
    def raw_data(self) -> Optional[bytes]:
        return self._raw_data

    def set_data_from_numpy(self, input_tensor: np.ndarray, binary_data: bool = True) -> None:
        """Attach tensor data; `binary_data` selects the V2 binary extension
        wire form (raw bytes + binary_data_size parameter) over inline JSON."""
        if not isinstance(input_tensor, np.ndarray):
            raise InvalidInput("input tensor must be a numpy array")
        dtype = from_np_dtype(input_tensor.dtype)
        if dtype is None:
            raise InvalidInput(f"unsupported numpy dtype {input_tensor.dtype}")
        self._datatype = dtype
        self._shape = list(input_tensor.shape)
        if binary_data:
            self._data = None
            if dtype == "BYTES":
                self._raw_data = serialize_byte_tensor(input_tensor)
            else:
                self._raw_data = np.ascontiguousarray(input_tensor).tobytes()
            self._parameters["binary_data_size"] = len(self._raw_data)
        else:
            self._raw_data = None
            self._parameters.pop("binary_data_size", None)
            self._data = _flatten_data(dtype, input_tensor)

    def as_numpy(self) -> np.ndarray:
        """Materialize as numpy in the declared shape."""
        dtype = to_np_dtype(self._datatype)
        if dtype is None:
            raise InvalidInput(f"invalid datatype {self._datatype} in input {self._name}")
        if self._raw_data is not None:
            if self._datatype == "BYTES":
                arr = deserialize_bytes_tensor(self._raw_data)
            else:
                arr = np.frombuffer(self._raw_data, dtype=dtype)
            return arr.reshape(self._shape)
        if self._data is None:
            raise InvalidInput(f"input {self._name} has no data")
        if self._datatype == "BYTES":
            encoded = [
                el.encode("utf-8") if isinstance(el, str) else (bytes(el) if isinstance(el, list) else el)
                for el in _iter_flat(self._data)
            ]
            return np.array(encoded, dtype=object).reshape(self._shape)
        return np.asarray(self._data, dtype=dtype).reshape(self._shape)

    def as_string(self) -> List[str]:
        if self._datatype != "BYTES":
            raise InvalidInput(f"input {self._name} datatype is {self._datatype}, not BYTES")
        arr = self.as_numpy().flatten()
        return [el.decode("utf-8") if isinstance(el, bytes) else str(el) for el in arr]

    def to_dict(self) -> dict:
        d: Dict[str, Any] = {
            "name": self._name,
            "shape": self._shape,
            "datatype": self._datatype,
        }
        if self._parameters:
            d["parameters"] = self._parameters
        if self._raw_data is None:
            d["data"] = self._data
        return d

    def __eq__(self, other) -> bool:
        if not isinstance(other, InferInput):
            return NotImplemented
        if (self._name, self._datatype) != (other._name, other._datatype):
            return False
        if list(self._shape) != list(other._shape):
            return False
        try:
            return np.array_equal(self.as_numpy(), other.as_numpy())
        except InvalidInput:
            return self._data == other._data and self._raw_data == other._raw_data

    def __repr__(self) -> str:
        return (
            f"InferInput(name={self._name!r}, shape={self._shape}, "
            f"datatype={self._datatype!r})"
        )


def _iter_flat(data):
    if isinstance(data, (list, tuple)):
        for el in data:
            yield from _iter_flat(el)
    else:
        yield data


class RequestedOutput:
    """Client request for a specific named output (V2 `outputs` entry)."""

    def __init__(self, name: str, parameters: Optional[Parameters] = None):
        self.name = name
        self.parameters = dict(parameters or {})

    @property
    def binary_data(self) -> Optional[bool]:
        v = self.parameters.get("binary_data")
        return bool(v) if v is not None else None

    def to_dict(self) -> dict:
        d: Dict[str, Any] = {"name": self.name}
        if self.parameters:
            d["parameters"] = self.parameters
        return d

    def __eq__(self, other) -> bool:
        if not isinstance(other, RequestedOutput):
            return NotImplemented
        return self.name == other.name and self.parameters == other.parameters

    def __repr__(self) -> str:
        return f"RequestedOutput(name={self.name!r})"


class InferRequest:
    """Protocol-agnostic inference request."""

    def __init__(
        self,
        model_name: str,
        infer_inputs: List[InferInput],
        request_id: Optional[str] = None,
        raw_inputs: Optional[List[bytes]] = None,
        from_grpc: bool = False,
        parameters: Optional[Parameters] = None,
        request_outputs: Optional[List[RequestedOutput]] = None,
        model_version: Optional[str] = None,
    ):
        self.model_name = model_name
        self.model_version = model_version
        self.id = request_id or str(uuid.uuid4())
        self.inputs = infer_inputs
        self.parameters = dict(parameters or {})
        self.from_grpc = from_grpc
        self.request_outputs = request_outputs
        if raw_inputs:
            if len(raw_inputs) != len(infer_inputs):
                raise InvalidInput("raw_input_contents count does not match inputs count")
            for i, raw in enumerate(raw_inputs):
                infer_inputs[i]._raw_data = raw

    # ---------- constructors ----------

    @classmethod
    def from_dict(cls, body: dict, model_name: Optional[str] = None) -> "InferRequest":
        """Build from a V2 JSON body (already parsed)."""
        if "inputs" not in body or not isinstance(body["inputs"], list):
            raise InvalidInput("missing 'inputs' in v2 inference request")
        inputs = []
        for entry in body["inputs"]:
            try:
                inputs.append(
                    InferInput(
                        name=entry["name"],
                        shape=entry["shape"],
                        datatype=entry["datatype"],
                        data=entry.get("data"),
                        parameters=entry.get("parameters"),
                    )
                )
            except KeyError as e:
                raise InvalidInput(f"input tensor missing required field {e}")
        outputs = None
        if body.get("outputs"):
            outputs = [
                RequestedOutput(name=o["name"], parameters=o.get("parameters"))
                for o in body["outputs"]
            ]
        return cls(
            model_name=model_name or body.get("model_name", ""),
            request_id=body.get("id"),
            infer_inputs=inputs,
            parameters=body.get("parameters"),
            request_outputs=outputs,
        )

    @classmethod
    def from_bytes(cls, req_bytes: bytes, json_length: int, model_name: str) -> "InferRequest":
        """Build from a V2 REST body carrying the binary tensor extension:
        `json_length` bytes of JSON header followed by raw tensor data."""
        import json

        if json_length > len(req_bytes):
            raise InvalidInput("Inference-Header-Content-Length exceeds body size")
        try:
            header = json.loads(req_bytes[:json_length])
        except json.JSONDecodeError as e:
            raise InvalidInput(f"unrecognized request format: {e}")
        req = cls.from_dict(header, model_name=model_name)
        offset = json_length
        blob = req_bytes
        for inp in req.inputs:
            size = inp.parameters.get("binary_data_size") if inp.parameters else None
            if size is None:
                continue
            size = int(size)
            if offset + size > len(blob):
                raise InvalidInput(f"binary data for input {inp.name} is truncated")
            inp._raw_data = blob[offset : offset + size]
            inp._data = None
            offset += size
        return req

    @classmethod
    def from_grpc(cls, request) -> "InferRequest":
        """Build from a pb ModelInferRequest."""
        inputs = []
        for t in request.inputs:
            data = None
            if t.HasField("contents"):
                field = _CONTENTS_FIELD.get(t.datatype)
                if field:
                    vals = list(getattr(t.contents, field))
                    if vals:
                        data = vals
            inputs.append(
                InferInput(
                    name=t.name,
                    shape=list(t.shape),
                    datatype=t.datatype,
                    data=data,
                    parameters=_params_from_pb_map(t.parameters),
                )
            )
        outputs = None
        if request.outputs:
            outputs = [
                RequestedOutput(name=o.name, parameters=_params_from_pb_map(o.parameters))
                for o in request.outputs
            ]
        return cls(
            model_name=request.model_name,
            model_version=request.model_version or None,
            request_id=request.id or None,
            infer_inputs=inputs,
            raw_inputs=list(request.raw_input_contents) or None,
            from_grpc=True,
            parameters=_params_from_pb_map(request.parameters),
            request_outputs=outputs,
        )

    # ---------- encoders ----------

    def to_rest(self) -> Tuple[Union[bytes, dict], Optional[int]]:
        """Encode to (body, json_length). json_length is None for pure-JSON
        bodies; set when any input uses the binary extension."""
        import json

        infer_inputs = []
        raw_parts: List[bytes] = []
        for inp in self.inputs:
            d = inp.to_dict()
            if inp.raw_data is not None:
                d.pop("data", None)
                d.setdefault("parameters", inp.parameters)
                raw_parts.append(inp.raw_data)
            else:
                if inp.datatype == "FP16":
                    raise InvalidInput(
                        f"FP16 input {inp.name} must use binary_data (no JSON form)"
                    )
            infer_inputs.append(d)
        body: Dict[str, Any] = {"id": self.id, "inputs": infer_inputs}
        if self.model_name:
            body["model_name"] = self.model_name
        if self.parameters:
            body["parameters"] = self.parameters
        if self.request_outputs:
            body["outputs"] = [o.to_dict() for o in self.request_outputs]
        if raw_parts:
            header = json.dumps(body).encode("utf-8")
            return header + b"".join(raw_parts), len(header)
        return body, None

    def to_grpc(self):
        """Encode to pb ModelInferRequest (tensor data in raw_input_contents)."""
        pb = _grpc_pb()
        req = pb.ModelInferRequest(
            model_name=self.model_name,
            model_version=self.model_version or "",
            id=self.id or "",
        )
        _params_to_pb_map(self.parameters, req.parameters, pb)
        use_raw = any(i.raw_data is not None for i in self.inputs)
        for inp in self.inputs:
            t = req.inputs.add()
            t.name = inp.name
            t.datatype = inp.datatype
            t.shape.extend(inp.shape)
            params = {k: v for k, v in inp.parameters.items() if k != "binary_data_size"}
            _params_to_pb_map(params, t.parameters, pb)
            if use_raw:
                if inp.raw_data is not None:
                    req.raw_input_contents.append(inp.raw_data)
                else:
                    arr = inp.as_numpy()
                    if inp.datatype == "BYTES":
                        req.raw_input_contents.append(serialize_byte_tensor(arr))
                    else:
                        req.raw_input_contents.append(np.ascontiguousarray(arr).tobytes())
            else:
                field = _CONTENTS_FIELD.get(inp.datatype)
                if field is None:
                    arr = inp.as_numpy()
                    req.raw_input_contents.append(np.ascontiguousarray(arr).tobytes())
                elif inp.datatype == "BYTES":
                    arr = inp.as_numpy().flatten()
                    t.contents.bytes_contents.extend(
                        el if isinstance(el, bytes) else str(el).encode("utf-8") for el in arr
                    )
                else:
                    getattr(t.contents, field).extend(_iter_flat(inp.data))
        for out in self.request_outputs or []:
            o = req.outputs.add()
            o.name = out.name
            _params_to_pb_map(out.parameters, o.parameters, pb)
        return req

    def as_dataframe(self):
        """Columns from named inputs (pandas optional)."""
        import pandas as pd

        cols = {}
        for inp in self.inputs:
            arr = inp.as_numpy()
            if inp.datatype == "BYTES":
                arr = np.array(
                    [el.decode("utf-8") if isinstance(el, bytes) else el for el in arr.flatten()]
                ).reshape(arr.shape)
            cols[inp.name] = arr.flatten() if arr.ndim <= 1 else list(arr)
        return pd.DataFrame(cols)

    def get_input_by_name(self, name: str) -> Optional[InferInput]:
        for inp in self.inputs:
            if inp.name == name:
                return inp
        return None

    def __eq__(self, other) -> bool:
        if not isinstance(other, InferRequest):
            return NotImplemented
        return (
            self.model_name == other.model_name
            and self.id == other.id
            and self.inputs == other.inputs
            and self.parameters == other.parameters
        )

    def __repr__(self) -> str:
        return f"InferRequest(model_name={self.model_name!r}, id={self.id!r}, inputs={self.inputs})"


class InferOutput(InferInput):
    """A named output tensor; wire-identical to InferInput."""


class InferResponse:
    """Protocol-agnostic inference response."""

    def __init__(
        self,
        response_id: str,
        model_name: str,
        infer_outputs: List[InferOutput],
        model_version: Optional[str] = None,
        raw_outputs: Optional[List[bytes]] = None,
        from_grpc: bool = False,
        parameters: Optional[Parameters] = None,
    ):
        self.id = response_id
        self.model_name = model_name
        self.model_version = model_version
        self.outputs = infer_outputs
        self.parameters = dict(parameters or {})
        self.from_grpc = from_grpc
        if raw_outputs:
            for i, raw in enumerate(raw_outputs):
                infer_outputs[i]._raw_data = raw

    @classmethod
    def from_dict(cls, body: dict) -> "InferResponse":
        outputs = [
            InferOutput(
                name=o["name"],
                shape=o["shape"],
                datatype=o["datatype"],
                data=o.get("data"),
                parameters=o.get("parameters"),
            )
            for o in body.get("outputs", [])
        ]
        return cls(
            response_id=body.get("id", ""),
            model_name=body.get("model_name", ""),
            model_version=body.get("model_version"),
            infer_outputs=outputs,
            parameters=body.get("parameters"),
        )

    @classmethod
    def from_bytes(cls, res_bytes: bytes, json_length: int) -> "InferResponse":
        import json

        header = json.loads(res_bytes[:json_length])
        res = cls.from_dict(header)
        offset = json_length
        for out in res.outputs:
            size = out.parameters.get("binary_data_size") if out.parameters else None
            if size is None:
                continue
            size = int(size)
            out._raw_data = res_bytes[offset : offset + size]
            out._data = None
            offset += size
        return res

    @classmethod
    def from_grpc(cls, response) -> "InferResponse":
        outputs = []
        for t in response.outputs:
            data = None
            if t.HasField("contents"):
                field = _CONTENTS_FIELD.get(t.datatype)
                if field:
                    vals = list(getattr(t.contents, field))
                    if vals:
                        data = vals
            outputs.append(
                InferOutput(
                    name=t.name,
                    shape=list(t.shape),
                    datatype=t.datatype,
                    data=data,
                    parameters=_params_from_pb_map(t.parameters),
                )
            )
        return cls(
            response_id=response.id,
            model_name=response.model_name,
            model_version=response.model_version or None,
            infer_outputs=outputs,
            raw_outputs=list(response.raw_output_contents) or None,
            from_grpc=True,
            parameters=_params_from_pb_map(response.parameters),
        )

    def to_rest(self, requested_outputs: Optional[List[RequestedOutput]] = None):
        """Encode to (body, json_length). Outputs marked binary_data (via the
        requested outputs or their own raw form) use the binary extension."""
        import json

        binary_names = set()
        drop_binary = set()
        for ro in requested_outputs or []:
            if ro.binary_data:
                binary_names.add(ro.name)
            elif ro.binary_data is False:
                drop_binary.add(ro.name)
        entries = []
        raw_parts: List[bytes] = []
        for out in self.outputs:
            wants_binary = out.name in binary_names or (
                out.raw_data is not None and out.name not in drop_binary
            )
            d = out.to_dict()
            if wants_binary:
                if out.raw_data is None:
                    arr = out.as_numpy()
                    if out.datatype == "BYTES":
                        raw = serialize_byte_tensor(arr)
                    else:
                        raw = np.ascontiguousarray(arr).tobytes()
                    out._raw_data = raw
                d.pop("data", None)
                params = dict(d.get("parameters") or {})
                params["binary_data_size"] = len(out.raw_data)
                d["parameters"] = params
                raw_parts.append(out.raw_data)
            else:
                if out.raw_data is not None:
                    arr = out.as_numpy()
                    d["data"] = _flatten_data(out.datatype, arr)
                d.get("parameters", {}).pop("binary_data_size", None)
                if "parameters" in d and not d["parameters"]:
                    del d["parameters"]
            entries.append(d)
        body: Dict[str, Any] = {
            "id": self.id,
            "model_name": self.model_name,
            "outputs": entries,
        }
        if self.model_version:
            body["model_version"] = self.model_version
        if self.parameters:
            body["parameters"] = self.parameters
        if raw_parts:
            header = json.dumps(body).encode("utf-8")
            return header + b"".join(raw_parts), len(header)
        return body, None

    def to_grpc(self):
        pb = _grpc_pb()
        res = pb.ModelInferResponse(
            model_name=self.model_name,
            model_version=self.model_version or "",
            id=self.id or "",
        )
        _params_to_pb_map(self.parameters, res.parameters, pb)
        use_raw = any(o.raw_data is not None for o in self.outputs)
        for out in self.outputs:
            t = res.outputs.add()
            t.name = out.name
            t.datatype = out.datatype
            t.shape.extend(out.shape)
            params = {k: v for k, v in out.parameters.items() if k != "binary_data_size"}
            _params_to_pb_map(params, t.parameters, pb)
            if use_raw:
                if out.raw_data is not None:
                    res.raw_output_contents.append(out.raw_data)
                else:
                    arr = out.as_numpy()
                    if out.datatype == "BYTES":
                        res.raw_output_contents.append(serialize_byte_tensor(arr))
                    else:
                        res.raw_output_contents.append(np.ascontiguousarray(arr).tobytes())
            else:
                field = _CONTENTS_FIELD.get(out.datatype)
                if field is None:
                    arr = out.as_numpy()
                    res.raw_output_contents.append(np.ascontiguousarray(arr).tobytes())
                elif out.datatype == "BYTES":
                    arr = out.as_numpy().flatten()
                    t.contents.bytes_contents.extend(
                        el if isinstance(el, bytes) else str(el).encode("utf-8") for el in arr
                    )
                else:
                    getattr(t.contents, field).extend(_iter_flat(out.data))
        return res

    def get_output_by_name(self, name: str) -> Optional[InferOutput]:
        for out in self.outputs:
            if out.name == name:
                return out
        return None

    def __eq__(self, other) -> bool:
        if not isinstance(other, InferResponse):
            return NotImplemented
        return (
            self.model_name == other.model_name
            and self.id == other.id
            and self.outputs == other.outputs
        )

    def __repr__(self) -> str:
        return (
            f"InferResponse(model_name={self.model_name!r}, id={self.id!r}, "
            f"outputs={self.outputs})"
        )
