"""InferenceGraph router: a standalone HTTP service executing a graph spec.

Node semantics (parity: cmd/router/main.go — graphHandler :405, weighted
pick :179, condition eval :195, ensemble fan-out :218, step exec :385):
- Sequence: steps run in order; `data: $request` re-sends the original
  request, `$response` pipes the previous step's output; a step may name
  another graph node (`nodeName`) instead of a service.
- Splitter: one step chosen by weight.
- Ensemble: all steps fan out concurrently; responses merged keyed by step
  name/index.
- Switch: first step whose `condition` matches the request payload runs.
Conditions use a dotted-path==value syntax evaluated against the JSON body
(the reference uses gjson path conditions).

Usage: python -m kserve_tpu.graph.router --graph-json '<spec>' --port 8080
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
from typing import Any, Dict, Optional
from urllib.parse import urlsplit

import httpx
from aiohttp import web

from ..logging import bind_log_context, configure_logging, logger
from ..metrics import RETRY_ATTEMPTS, record_breaker_transition
from ..tracing import TraceContext, propagate_headers, trace_scope
from ..resilience import (
    DEADLINE_HEADER,
    MONOTONIC,
    BreakerRegistry,
    Clock,
    Deadline,
    RetryPolicy,
    parse_retry_after,
)

DEFAULT_TIMEOUT = 60.0


class GraphExecutionError(Exception):
    def __init__(self, message: str, status: int = 500):
        super().__init__(message)
        self.status = status


def eval_condition(condition: str, payload: Any) -> bool:
    """`path.to.field==value` (or bare `path` for existence) against JSON."""
    if not condition:
        return True
    if "==" in condition:
        path, _, expected = condition.partition("==")
    else:
        path, expected = condition, None
    node = payload
    for part in path.strip().split("."):
        if isinstance(node, dict) and part in node:
            node = node[part]
        elif isinstance(node, list):
            try:
                node = node[int(part)]
            except (ValueError, IndexError):
                return False
        else:
            return False
    if expected is None:
        return True
    expected = expected.strip()
    if isinstance(node, bool):
        return str(node).lower() == expected.lower()
    if isinstance(node, (int, float)):
        try:
            return float(node) == float(expected)
        except ValueError:
            return False
    return str(node) == expected.strip('"')


class GraphRouter:
    def __init__(self, graph_spec: dict, timeout: float = DEFAULT_TIMEOUT,
                 retries: int = 1, client: Optional[httpx.AsyncClient] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 breakers: Optional[BreakerRegistry] = None,
                 clock: Clock = MONOTONIC):
        self.nodes: Dict[str, dict] = graph_spec["nodes"]
        self.timeout = graph_spec.get("timeout") or timeout
        self.retries = retries
        self.clock = clock
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=retries + 1, base_backoff_s=0.05, max_backoff_s=1.0,
        )
        self.breakers = breakers if breakers is not None else BreakerRegistry(
            clock=clock, on_transition=record_breaker_transition,
        )
        self._client = client or httpx.AsyncClient(timeout=self.timeout)

    async def close(self):
        await self._client.aclose()

    @staticmethod
    def _backend_key(url: str) -> str:
        parts = urlsplit(url)
        return parts.netloc or url

    def _step_url(self, step: dict) -> str:
        if step.get("serviceUrl"):
            return step["serviceUrl"]
        if step.get("serviceName"):
            # ISVC predictor service; default v1 predict path
            model = step.get("name") or step["serviceName"]
            return f"http://{step['serviceName']}/v1/models/{model}:predict"
        raise GraphExecutionError(f"step has neither serviceUrl nor serviceName: {step}")

    async def _call_step(self, step: dict, payload: Any, headers: Dict[str, str],
                         deadline: Optional[Deadline] = None) -> Any:
        """One step call under the resilience policy: per-backend circuit
        breaker, RetryPolicy backoff (Retry-After aware, deadline-capped),
        transport errors mapped to gateway statuses (timeout -> 504,
        connect -> 502) naming the step that failed."""
        if step.get("nodeName"):
            return await self.execute_node(
                step["nodeName"], payload, headers, deadline=deadline
            )
        url = self._step_url(step)
        name = step.get("name") or url
        backend = self._backend_key(url)
        soft = step.get("dependency") == "Soft"
        started = self.clock.now()
        attempt = 0
        last_exc: Optional[GraphExecutionError] = None
        while True:
            if deadline is not None and deadline.expired:
                last_exc = GraphExecutionError(
                    f"step {name}: request deadline exceeded", status=504
                )
                break
            if not self.breakers.allow(backend):
                last_exc = GraphExecutionError(
                    f"step {name}: circuit open for backend {backend}", status=503
                )
                break
            attempt += 1
            retry_after = None
            retryable = True
            try:
                send_headers = dict(headers)
                if deadline is not None:
                    send_headers[DEADLINE_HEADER] = deadline.to_header()
                # same propagation path as the EPP proxy / REST client:
                # each step call is a child hop of the graph request's trace
                propagate_headers(send_headers)
                response = await self._client.post(
                    url, json=payload, headers=send_headers
                )
                if response.status_code == 200:
                    self.breakers.record_success(backend)
                    return response.json()
                # 429 (shedding) and 5xx mark backend health; client-fault
                # 4xx would fail identically anywhere and must not trip it
                if response.status_code == 429 or response.status_code >= 500:
                    self.breakers.record_failure(backend)
                retry_after = parse_retry_after(response.headers.get("Retry-After"))
                retryable = self.retry_policy.retryable(response.status_code)
                last_exc = GraphExecutionError(
                    f"step {name} returned {response.status_code}: "
                    f"{response.text[:200]}",
                    status=response.status_code,
                )
            except (httpx.ConnectTimeout, httpx.PoolTimeout) as e:
                # pre-send timeouts: the request never reached the backend,
                # so replaying it cannot duplicate work
                self.breakers.record_failure(backend)
                last_exc = GraphExecutionError(
                    f"step {name} timed out: {e}", status=504
                )
            except httpx.TimeoutException as e:
                # read/write timeout: the backend may be EXECUTING the
                # request — replaying would duplicate (expensive) inference
                self.breakers.record_failure(backend)
                retryable = False
                last_exc = GraphExecutionError(
                    f"step {name} timed out: {e}", status=504
                )
            except httpx.ConnectError as e:
                self.breakers.record_failure(backend)
                last_exc = GraphExecutionError(
                    f"step {name} connect failed: {e}", status=502
                )
            except httpx.HTTPError as e:
                self.breakers.record_failure(backend)
                last_exc = GraphExecutionError(
                    f"step {name} call failed: {e}", status=503
                )
            if soft or not retryable:
                break
            delay = self.retry_policy.next_delay(
                attempt,
                retry_after=retry_after,
                elapsed=self.clock.now() - started,
                deadline=deadline,
            )
            if delay is None:
                break
            RETRY_ATTEMPTS.labels(component="graph").inc()
            await self.clock.sleep(delay)
        if soft:
            logger.warning("soft-dependency step failed, continuing: %s", last_exc)
            return None
        raise last_exc

    def _splitter_candidates(self, steps: list) -> list:
        """Weighted-pick candidates with open-breaker backends excluded —
        the router routes around a tripped backend instead of burning a
        pick on it.  When nothing pickable remains (all open, or only
        zero-weight steps survive the filter), fall back to the full set:
        every choice then fails fast in _call_step with an accurate,
        retryable 503 'circuit open' instead of a misleading 422."""
        viable = [
            s for s in steps
            if s.get("weight", 0) > 0
            and (s.get("nodeName")
                 # available(), not allow(): filtering must not consume the
                 # half-open probe of a step that may not even be picked
                 or self.breakers.available(self._backend_key(self._step_url(s))))
        ]
        return viable if viable else steps

    async def execute_node(self, node_name: str, payload: Any,
                           headers: Dict[str, str],
                           deadline: Optional[Deadline] = None) -> Any:
        node = self.nodes.get(node_name)
        if node is None:
            raise GraphExecutionError(f"graph node {node_name!r} not found", status=404)
        if deadline is not None and deadline.expired:
            raise GraphExecutionError(
                f"node {node_name}: request deadline exceeded", status=504
            )
        router_type = node["routerType"]
        steps = node.get("steps", [])
        if router_type == "Sequence":
            request_payload = payload
            current = payload
            for step in steps:
                data = step.get("data", "$request" if step is steps[0] else "$response")
                step_input = request_payload if data == "$request" else current
                result = await self._call_step(step, step_input, headers, deadline)
                if result is not None:
                    current = result
            return current
        if router_type == "Splitter":
            candidates = self._splitter_candidates(steps)
            total = sum(s.get("weight", 0) for s in candidates)
            if total <= 0:
                raise GraphExecutionError("splitter steps need positive weights", 422)
            pick = random.uniform(0, total)
            acc = 0.0
            chosen = candidates[-1]
            for s in candidates:
                acc += s.get("weight", 0)
                if pick <= acc:
                    chosen = s
                    break
            return await self._call_step(chosen, payload, headers, deadline)
        if router_type == "Ensemble":
            results = await asyncio.gather(
                *[self._call_step(s, payload, headers, deadline) for s in steps],
                return_exceptions=True,
            )
            merged: Dict[str, Any] = {}
            failed: list = []  # (member_key, GraphExecutionError)
            for i, (step, result) in enumerate(zip(steps, results)):
                key = step.get("name") or step.get("serviceName") or str(i)
                if isinstance(result, GraphExecutionError):
                    failed.append((key, result))
                    continue
                if isinstance(result, BaseException):
                    raise result
                merged[key] = result
            if failed:
                # hard-dependency member death fails the ensemble naming
                # WHICH member died (soft members already degraded to None)
                members = ", ".join(k for k, _ in failed)
                first = failed[0][1]
                raise GraphExecutionError(
                    f"ensemble member(s) [{members}] failed: {first}",
                    status=first.status,
                )
            return merged
        if router_type == "Switch":
            for step in steps:
                if eval_condition(step.get("condition", ""), payload):
                    return await self._call_step(step, payload, headers, deadline)
            raise GraphExecutionError("no switch branch matched the request", status=404)
        raise GraphExecutionError(f"unknown routerType {router_type!r}", status=422)

    # ---------------- http surface ----------------

    async def handle(self, request: web.Request) -> web.Response:
        try:
            payload = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        headers = {
            k: v for k, v in request.headers.items()
            if k.lower() in ("x-request-id", "authorization", "content-type")
        }
        # the deadline budget is re-anchored here and decremented per hop:
        # every outgoing step call carries the REMAINING budget
        deadline = Deadline.from_header(
            request.headers.get(DEADLINE_HEADER), clock=self.clock
        )
        # the graph request's trace context: child of the caller's
        # traceparent, or a fresh root when the router is the first hop —
        # every step call below derives its own child from this scope
        ctx = TraceContext.derive(TraceContext.from_headers(request.headers))
        with trace_scope(ctx), bind_log_context(
            request_id=request.headers.get("x-request-id", "-"),
            trace_id=ctx.trace_id,
        ):
            try:
                result = await self.execute_node(
                    "root", payload, headers, deadline)
            except GraphExecutionError as e:
                return web.json_response({"error": str(e)}, status=e.status)
            return web.json_response(result)

    def create_application(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/", self.handle)
        async def healthz(_request: web.Request) -> web.Response:
            return web.json_response({"status": "ok"})

        app.router.add_get("/healthz", healthz)
        return app


def main(argv=None):
    configure_logging()
    parser = argparse.ArgumentParser()
    parser.add_argument("--graph-json", required=True)
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--timeout", type=float, default=DEFAULT_TIMEOUT)
    args = parser.parse_args(argv)
    router = GraphRouter(json.loads(args.graph_json), timeout=args.timeout)
    web.run_app(router.create_application(), port=args.port)


if __name__ == "__main__":
    main()
