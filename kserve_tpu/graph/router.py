"""InferenceGraph router: a standalone HTTP service executing a graph spec.

Node semantics (parity: cmd/router/main.go — graphHandler :405, weighted
pick :179, condition eval :195, ensemble fan-out :218, step exec :385):
- Sequence: steps run in order; `data: $request` re-sends the original
  request, `$response` pipes the previous step's output; a step may name
  another graph node (`nodeName`) instead of a service.
- Splitter: one step chosen by weight.
- Ensemble: all steps fan out concurrently; responses merged keyed by step
  name/index.
- Switch: first step whose `condition` matches the request payload runs.
Conditions use a dotted-path==value syntax evaluated against the JSON body
(the reference uses gjson path conditions).

Usage: python -m kserve_tpu.graph.router --graph-json '<spec>' --port 8080
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
from typing import Any, Dict, Optional

import httpx
from aiohttp import web

from ..logging import configure_logging, logger

DEFAULT_TIMEOUT = 60.0


class GraphExecutionError(Exception):
    def __init__(self, message: str, status: int = 500):
        super().__init__(message)
        self.status = status


def eval_condition(condition: str, payload: Any) -> bool:
    """`path.to.field==value` (or bare `path` for existence) against JSON."""
    if not condition:
        return True
    if "==" in condition:
        path, _, expected = condition.partition("==")
    else:
        path, expected = condition, None
    node = payload
    for part in path.strip().split("."):
        if isinstance(node, dict) and part in node:
            node = node[part]
        elif isinstance(node, list):
            try:
                node = node[int(part)]
            except (ValueError, IndexError):
                return False
        else:
            return False
    if expected is None:
        return True
    expected = expected.strip()
    if isinstance(node, bool):
        return str(node).lower() == expected.lower()
    if isinstance(node, (int, float)):
        try:
            return float(node) == float(expected)
        except ValueError:
            return False
    return str(node) == expected.strip('"')


class GraphRouter:
    def __init__(self, graph_spec: dict, timeout: float = DEFAULT_TIMEOUT,
                 retries: int = 1, client: Optional[httpx.AsyncClient] = None):
        self.nodes: Dict[str, dict] = graph_spec["nodes"]
        self.timeout = graph_spec.get("timeout") or timeout
        self.retries = retries
        self._client = client or httpx.AsyncClient(timeout=self.timeout)

    async def close(self):
        await self._client.aclose()

    def _step_url(self, step: dict) -> str:
        if step.get("serviceUrl"):
            return step["serviceUrl"]
        if step.get("serviceName"):
            # ISVC predictor service; default v1 predict path
            model = step.get("name") or step["serviceName"]
            return f"http://{step['serviceName']}/v1/models/{model}:predict"
        raise GraphExecutionError(f"step has neither serviceUrl nor serviceName: {step}")

    async def _call_step(self, step: dict, payload: Any, headers: Dict[str, str]) -> Any:
        if step.get("nodeName"):
            return await self.execute_node(step["nodeName"], payload, headers)
        url = self._step_url(step)
        last_exc: Optional[Exception] = None
        for _ in range(self.retries + 1):
            try:
                response = await self._client.post(url, json=payload, headers=headers)
                if response.status_code == 200:
                    return response.json()
                last_exc = GraphExecutionError(
                    f"step {step.get('name') or url} returned {response.status_code}: "
                    f"{response.text[:200]}",
                    status=response.status_code,
                )
                if step.get("dependency") == "Soft":
                    break
            except httpx.HTTPError as e:
                last_exc = GraphExecutionError(f"step call failed: {e}", status=503)
        if step.get("dependency") == "Soft":
            logger.warning("soft-dependency step failed, continuing: %s", last_exc)
            return None
        raise last_exc

    async def execute_node(self, node_name: str, payload: Any, headers: Dict[str, str]) -> Any:
        node = self.nodes.get(node_name)
        if node is None:
            raise GraphExecutionError(f"graph node {node_name!r} not found", status=404)
        router_type = node["routerType"]
        steps = node.get("steps", [])
        if router_type == "Sequence":
            request_payload = payload
            current = payload
            for step in steps:
                data = step.get("data", "$request" if step is steps[0] else "$response")
                step_input = request_payload if data == "$request" else current
                result = await self._call_step(step, step_input, headers)
                if result is not None:
                    current = result
            return current
        if router_type == "Splitter":
            total = sum(s.get("weight", 0) for s in steps)
            if total <= 0:
                raise GraphExecutionError("splitter steps need positive weights", 422)
            pick = random.uniform(0, total)
            acc = 0.0
            chosen = steps[-1]
            for s in steps:
                acc += s.get("weight", 0)
                if pick <= acc:
                    chosen = s
                    break
            return await self._call_step(chosen, payload, headers)
        if router_type == "Ensemble":
            results = await asyncio.gather(
                *[self._call_step(s, payload, headers) for s in steps],
                return_exceptions=True,
            )
            merged: Dict[str, Any] = {}
            for i, (step, result) in enumerate(zip(steps, results)):
                key = step.get("name") or step.get("serviceName") or str(i)
                if isinstance(result, Exception):
                    raise result
                merged[key] = result
            return merged
        if router_type == "Switch":
            for step in steps:
                if eval_condition(step.get("condition", ""), payload):
                    return await self._call_step(step, payload, headers)
            raise GraphExecutionError("no switch branch matched the request", status=404)
        raise GraphExecutionError(f"unknown routerType {router_type!r}", status=422)

    # ---------------- http surface ----------------

    async def handle(self, request: web.Request) -> web.Response:
        try:
            payload = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        headers = {
            k: v for k, v in request.headers.items()
            if k.lower() in ("x-request-id", "authorization", "content-type")
        }
        try:
            result = await self.execute_node("root", payload, headers)
        except GraphExecutionError as e:
            return web.json_response({"error": str(e)}, status=e.status)
        return web.json_response(result)

    def create_application(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/", self.handle)
        async def healthz(_request: web.Request) -> web.Response:
            return web.json_response({"status": "ok"})

        app.router.add_get("/healthz", healthz)
        return app


def main(argv=None):
    configure_logging()
    parser = argparse.ArgumentParser()
    parser.add_argument("--graph-json", required=True)
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--timeout", type=float, default=DEFAULT_TIMEOUT)
    args = parser.parse_args(argv)
    router = GraphRouter(json.loads(args.graph_json), timeout=args.timeout)
    web.run_app(router.create_application(), port=args.port)


if __name__ == "__main__":
    main()
