"""Protocol-agnostic data plane: decode -> dispatch -> encode.

Every protocol head (V1 REST, V2 REST, gRPC) funnels through `DataPlane`,
which owns server/model health, request decoding (V2 JSON, V2 binary-tensor
extension, CloudEvents structured+binary), model dispatch, and response
encoding.

Parity: reference python/kserve/kserve/protocol/dataplane.py (infer :439,
explain :477, decode :332).  CloudEvents handling is hand-rolled (no
cloudevents dependency in this image) but wire-compatible for the JSON
structured and binary modes the reference supports.
"""

from __future__ import annotations

import json
import uuid
from typing import Dict, Optional, Tuple, Union

from ..errors import InvalidInput, ModelNotFound, ModelNotReady
from ..infer_type import InferRequest, InferResponse
from ..model import BaseModel, InferenceVerb
from ..model_repository import ModelRepository

SERVER_NAME = "kserve-tpu"
SERVER_VERSION = "0.1.0"

_CE_REQUIRED = ("ce-specversion", "ce-source", "ce-type", "ce-id")


def _is_binary_cloudevent(headers: Optional[Dict[str, str]]) -> bool:
    if not headers:
        return False
    lower = {k.lower(): v for k, v in headers.items()}
    return all(h in lower for h in _CE_REQUIRED)


def _is_structured_cloudevent(body: dict) -> bool:
    return (
        isinstance(body, dict)
        and "time" in body
        and "type" in body
        and "source" in body
        and "id" in body
        and "specversion" in body
        and "data" in body
    )


class DataPlane:
    """Core dispatch layer shared by all protocol heads."""

    def __init__(self, model_registry: ModelRepository):
        self._model_registry = model_registry
        self._server_name = SERVER_NAME
        self._server_version = SERVER_VERSION

    @property
    def model_registry(self) -> ModelRepository:
        return self._model_registry

    def get_model_from_registry(self, name: str) -> BaseModel:
        model = self._model_registry.get_model(name)
        if model is None:
            raise ModelNotFound(name)
        return model

    async def get_model(self, name: str) -> BaseModel:
        """Resolve a model; raises ModelNotFound / ModelNotReady."""
        model = self._model_registry.get_model(name)
        if model is None:
            raise ModelNotFound(name)
        if not await self._model_registry.is_model_ready(name):
            raise ModelNotReady(name)
        return model

    # ---------- health & metadata ----------

    async def live(self) -> Dict[str, str]:
        """'alive' unless some model reports its background loop wedged —
        liveness is the restart signal, so a wedged engine must surface
        here, not just in readiness."""
        for model in self._model_registry.get_models().values():
            if isinstance(model, BaseModel) and not await model.live():
                return {"status": "wedged"}
        return {"status": "alive"}

    async def ready(self) -> bool:
        """Server readiness: every registered model healthy (empty registry is
        ready so the pod can come up before models stream in)."""
        models = self._model_registry.get_models().values()
        for model in models:
            if isinstance(model, BaseModel):
                if not await model.healthy():
                    return False
        return True

    async def model_ready(self, model_name: str) -> bool:
        if self._model_registry.get_model(model_name) is None:
            raise ModelNotFound(model_name)
        return await self._model_registry.is_model_ready(model_name)

    def metadata(self) -> Dict:
        return {
            "name": self._server_name,
            "version": self._server_version,
            "extensions": ["model_repository_extension"],
        }

    async def model_metadata(self, model_name: str) -> Dict:
        model = self.get_model_from_registry(model_name)
        input_types = model.get_input_types() if hasattr(model, "get_input_types") else []
        output_types = model.get_output_types() if hasattr(model, "get_output_types") else []
        return {
            "name": model_name,
            "platform": "",
            "inputs": input_types,
            "outputs": output_types,
        }

    # ---------- decode / encode ----------

    def decode(
        self,
        body: Union[bytes, dict, InferRequest],
        headers: Optional[Dict[str, str]] = None,
        json_length: Optional[int] = None,
        model_name: Optional[str] = None,
    ) -> Tuple[Union[dict, InferRequest], Dict]:
        """bytes/dict -> (InferRequest | raw dict, attributes).  Handles the
        V2 binary-tensor extension and CloudEvents."""
        attributes: Dict = {}
        if isinstance(body, InferRequest):
            return body, attributes
        if json_length is not None and isinstance(body, (bytes, bytearray)):
            return (
                InferRequest.from_bytes(bytes(body), json_length, model_name or ""),
                attributes,
            )
        if isinstance(body, (bytes, bytearray)):
            if _is_binary_cloudevent(headers):
                lower = {k.lower(): v for k, v in (headers or {}).items()}
                attributes = {
                    k[3:]: v for k, v in lower.items() if k.startswith("ce-")
                }
                try:
                    decoded = json.loads(body) if body else {}
                except json.JSONDecodeError as e:
                    raise InvalidInput(f"Failed to decode binary cloudevent data: {e}")
                return decoded, attributes
            try:
                body = json.loads(body) if body else {}
            except json.JSONDecodeError as e:
                raise InvalidInput(f"Unrecognized request format: {e}")
        if isinstance(body, dict) and _is_structured_cloudevent(body):
            attributes = {k: v for k, v in body.items() if k != "data"}
            body = body["data"]
            if isinstance(body, str):
                try:
                    body = json.loads(body)
                except json.JSONDecodeError as e:
                    raise InvalidInput(f"Failed to decode cloudevent data: {e}")
        if isinstance(body, dict) and "inputs" in body and "instances" not in body:
            return InferRequest.from_dict(body, model_name=model_name), attributes
        return body, attributes

    def encode(
        self,
        model_name: str,
        response: Union[dict, InferResponse],
        headers: Optional[Dict[str, str]] = None,
        req_attributes: Optional[Dict] = None,
    ) -> Tuple[Union[dict, bytes], Dict[str, str]]:
        """Model output -> (body, response headers).  CloudEvent requests get
        CloudEvent responses; InferResponse encodes to V2 JSON or binary."""
        response_headers: Dict[str, str] = {}
        if isinstance(response, InferResponse):
            res, json_length = response.to_rest()
            if json_length is not None:
                response_headers["inference-header-content-length"] = str(json_length)
                response_headers["content-type"] = "application/octet-stream"
            return res, response_headers
        if _is_binary_cloudevent(headers) or (req_attributes and "specversion" in req_attributes):
            attrs = req_attributes or {}
            response_headers = {
                "ce-specversion": str(attrs.get("specversion", "1.0")),
                "ce-id": str(uuid.uuid4()),
                "ce-source": f"io.kserve.inference.{model_name}",
                "ce-type": "io.kserve.inference.response",
                "content-type": "application/json",
            }
            return response, response_headers
        return response, response_headers

    # ---------- dispatch ----------

    async def infer(
        self,
        model_name: str,
        request: Union[bytes, dict, InferRequest],
        headers: Optional[Dict[str, str]] = None,
        response_headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[Union[dict, InferResponse], Dict]:
        model = await self.get_model(model_name)
        response = await model(
            request,
            verb=InferenceVerb.PREDICT,
            headers=headers,
            response_headers=response_headers,
        )
        return response, headers or {}

    async def explain(
        self,
        model_name: str,
        request: Union[bytes, dict, InferRequest],
        headers: Optional[Dict[str, str]] = None,
        response_headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[Union[dict, InferResponse], Dict]:
        model = await self.get_model(model_name)
        response = await model(
            request,
            verb=InferenceVerb.EXPLAIN,
            headers=headers,
            response_headers=response_headers,
        )
        return response, headers or {}
