"""Time-series forecasting protocol (the OpenAI-pattern mirror for
forecasting runtimes).

Parity: reference python/kserve/kserve/protocol/rest/timeseries/
(types.py — univariate/multivariate series, Frequency enum + step math,
quantile forecasts, per-output status; endpoints.py — POST
/v1/timeseries/forecast + GET /v1/timeseries/models; dataplane.py;
error.py), rebuilt on aiohttp + pydantic v2.
"""

from __future__ import annotations

import json
import time
import uuid
from datetime import datetime, timedelta
from enum import Enum
from typing import Dict, List, Optional, Union

from aiohttp import web
from pydantic import BaseModel, ConfigDict, Field, ValidationError

from ..errors import InvalidInput, ModelNotFound, ModelNotReady
from ..model import BaseModel as ServableModel

# List[float] (univariate) or List[List[float]] (multivariate, one inner
# list per timestep)
TimeSeries = Union[List[float], List[List[float]]]


class Error(BaseModel):
    code: Optional[str] = None
    message: str
    param: Optional[str] = None
    type: str


class ErrorResponse(BaseModel):
    error: Error


class Frequency(str, Enum):
    SECOND = "second"
    SECOND_SHORT = "S"
    MINUTE = "minute"
    MINUTE_SHORT = "T"
    HOUR = "hour"
    HOUR_SHORT = "H"
    DAY = "day"
    DAY_SHORT = "D"
    WEEK = "week"
    WEEK_SHORT = "W"
    MONTH = "month"
    MONTH_SHORT = "M"
    QUARTER = "quarter"
    QUARTER_SHORT = "Q"
    YEAR = "year"
    YEAR_SHORT = "Y"


def _month_add(dt: datetime, months: int) -> datetime:
    import calendar

    month = dt.month - 1 + months
    year = dt.year + month // 12
    month = month % 12 + 1
    # clamp the day (Jan 31 + 1 month -> Feb 28/29)
    return dt.replace(
        year=year, month=month,
        day=min(dt.day, calendar.monthrange(year, month)[1]))


FREQUENCY_MAP = {
    "S": lambda steps: timedelta(seconds=steps),
    "second": lambda steps: timedelta(seconds=steps),
    "T": lambda steps: timedelta(minutes=steps),
    "minute": lambda steps: timedelta(minutes=steps),
    "H": lambda steps: timedelta(hours=steps),
    "hour": lambda steps: timedelta(hours=steps),
    "D": lambda steps: timedelta(days=steps),
    "day": lambda steps: timedelta(days=steps),
    "W": lambda steps: timedelta(weeks=steps),
    "week": lambda steps: timedelta(weeks=steps),
}
_MONTHLY = {"M": 1, "month": 1, "Q": 3, "quarter": 3, "Y": 12, "year": 12}


def _parse_iso(ts: str) -> datetime:
    # py3.10's fromisoformat rejects the common 'Z' UTC suffix
    return datetime.fromisoformat(ts.replace("Z", "+00:00"))


def advance_timestamp(start: str, frequency: Frequency, steps: int) -> str:
    """ISO8601 start + N frequency steps (a forecast's start is the
    observation window's end + one step)."""
    dt = _parse_iso(start)
    freq = frequency.value
    if freq in _MONTHLY:
        return _month_add(dt, _MONTHLY[freq] * steps).isoformat()
    return (dt + FREQUENCY_MAP[freq](steps)).isoformat()


# one request may not demand more than this many forecast steps (a cap on
# the allocation/compile cost a single unauthenticated call can trigger)
MAX_HORIZON = 10_000


class Status(str, Enum):
    COMPLETED = "completed"
    ERROR = "error"
    PENDING = "pending"
    PARTIAL = "partial"


class TimeSeriesType(str, Enum):
    UNIVARIATE = "univariate_time_series"
    MULTIVARIATE = "multivariate_time_series"


class TimeSeriesInput(BaseModel):
    model_config = ConfigDict(extra="allow")
    type: TimeSeriesType
    name: str
    series: TimeSeries
    frequency: Frequency
    start_timestamp: Optional[str] = None


class ForecastOptions(BaseModel):
    model_config = ConfigDict(extra="allow")
    horizon: int
    quantiles: Optional[List[float]] = None


class Metadata(BaseModel):
    model_config = ConfigDict(extra="allow")


class ForecastRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    inputs: List[TimeSeriesInput]
    options: ForecastOptions
    metadata: Optional[Metadata] = None


class TimeSeriesForecast(BaseModel):
    model_config = ConfigDict(extra="allow")
    type: TimeSeriesType
    name: str
    mean_forecast: TimeSeries
    frequency: Frequency
    start_timestamp: str
    quantiles: Optional[Dict[str, TimeSeries]] = None


class ForecastOutput(BaseModel):
    model_config = ConfigDict(extra="allow")
    type: str = "forecast"
    id: str = Field(default_factory=lambda: f"fo-{uuid.uuid4().hex}")
    status: Status
    content: List[TimeSeriesForecast]
    error: Optional[Error] = None


class Usage(BaseModel):
    model_config = ConfigDict(extra="allow")
    prompt_tokens: int
    completion_tokens: int
    total_tokens: int


class ForecastResponse(BaseModel):
    model_config = ConfigDict(extra="allow")
    id: str
    created_at: int
    status: Status
    error: Optional[Error] = None
    model: str
    outputs: List[ForecastOutput]
    usage: Optional[Usage] = None


def make_forecast_response(model: str, outputs: List[ForecastOutput],
                           usage: Optional[Usage] = None) -> ForecastResponse:
    """Response envelope with id/timestamp/aggregate status filled in."""
    if outputs and all(o.status == Status.COMPLETED for o in outputs):
        status = Status.COMPLETED
    elif any(o.status == Status.COMPLETED for o in outputs):
        status = Status.PARTIAL
    else:
        status = Status.ERROR
    return ForecastResponse(
        id=f"forecast-{uuid.uuid4().hex}",
        created_at=int(time.time()),
        status=status,
        model=model,
        outputs=outputs,
        usage=usage,
    )


class TimeSeriesModel(ServableModel):
    """Forecasting runtimes implement create_forecast."""

    async def create_forecast(self, request: ForecastRequest,
                              context=None) -> ForecastResponse:
        raise NotImplementedError()


def _validate_series(inputs: List[TimeSeriesInput]) -> None:
    for ts in inputs:
        if not ts.series:
            raise InvalidInput(f"series {ts.name!r} is empty")
        first = ts.series[0]
        if ts.type == TimeSeriesType.MULTIVARIATE:
            if not isinstance(first, list):
                raise InvalidInput(
                    f"series {ts.name!r} is multivariate but rows are scalars")
            width = len(first)
            if width == 0:
                raise InvalidInput(
                    f"series {ts.name!r} rows are empty (0 variables)")
            if any(not isinstance(row, list) or len(row) != width
                   for row in ts.series):
                raise InvalidInput(
                    f"series {ts.name!r} rows must all have {width} variables")
        elif isinstance(first, list):
            raise InvalidInput(
                f"series {ts.name!r} is univariate but rows are lists")
        if ts.start_timestamp is not None:
            try:
                _parse_iso(ts.start_timestamp)
            except ValueError:
                raise InvalidInput(
                    f"series {ts.name!r} start_timestamp is not ISO8601")


class TimeSeriesDataPlane:
    """Validation + model dispatch (ref dataplane.py)."""

    def __init__(self, model_registry):
        self._registry = model_registry

    async def forecast(self, request: ForecastRequest) -> ForecastResponse:
        model = self._registry.get_model(request.model)
        if model is None:
            raise ModelNotFound(request.model)
        if not await self._registry.is_model_ready(request.model):
            raise ModelNotReady(request.model)
        if not isinstance(model, TimeSeriesModel):
            raise InvalidInput(
                f"model {request.model} does not support forecasting")
        if request.options.horizon < 1:
            raise InvalidInput("options.horizon must be >= 1")
        if request.options.horizon > MAX_HORIZON:
            # unbounded horizons are an allocation/compile DoS vector
            raise InvalidInput(
                f"options.horizon must be <= {MAX_HORIZON}")
        for q in request.options.quantiles or []:
            if not 0.0 < q < 1.0:
                raise InvalidInput(f"quantile {q} outside (0, 1)")
        _validate_series(request.inputs)
        return await model.create_forecast(request)

    async def models(self) -> List[str]:
        return [
            name for name in self._registry.get_models()
            if isinstance(self._registry.get_model(name), TimeSeriesModel)
        ]


class TimeSeriesEndpoints:
    def __init__(self, model_registry):
        self.dataplane = TimeSeriesDataPlane(model_registry)

    async def forecast(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            raise InvalidInput(f"invalid JSON body: {e}")
        try:
            params = ForecastRequest.model_validate(body)
        except ValidationError as e:
            raise InvalidInput(str(e))
        result = await self.dataplane.forecast(params)
        return web.json_response(result.model_dump(exclude_none=True))

    async def models(self, request: web.Request) -> web.Response:
        return web.json_response(await self.dataplane.models())

    def register(self, app: web.Application) -> None:
        app.router.add_post("/v1/timeseries/forecast", self.forecast)
        app.router.add_get("/v1/timeseries/models", self.models)
