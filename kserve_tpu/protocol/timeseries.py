"""Time-series protocol head: /timeseries/v1/forecast.

Parity: reference python/kserve/kserve/protocol/rest/timeseries/ (the
OpenAI-pattern mirror for forecasting runtimes — typed request/response,
model ABC, aiohttp routes)."""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from aiohttp import web
from pydantic import BaseModel, ConfigDict, Field, ValidationError

from ..errors import InvalidInput, ModelNotFound, ModelNotReady
from ..model import BaseModel as ServableModel


class TimeSeries(BaseModel):
    model_config = ConfigDict(extra="allow")
    timestamps: List[str] = Field(default_factory=list)
    values: List[float] = Field(default_factory=list)
    id: Optional[str] = None


class ForecastRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    inputs: List[TimeSeries]
    horizon: int = 1
    quantiles: Optional[List[float]] = None
    parameters: Dict[str, object] = Field(default_factory=dict)


class Forecast(BaseModel):
    id: Optional[str] = None
    values: List[float] = Field(default_factory=list)
    quantile_values: Optional[Dict[str, List[float]]] = None


class ForecastResponse(BaseModel):
    model: str = ""
    forecasts: List[Forecast] = Field(default_factory=list)


class TimeSeriesModel(ServableModel):
    """Forecasting runtimes implement create_forecast."""

    async def create_forecast(self, request: ForecastRequest, context=None) -> ForecastResponse:
        raise NotImplementedError()


class TimeSeriesEndpoints:
    def __init__(self, model_registry):
        self._registry = model_registry

    async def forecast(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            raise InvalidInput(f"invalid JSON body: {e}")
        try:
            params = ForecastRequest.model_validate(body)
        except ValidationError as e:
            raise InvalidInput(str(e))
        model = self._registry.get_model(params.model)
        if model is None:
            raise ModelNotFound(params.model)
        if not await self._registry.is_model_ready(params.model):
            raise ModelNotReady(params.model)
        if not isinstance(model, TimeSeriesModel):
            raise InvalidInput(f"model {params.model} does not support forecasting")
        result = await model.create_forecast(params)
        return web.json_response(result.model_dump(exclude_none=True))

    def register(self, app: web.Application) -> None:
        app.router.add_post("/timeseries/v1/forecast", self.forecast)
