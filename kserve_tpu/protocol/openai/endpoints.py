"""OpenAI REST endpoints (aiohttp) with SSE streaming.

Routes: /openai/v1/{models,completions,chat/completions,embeddings,rerank}
plus unprefixed /v1/chat/completions-style aliases for stock OpenAI clients.

Parity: reference python/kserve/kserve/protocol/rest/openai/endpoints.py:52
(SSE streaming at :58-146); aiohttp StreamResponse replaces FastAPI
StreamingResponse.
"""

from __future__ import annotations

import json
from typing import AsyncIterator

from aiohttp import web
from pydantic import ValidationError

from ...errors import InvalidInput, ModelNotFound, ModelNotReady
from ...lifecycle import GenerationPreempted, ReplicaDrainingError
from ...logging import current_request_id, logger
from .dataplane import OpenAIDataPlane
from .types import (
    ChatCompletionRequest,
    CompletionRequest,
    EmbeddingRequest,
    ErrorInfo,
    ErrorResponse,
    RerankRequest,
)


def _openai_error(status: int, message: str, err_type: str = "invalid_request_error"):
    body = ErrorResponse(error=ErrorInfo(message=message, type=err_type))
    return web.json_response(body.model_dump(), status=status)


async def _final_event(response: web.StreamResponse, payload: dict) -> None:
    """Write a terminal SSE event, tolerating a client that already hung
    up.  The stream then ends WITHOUT [DONE], keeping the truncation
    detectable to splice-aware clients."""
    try:
        await response.write(
            f"data: {json.dumps(payload)}\n\n".encode("utf-8"))
    except ConnectionResetError:
        pass


async def _stream_sse(request: web.Request, iterator: AsyncIterator) -> web.StreamResponse:
    headers = {
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
        "Connection": "keep-alive",
    }
    # streamed responses prepare their headers here, before the context
    # middleware could stamp them — echo the correlation id ourselves so a
    # client can quote it when reporting a bad stream
    rid = current_request_id()
    if rid and rid != "-":
        headers["x-request-id"] = rid
    response = web.StreamResponse(status=200, headers=headers)
    await response.prepare(request)
    try:
        async for chunk in iterator:
            if isinstance(chunk, (bytes, str)):
                data = chunk if isinstance(chunk, str) else chunk.decode("utf-8")
            else:
                data = chunk.model_dump_json(exclude_unset=False, exclude_none=True)
            await response.write(f"data: {data}\n\n".encode("utf-8"))
        await response.write(b"data: [DONE]\n\n")
    except ConnectionResetError:
        pass
    except GenerationPreempted as e:
        # drained mid-stream with headers already sent: emit the portable
        # checkpoint as the final event — the client re-seats it
        # (x-generation-checkpoint request header) on a healthy replica
        # and splices the continuation deltas after what it already
        # received: zero lost, zero duplicated
        await _final_event(response, {
            "error": {"type": "generation_preempted", "message": str(e)},
            "checkpoint": e.checkpoint.to_header(),
        })
    except ReplicaDrainingError as e:
        # a drain landed between sync admission and the first enqueue:
        # the client retries from scratch on a healthy replica
        await _final_event(response, {
            "error": {"type": "replica_draining", "message": str(e)},
        })
    except Exception as e:
        # headers are already on the wire: letting this escape would have
        # the error middleware write a SECOND response into the chunked
        # stream, corrupting it mid-flight (the client sees a bare parse
        # error instead of what went wrong)
        logger.exception("mid-stream failure after SSE prepare")
        await _final_event(response, {
            "error": {"type": "internal_error", "message": str(e)},
        })
    await response.write_eof()
    return response


class OpenAIEndpoints:
    def __init__(self, dataplane: OpenAIDataPlane):
        self.dataplane = dataplane

    async def models(self, request: web.Request) -> web.Response:
        model_list = await self.dataplane.models()
        return web.json_response(model_list.model_dump())

    async def _parse(self, request: web.Request, model_cls):
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            raise InvalidInput(f"invalid JSON body: {e}")
        try:
            return model_cls.model_validate(body)
        except ValidationError as e:
            raise InvalidInput(str(e))

    async def create_completion(self, request: web.Request):
        params = await self._parse(request, CompletionRequest)
        headers = {k.lower(): v for k, v in request.headers.items()}
        result = await self.dataplane.create_completion(
            params.model, params, raw_request=request, context=headers
        )
        if params.stream:
            return await _stream_sse(request, result)
        return web.json_response(result.model_dump(exclude_none=True))

    async def create_chat_completion(self, request: web.Request):
        params = await self._parse(request, ChatCompletionRequest)
        headers = {k.lower(): v for k, v in request.headers.items()}
        result = await self.dataplane.create_chat_completion(
            params.model, params, raw_request=request, context=headers
        )
        if params.stream:
            return await _stream_sse(request, result)
        return web.json_response(result.model_dump(exclude_none=True))

    async def create_embedding(self, request: web.Request):
        params = await self._parse(request, EmbeddingRequest)
        headers = {k.lower(): v for k, v in request.headers.items()}
        result = await self.dataplane.create_embedding(
            params.model, params, raw_request=request, context=headers
        )
        return web.json_response(result.model_dump(exclude_none=True))

    async def create_rerank(self, request: web.Request):
        params = await self._parse(request, RerankRequest)
        headers = {k.lower(): v for k, v in request.headers.items()}
        result = await self.dataplane.create_rerank(
            params.model, params, raw_request=request, context=headers
        )
        return web.json_response(result.model_dump(exclude_none=True))

    def register(self, app: web.Application) -> None:
        for prefix in ("/openai/v1", "/openai"):
            app.router.add_get(f"{prefix}/models", self.models)
            app.router.add_post(f"{prefix}/completions", self.create_completion)
            app.router.add_post(f"{prefix}/chat/completions", self.create_chat_completion)
            app.router.add_post(f"{prefix}/embeddings", self.create_embedding)
            app.router.add_post(f"{prefix}/rerank", self.create_rerank)


def register_openai_routes(app: web.Application, dataplane) -> None:
    if not isinstance(dataplane, OpenAIDataPlane):
        # Share the registry; OpenAI verbs only need repository access.
        dataplane = OpenAIDataPlane(dataplane.model_registry)
    OpenAIEndpoints(dataplane).register(app)
