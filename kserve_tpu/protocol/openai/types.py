"""OpenAI API pydantic types (completions, chat, embeddings, rerank).

Parity: reference python/kserve/kserve/protocol/rest/openai/types/ (generated
from the OpenAI spec); here hand-written with the fields the serving path
actually consumes, plus vLLM-style extensions the JAX engine honors
(top_k, min_p, repetition_penalty, ignore_eos).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field


def random_uuid(prefix: str = "") -> str:
    return f"{prefix}{uuid.uuid4().hex}"


class ErrorInfo(BaseModel):
    message: str
    type: str = "server_error"
    param: Optional[str] = None
    code: Optional[str] = None


class ErrorResponse(BaseModel):
    error: ErrorInfo


class ModelCard(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "kserve-tpu"


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: List[ModelCard] = Field(default_factory=list)


class UsageInfo(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: Optional[int] = 0
    total_tokens: int = 0


class StreamOptions(BaseModel):
    include_usage: Optional[bool] = False
    continuous_usage_stats: Optional[bool] = False


class CompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")

    model: str
    prompt: Union[str, List[str], List[int], List[List[int]]]
    best_of: Optional[int] = None
    echo: Optional[bool] = False
    frequency_penalty: Optional[float] = 0.0
    logit_bias: Optional[Dict[str, float]] = None
    logprobs: Optional[int] = None
    max_tokens: Optional[int] = 16
    n: int = 1
    presence_penalty: Optional[float] = 0.0
    seed: Optional[int] = None
    stop: Optional[Union[str, List[str]]] = None
    stream: Optional[bool] = False
    stream_options: Optional[StreamOptions] = None
    suffix: Optional[str] = None
    temperature: Optional[float] = 1.0
    top_p: Optional[float] = 1.0
    user: Optional[str] = None
    # engine extensions
    top_k: Optional[int] = None
    min_p: Optional[float] = None
    repetition_penalty: Optional[float] = None
    ignore_eos: Optional[bool] = False
    min_tokens: Optional[int] = 0


class CompletionLogprobs(BaseModel):
    text_offset: List[int] = Field(default_factory=list)
    token_logprobs: List[Optional[float]] = Field(default_factory=list)
    tokens: List[str] = Field(default_factory=list)
    top_logprobs: Optional[List[Optional[Dict[str, float]]]] = None


class CompletionChoice(BaseModel):
    index: int
    text: str
    logprobs: Optional[CompletionLogprobs] = None
    finish_reason: Optional[Literal["stop", "length", "content_filter", "tool_calls"]] = None
    stop_reason: Optional[Union[int, str]] = None


class Completion(BaseModel):
    id: str = Field(default_factory=lambda: random_uuid("cmpl-"))
    object: Literal["text_completion"] = "text_completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[CompletionChoice] = Field(default_factory=list)
    usage: Optional[UsageInfo] = None
    system_fingerprint: Optional[str] = None


# ---------------- chat ----------------


class FunctionCall(BaseModel):
    name: str
    arguments: str


class ToolCall(BaseModel):
    id: str = Field(default_factory=lambda: random_uuid("call-"))
    type: Literal["function"] = "function"
    function: FunctionCall


class FunctionDefinition(BaseModel):
    name: str
    description: Optional[str] = None
    parameters: Optional[Dict[str, Any]] = None


class ChatCompletionTool(BaseModel):
    type: Literal["function"] = "function"
    function: FunctionDefinition


class ChatCompletionContentPart(BaseModel):
    model_config = ConfigDict(extra="allow")
    type: str
    text: Optional[str] = None
    image_url: Optional[Dict[str, Any]] = None


class ChatCompletionMessage(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: str
    content: Optional[Union[str, List[ChatCompletionContentPart]]] = None
    name: Optional[str] = None
    tool_calls: Optional[List[ToolCall]] = None
    tool_call_id: Optional[str] = None

    def text_content(self) -> str:
        if self.content is None:
            return ""
        if isinstance(self.content, str):
            return self.content
        return "".join(p.text or "" for p in self.content if p.type == "text")


class ResponseFormat(BaseModel):
    model_config = ConfigDict(extra="allow")
    type: Literal["text", "json_object", "json_schema"] = "text"
    json_schema: Optional[Dict[str, Any]] = None


class ChatCompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")

    model: str
    messages: List[ChatCompletionMessage]
    frequency_penalty: Optional[float] = 0.0
    logit_bias: Optional[Dict[str, float]] = None
    logprobs: Optional[bool] = False
    top_logprobs: Optional[int] = None
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    n: int = 1
    presence_penalty: Optional[float] = 0.0
    response_format: Optional[ResponseFormat] = None
    seed: Optional[int] = None
    stop: Optional[Union[str, List[str]]] = None
    stream: Optional[bool] = False
    stream_options: Optional[StreamOptions] = None
    temperature: Optional[float] = 1.0
    top_p: Optional[float] = 1.0
    tools: Optional[List[ChatCompletionTool]] = None
    tool_choice: Optional[Union[str, Dict[str, Any]]] = None
    user: Optional[str] = None
    # engine extensions
    top_k: Optional[int] = None
    min_p: Optional[float] = None
    repetition_penalty: Optional[float] = None
    ignore_eos: Optional[bool] = False
    min_tokens: Optional[int] = 0
    chat_template_kwargs: Optional[Dict[str, Any]] = None


class ChatCompletionLogprob(BaseModel):
    token: str
    logprob: float = -9999.0
    bytes: Optional[List[int]] = None


class ChatCompletionLogprobsContent(ChatCompletionLogprob):
    top_logprobs: List[ChatCompletionLogprob] = Field(default_factory=list)


class ChatCompletionLogprobs(BaseModel):
    content: Optional[List[ChatCompletionLogprobsContent]] = None


class ChatCompletionResponseMessage(BaseModel):
    role: str = "assistant"
    content: Optional[str] = None
    tool_calls: Optional[List[ToolCall]] = None
    reasoning_content: Optional[str] = None


class ChatCompletionChoice(BaseModel):
    index: int
    message: ChatCompletionResponseMessage
    logprobs: Optional[ChatCompletionLogprobs] = None
    finish_reason: Optional[str] = None


class ChatCompletion(BaseModel):
    id: str = Field(default_factory=lambda: random_uuid("chatcmpl-"))
    object: Literal["chat.completion"] = "chat.completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[ChatCompletionChoice] = Field(default_factory=list)
    usage: Optional[UsageInfo] = None
    system_fingerprint: Optional[str] = None


class ChatCompletionChunkDelta(BaseModel):
    role: Optional[str] = None
    content: Optional[str] = None
    tool_calls: Optional[List[ToolCall]] = None


class ChatCompletionChunkChoice(BaseModel):
    index: int
    delta: ChatCompletionChunkDelta
    logprobs: Optional[ChatCompletionLogprobs] = None
    finish_reason: Optional[str] = None


class ChatCompletionChunk(BaseModel):
    id: str = Field(default_factory=lambda: random_uuid("chatcmpl-"))
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[ChatCompletionChunkChoice] = Field(default_factory=list)
    usage: Optional[UsageInfo] = None


# ---------------- embeddings / rerank ----------------


class EmbeddingRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    input: Union[str, List[str], List[int], List[List[int]]]
    encoding_format: Literal["float", "base64"] = "float"
    dimensions: Optional[int] = None
    user: Optional[str] = None


class EmbeddingObject(BaseModel):
    object: Literal["embedding"] = "embedding"
    index: int
    embedding: Union[List[float], str]


class Embedding(BaseModel):
    object: Literal["list"] = "list"
    data: List[EmbeddingObject] = Field(default_factory=list)
    model: str = ""
    usage: UsageInfo = Field(default_factory=UsageInfo)


class RerankRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    query: str
    documents: List[str]
    top_n: Optional[int] = None
    return_documents: bool = True


class RerankResultDocument(BaseModel):
    text: str


class RerankResult(BaseModel):
    index: int
    relevance_score: float
    document: Optional[RerankResultDocument] = None


class Rerank(BaseModel):
    id: str = Field(default_factory=lambda: random_uuid("rerank-"))
    results: List[RerankResult] = Field(default_factory=list)
    model: str = ""
    usage: UsageInfo = Field(default_factory=UsageInfo)
