"""OpenAI data plane: resolves the model and dispatches typed requests.

Parity: reference python/kserve/kserve/protocol/rest/openai/dataplane.py:41.
"""

from __future__ import annotations

from typing import AsyncIterator, Union

from ...errors import InvalidInput, ModelNotFound, ModelNotReady
from ..dataplane import DataPlane
from .openai_model import OpenAIEncoderModel, OpenAIGenerativeModel, OpenAIModel
from .types import (
    ChatCompletion,
    ChatCompletionChunk,
    ChatCompletionRequest,
    Completion,
    CompletionRequest,
    Embedding,
    EmbeddingRequest,
    ModelCard,
    ModelList,
    Rerank,
    RerankRequest,
)


class OpenAIDataPlane(DataPlane):
    """Adds OpenAI verbs on top of the core data plane."""

    async def _get_openai_model(self, name: str, kind) -> OpenAIModel:
        model = self._model_registry.get_model(name)
        if model is None:
            raise ModelNotFound(name)
        if not await self._model_registry.is_model_ready(name):
            raise ModelNotReady(name)
        if not isinstance(model, kind):
            raise InvalidInput(f"Model {name} does not support this endpoint")
        return model

    async def create_completion(
        self, model_name: str, request: CompletionRequest, raw_request=None, context=None
    ) -> Union[Completion, AsyncIterator[Completion]]:
        model = await self._get_openai_model(model_name, OpenAIGenerativeModel)
        return await model.create_completion(request, raw_request, context)

    async def create_chat_completion(
        self, model_name: str, request: ChatCompletionRequest, raw_request=None, context=None
    ) -> Union[ChatCompletion, AsyncIterator[ChatCompletionChunk]]:
        model = await self._get_openai_model(model_name, OpenAIGenerativeModel)
        return await model.create_chat_completion(request, raw_request, context)

    async def create_embedding(
        self, model_name: str, request: EmbeddingRequest, raw_request=None, context=None
    ) -> Embedding:
        model = await self._get_openai_model(model_name, OpenAIEncoderModel)
        return await model.create_embedding(request, raw_request, context)

    async def create_rerank(
        self, model_name: str, request: RerankRequest, raw_request=None, context=None
    ) -> Rerank:
        model = await self._get_openai_model(model_name, OpenAIEncoderModel)
        return await model.create_rerank(request, raw_request, context)

    async def models(self) -> ModelList:
        cards = []
        for name, model in self._model_registry.get_models().items():
            if not isinstance(model, OpenAIModel):
                continue
            cards.append(ModelCard(id=name))
            # LoRA adapters list as selectable models (vLLM semantics)
            for alias in getattr(model, "aliases", ()):
                cards.append(ModelCard(id=alias))
        return ModelList(data=cards)
