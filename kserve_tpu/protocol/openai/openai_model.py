"""OpenAI-protocol model ABCs.

`OpenAIModel` is the marker base the server uses to route OpenAI endpoints;
`OpenAIGenerativeModel` adds completions/chat, `OpenAIEncoderModel` adds
embeddings/rerank.  `ChatAdapterModel` upgrades a completions-only model to
chat by applying a chat template.

Parity: reference python/kserve/kserve/protocol/rest/openai/openai_model.py:42-110
and chat_adapter_model.py.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional, Union

from ...model import BaseModel
from .types import (
    ChatCompletion,
    ChatCompletionChunk,
    ChatCompletionRequest,
    Completion,
    CompletionRequest,
    Embedding,
    EmbeddingRequest,
    Rerank,
    RerankRequest,
)


class OpenAIModel(BaseModel):
    """Marker base; routed to /openai/v1/* instead of V1/V2 dispatch."""

    def __init__(self, name: str):
        super().__init__(name)
        self.ready = False


class OpenAIGenerativeModel(OpenAIModel):
    async def create_completion(
        self, request: CompletionRequest, raw_request=None, context=None
    ) -> Union[Completion, AsyncIterator[Completion]]:
        raise NotImplementedError()

    async def create_chat_completion(
        self, request: ChatCompletionRequest, raw_request=None, context=None
    ) -> Union[ChatCompletion, AsyncIterator[ChatCompletionChunk]]:
        raise NotImplementedError()


class OpenAIEncoderModel(OpenAIModel):
    async def create_embedding(
        self, request: EmbeddingRequest, raw_request=None, context=None
    ) -> Embedding:
        raise NotImplementedError()

    async def create_rerank(
        self, request: RerankRequest, raw_request=None, context=None
    ) -> Rerank:
        raise NotImplementedError()
