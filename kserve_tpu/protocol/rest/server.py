"""aiohttp REST server assembling all protocol heads.

The reference builds on FastAPI/uvicorn; this image ships aiohttp, which is a
better fit anyway for the streaming-heavy OpenAI path (no ASGI translation
layer under SSE).  Exception -> status mapping, timing middleware, and the
/metrics endpoint mirror the reference's rest/server.py.

Parity: reference python/kserve/kserve/protocol/rest/server.py.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import TYPE_CHECKING, List, Optional

from aiohttp import web
from prometheus_client import CONTENT_TYPE_LATEST, generate_latest

from ...errors import (
    InferenceError,
    InvalidInput,
    ModelNotFound,
    ModelNotReady,
    ServerNotLive,
    ServerNotReady,
    UnsupportedProtocol,
)
from ...lifecycle import (
    CHECKPOINT_FIELD_SIZE_LIMIT,
    CHECKPOINT_HEADER,
    CHECKPOINT_HEADER_SAFE_BYTES,
    READY,
    GenerationPreempted,
    ReplicaDrainingError,
    ReplicaLifecycle,
    lifecycle_middleware,
    register_admin_routes,
)
from ...kvstore import PAGE_ROUTE
from ...logging import logger, trace_logger
from ...metrics import DEADLINE_REJECTED, KV_PEER_PAGES_SERVED, SHED_REQUESTS
from ...resilience import (
    DEADLINE_HEADER,
    Deadline,
    DeadlineExceededError,
    LoadShedder,
    ShedConfig,
    deadline_scope,
    shedding_middleware,
)
from .v1_endpoints import V1Endpoints
from .v2_endpoints import V2Endpoints

if TYPE_CHECKING:
    from ..dataplane import DataPlane
    from ..model_repository_extension import ModelRepositoryExtension


def _error_response(status: int, reason: str) -> web.Response:
    return web.json_response({"error": reason}, status=status)


@web.middleware
async def error_middleware(request: web.Request, handler):
    try:
        return await handler(request)
    except InvalidInput as e:
        return _error_response(400, str(e))
    except ModelNotFound as e:
        return _error_response(404, e.reason)
    except (ModelNotReady, ServerNotReady, ServerNotLive) as e:
        return _error_response(503, str(e))
    except UnsupportedProtocol as e:
        return _error_response(400, e.reason)
    except NotImplementedError as e:
        return _error_response(501, str(e) or "Not implemented")
    except DeadlineExceededError as e:
        return _error_response(504, str(e))
    except ReplicaDrainingError as e:
        # this replica is going away: 503 + Retry-After sends the client's
        # RetryPolicy (or the EPP) to a healthy replica
        return web.json_response(
            {"error": str(e)}, status=503,
            headers={"Retry-After": f"{e.retry_after_s:g}"},
        )
    except GenerationPreempted as e:
        # the drain budget expired mid-generation: hand the caller the
        # portable checkpoint so the retry RESUMES (zero tokens lost)
        # instead of restarting from the prompt.  The body always carries
        # it; the header convenience form is attached only while it fits
        # the parsers of stock intermediaries (httpx/h11, default aiohttp)
        # — an oversized response header would crash the very client the
        # checkpoint is meant to save
        headers = {"Retry-After": "1"}
        header_form = e.checkpoint.to_header()
        if len(header_form) <= CHECKPOINT_HEADER_SAFE_BYTES:
            headers[CHECKPOINT_HEADER] = header_form
        return web.json_response(
            {"error": str(e), "checkpoint": e.checkpoint.to_dict()},
            status=503,
            headers=headers,
        )
    except InferenceError as e:
        return _error_response(500, str(e))
    except web.HTTPException:
        raise
    except Exception as e:  # noqa: BLE001 — last-resort 500 with log
        logger.exception("Internal server error handling %s", request.path)
        return _error_response(500, f"{type(e).__name__}: {e}")


@web.middleware
async def deadline_middleware(request: web.Request, handler):
    """Parse the propagated deadline budget (resilience/deadline.py) and
    bind it as the request's contextvar scope; an already-dead budget is
    rejected 504 here, before any handler work."""
    deadline = Deadline.from_header(request.headers.get(DEADLINE_HEADER))
    if deadline is None:
        return await handler(request)
    if deadline.expired:
        DEADLINE_REJECTED.labels(component="rest").inc()
        return _error_response(504, "request deadline exceeded before handling")
    with deadline_scope(deadline):
        return await handler(request)


@web.middleware
async def timing_middleware(request: web.Request, handler):
    start = time.perf_counter()
    response = await handler(request)
    elapsed_ms = (time.perf_counter() - start) * 1000
    trace_logger.info(
        "%s %s %s %.3fms", request.method, request.path, response.status, elapsed_ms
    )
    return response


async def metrics_handler(request: web.Request) -> web.Response:
    body = generate_latest()
    return web.Response(body=body, content_type=CONTENT_TYPE_LATEST.split(";")[0])


async def root_handler(request: web.Request) -> web.Response:
    return web.json_response({"status": "alive"})


class RESTServer:
    """Owns the aiohttp Application; `create_application()` is separated out
    so tests can drive it with aiohttp's in-process test client."""

    def __init__(
        self,
        dataplane: "DataPlane",
        model_repository_extension: Optional["ModelRepositoryExtension"] = None,
        http_port: int = 8080,
        access_log_format: Optional[str] = None,
        enable_docs_url: bool = False,
        openai_models: Optional[List] = None,
        enable_latency_logging: bool = True,
        reuse_port: bool = False,
        ssl_context=None,  # ssl.SSLContext (controlplane/tls.py helpers)
        shed_config: Optional[ShedConfig] = None,  # None = env defaults
        lifecycle: Optional[ReplicaLifecycle] = None,
        on_drain=None,  # async callable kicked by POST /admin/drain
        profiler=None,  # observability.ProfilerSession (None = default)
    ):
        self.dataplane = dataplane
        # replica lifecycle (kserve_tpu/lifecycle): drives the admission
        # gate, the readiness override while draining, and /admin/drain
        self.lifecycle = lifecycle
        self.on_drain = on_drain
        self.model_repository_extension = model_repository_extension
        self.http_port = http_port
        self.access_log_format = access_log_format
        self.enable_latency_logging = enable_latency_logging
        # admission-time load shedding (resilience/shedding.py): inference
        # POSTs bounce 429 + Retry-After once the aggregate engine queue
        # crosses the watermark (KSERVE_TPU_SHED_WATERMARK; <=0 disables)
        self.shedder = LoadShedder(
            shed_config or ShedConfig.from_env(),
            on_shed=lambda: SHED_REQUESTS.labels(component="rest").inc(),
        )
        # SO_REUSEPORT is for the multiprocess worker mode only — with it on
        # by default, stale processes silently share (and steal from) the port
        self.reuse_port = reuse_port
        self.ssl_context = ssl_context
        # POST /admin/profile session (observability/introspection.py);
        # injectable so tests drive the capture window with a FakeClock
        self.profiler = profiler
        # peer page server bound (docs/kv_hierarchy.md "Cross-replica
        # page serving"): at most this many concurrent page reads, so a
        # fleet of cold-waking peers can't starve local decode of disk
        # bandwidth or executor threads.  The route itself is read-only,
        # GET, and therefore naturally exempt from the (POST-inference-
        # only) shedder and lifecycle admission gates.
        self.peer_page_concurrency = 4
        self._peer_page_sem: Optional[asyncio.Semaphore] = None
        self._runner: Optional[web.AppRunner] = None

    def create_application(self) -> web.Application:
        from ...tracing import (
            get_tracer,
            request_context_middleware,
            tracing_middleware,
        )

        # request context is OUTERMOST and unconditional: every request
        # gets a bound TraceContext (child of the caller's traceparent, or
        # a fresh root) + request id, so engine timelines and log lines
        # correlate even with no tracer installed
        middlewares = [request_context_middleware]
        # tracing wraps OUTSIDE error mapping so spans observe the final
        # mapped status (a 404 must be a clean span, not an exception span)
        if get_tracer() is not None:
            middlewares.append(tracing_middleware)
        middlewares.append(error_middleware)
        # lifecycle sits directly inside error mapping: a draining replica
        # must reject before shedding counts the request or the deadline
        # budget is parsed (readiness red / admission 503 — /admin routes
        # and liveness keep answering)
        if self.lifecycle is not None:
            middlewares.append(lifecycle_middleware(self.lifecycle))
        # shedding sits inside error mapping but before deadline parsing:
        # a shed request must cost nothing beyond the depth read
        if self.shedder.enabled:
            middlewares.append(
                shedding_middleware(self.shedder, self._total_queue_depth)
            )
        middlewares.append(deadline_middleware)
        if self.enable_latency_logging:
            middlewares.append(timing_middleware)
        app = web.Application(middlewares=middlewares, client_max_size=1024**3)
        app.router.add_get("/", root_handler)
        app.router.add_get("/metrics", metrics_handler)
        V1Endpoints(self.dataplane, self.model_repository_extension).register(app)
        V2Endpoints(self.dataplane, self.model_repository_extension).register(app)
        # OpenAI + timeseries heads are registered lazily so pure-predictive
        # servers never import transformers/pydantic generative types.
        from ..openai.endpoints import register_openai_routes
        from ..timeseries import TimeSeriesEndpoints

        register_openai_routes(app, self.dataplane)
        TimeSeriesEndpoints(self.dataplane.model_registry).register(app)
        from ..pd import PDEndpoints

        PDEndpoints(self.dataplane.model_registry).register(app)
        app.router.add_get(
            "/v1/internal/scheduler/state", self._scheduler_state_handler
        )
        # cross-replica KV page server (kvstore/peer.py wire contract)
        app.router.add_get(
            PAGE_ROUTE + "/{digest}", self._peer_page_handler
        )
        if self.lifecycle is not None:
            register_admin_routes(app, self.lifecycle, on_drain=self.on_drain)
        # observability introspection (docs/observability.md): rolling
        # TTFT/ITL/step percentiles + on-demand jax.profiler capture
        from ...observability import register_observability_routes

        register_observability_routes(
            app, self.dataplane.model_registry, profiler=self.profiler
        )
        return app

    def _total_queue_depth(self) -> int:
        """Aggregate engine admission queue depth — the load-shedding
        watermark signal (mirrors what /v1/internal/scheduler/state
        advertises to the EPP)."""
        depth = 0
        for model in self.dataplane.model_registry.get_models().values():
            engine = getattr(model, "engine", None)
            if engine is not None:
                depth += int(getattr(engine, "queue_depth", 0) or 0)
        return depth

    async def _scheduler_state_handler(self, request: web.Request) -> web.Response:
        """Per-replica load + prefix-cache snapshot consumed by the EPP
        endpoint picker (scheduler/picker.py).  Models without an engine
        report queue_depth 0 — the picker then degrades to round-robin."""
        models = {}
        for name, model in self.dataplane.model_registry.get_models().items():
            engine = getattr(model, "engine", None)
            if engine is not None and hasattr(engine, "scheduler_state"):
                models[name] = engine.scheduler_state()
        telemetry = [m.get("telemetry") or {} for m in models.values()]

        def worst(key: str):
            vals = [t.get(key) for t in telemetry if t.get(key) is not None]
            return max(vals) if vals else None

        agg = {
            "queue_depth": sum(m["queue_depth"] for m in models.values()),
            "inflight": sum(m.get("inflight", 0) for m in models.values()),
            "free_pages": sum(m["free_pages"] for m in models.values()),
            "models": models,
            # the EPP excludes DRAINING/TERMINATING backends from picks
            # (scheduler/picker.py), same contract as open breakers
            "lifecycle": (
                self.lifecycle.state if self.lifecycle is not None else READY
            ),
            # admission-shed counters + rolling latency windows: the
            # serving-native signals the autoscaler scales on
            # (kserve_tpu/autoscale/signals.py; docs/autoscaling.md)
            "shed": {
                "count": self.shedder.shed_count,
                "shedding": self.shedder.shedding,
            },
            "telemetry": {
                "ttft_p99_s": worst("ttft_p99_s"),
                "itl_p99_s": worst("itl_p99_s"),
            },
        }
        return web.json_response(agg)

    async def _peer_page_handler(self, request: web.Request) -> web.Response:
        """GET /v1/internal/kv/pages/{digest} — serve one persisted px-
        page to a peer replica in the self-verifying wire form
        (kvstore/peer.py encode_page).  Read-only and engine-loop-free:
        the page bytes come straight off the persistent store's files on
        an executor thread, bounded by the server's page semaphore.  404
        on miss (including an undecodable digest) — the peer degrades to
        re-prefill, so a miss here is never worth more than a miss."""
        try:
            digest = bytes.fromhex(request.match_info["digest"])
        except ValueError:
            return _error_response(404, "not a page digest")
        if self._peer_page_sem is None:
            self._peer_page_sem = asyncio.Semaphore(self.peer_page_concurrency)
        loop = asyncio.get_running_loop()
        async with self._peer_page_sem:
            for model in self.dataplane.model_registry.get_models().values():
                engine = getattr(model, "engine", None)
                reader = getattr(engine, "read_peer_page", None)
                if reader is None:
                    continue
                wire = await loop.run_in_executor(None, reader, digest)
                if wire is not None:
                    KV_PEER_PAGES_SERVED.inc()
                    return web.Response(
                        body=wire, content_type="application/octet-stream"
                    )
        return _error_response(404, "page not resident")

    async def start(self) -> None:
        app = self.create_application()
        # header-field limit raised past aiohttp's 8190 default: the
        # x-generation-checkpoint request header a resuming client carries
        # grows with prompt+generated length (lifecycle/checkpoint.py) and
        # a 400 'header too long' would turn every long-prompt resume into
        # a hard failure
        self._runner = web.AppRunner(
            app, access_log=None,
            max_field_size=CHECKPOINT_FIELD_SIZE_LIMIT,
            max_line_size=CHECKPOINT_FIELD_SIZE_LIMIT,
        )
        await self._runner.setup()
        site = web.TCPSite(
            self._runner, host="0.0.0.0", port=self.http_port,
            reuse_port=self.reuse_port, ssl_context=self.ssl_context,
        )
        await site.start()
        logger.info(
            "REST server listening on port %s%s", self.http_port,
            " (TLS)" if self.ssl_context is not None else "",
        )

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
