"""V1 (TFServing-style) REST protocol head.

Routes: GET /v1/models, GET /v1/models/{name}, POST /v1/models/{name}:predict,
POST /v1/models/{name}:explain.

Parity: reference python/kserve/kserve/protocol/rest/v1_endpoints.py:155-170.
"""

from __future__ import annotations

import json
from typing import TYPE_CHECKING

from aiohttp import web

from ...errors import ModelNotFound
from ...infer_type import InferResponse

if TYPE_CHECKING:
    from ..dataplane import DataPlane
    from ..model_repository_extension import ModelRepositoryExtension


class V1Endpoints:
    def __init__(self, dataplane: "DataPlane", model_repository_extension=None):
        self.dataplane = dataplane
        self.model_repository_extension = model_repository_extension

    async def models(self, request: web.Request) -> web.Response:
        models = list(self.dataplane.model_registry.get_models().keys())
        return web.json_response({"models": models})

    async def model_ready(self, request: web.Request) -> web.Response:
        model_name = request.match_info["model_name"]
        ready = await self.dataplane.model_ready(model_name)
        return web.json_response({"name": model_name, "ready": ready})

    async def predict(self, request: web.Request) -> web.Response:
        model_name = request.match_info["model_name"]
        headers = {k.lower(): v for k, v in request.headers.items()}
        body = await request.read()
        decoded, attributes = self.dataplane.decode(body, headers)
        response_headers: dict = {}
        response, res_headers = await self.dataplane.infer(
            model_name, decoded, headers, response_headers
        )
        encoded, extra_headers = self.dataplane.encode(
            model_name, response, headers, attributes
        )
        response_headers.update(extra_headers)
        response_headers.pop("content-length", None)
        if isinstance(encoded, (bytes, bytearray)):
            return web.Response(body=bytes(encoded), headers=response_headers)
        if isinstance(encoded, InferResponse):
            encoded, _ = encoded.to_rest()
        return web.Response(
            body=json.dumps(encoded).encode("utf-8"),
            content_type=response_headers.pop("content-type", None) or "application/json",
            headers=response_headers,
        )

    async def explain(self, request: web.Request) -> web.Response:
        model_name = request.match_info["model_name"]
        headers = {k.lower(): v for k, v in request.headers.items()}
        body = await request.read()
        decoded, attributes = self.dataplane.decode(body, headers)
        response_headers: dict = {}
        response, res_headers = await self.dataplane.explain(
            model_name, decoded, headers, response_headers
        )
        encoded, extra_headers = self.dataplane.encode(
            model_name, response, headers, attributes
        )
        response_headers.update(extra_headers)
        response_headers.pop("content-length", None)
        if isinstance(encoded, (bytes, bytearray)):
            return web.Response(body=bytes(encoded), headers=response_headers)
        return web.Response(
            body=json.dumps(encoded).encode("utf-8"),
            content_type=response_headers.pop("content-type", None) or "application/json",
            headers=response_headers,
        )

    def register(self, app: web.Application) -> None:
        app.router.add_get("/v1/models", self.models)
        app.router.add_get("/v1/models/{model_name}", self.model_ready)
        app.router.add_post("/v1/models/{model_name}:predict", self.predict)
        app.router.add_post("/v1/models/{model_name}:explain", self.explain)
