"""V2 / Open Inference Protocol REST head, including the binary-tensor
extension (Inference-Header-Content-Length) and the model repository
extension (load/unload).

Parity: reference python/kserve/kserve/protocol/rest/v2_endpoints.py:237-302.
"""

from __future__ import annotations

import json
from typing import TYPE_CHECKING, Optional

from aiohttp import web

from ...errors import InvalidInput
from ...infer_type import InferRequest, InferResponse

if TYPE_CHECKING:
    from ..dataplane import DataPlane


class V2Endpoints:
    def __init__(self, dataplane: "DataPlane", model_repository_extension=None):
        self.dataplane = dataplane
        self.model_repository_extension = model_repository_extension

    async def metadata(self, request: web.Request) -> web.Response:
        return web.json_response(self.dataplane.metadata())

    async def live(self, request: web.Request) -> web.Response:
        status = await self.dataplane.live()
        live = status["status"] == "alive"
        # non-2xx on wedge: kubelet httpGet probes key off the status code
        return web.json_response({"live": live},
                                 status=200 if live else 503)

    async def ready(self, request: web.Request) -> web.Response:
        ready = await self.dataplane.ready()
        if not ready:
            return web.json_response({"ready": False}, status=503)
        return web.json_response({"ready": True})

    async def model_metadata(self, request: web.Request) -> web.Response:
        model_name = request.match_info["model_name"]
        metadata = await self.dataplane.model_metadata(model_name)
        return web.json_response(metadata)

    async def model_ready(self, request: web.Request) -> web.Response:
        model_name = request.match_info["model_name"]
        ready = await self.dataplane.model_ready(model_name)
        if not ready:
            return web.json_response({"name": model_name, "ready": False}, status=503)
        return web.json_response({"name": model_name, "ready": True})

    async def infer(self, request: web.Request) -> web.Response:
        model_name = request.match_info["model_name"]
        model_version = request.match_info.get("model_version")
        headers = {k.lower(): v for k, v in request.headers.items()}
        body = await request.read()
        json_length: Optional[int] = None
        if "inference-header-content-length" in headers:
            try:
                json_length = int(headers["inference-header-content-length"])
            except ValueError:
                raise InvalidInput("Inference-Header-Content-Length must be an integer")
        infer_request, attributes = self.dataplane.decode(
            body, headers, json_length=json_length, model_name=model_name
        )
        if isinstance(infer_request, dict):
            infer_request = InferRequest.from_dict(infer_request, model_name=model_name)
        if model_version:
            infer_request.model_version = model_version
        response_headers: dict = {}
        response, _ = await self.dataplane.infer(
            model_name, infer_request, headers, response_headers
        )
        if isinstance(response, InferResponse):
            res, res_json_length = response.to_rest(infer_request.request_outputs)
        else:
            res, res_json_length = response, None
        response_headers.pop("content-length", None)
        if res_json_length is not None:
            response_headers["inference-header-content-length"] = str(res_json_length)
            return web.Response(
                body=res, content_type="application/octet-stream", headers=response_headers
            )
        return web.Response(
            body=json.dumps(res).encode("utf-8"),
            content_type="application/json",
            headers=response_headers,
        )

    async def load(self, request: web.Request) -> web.Response:
        model_name = request.match_info["model_name"]
        await self.model_repository_extension.load(model_name)
        return web.json_response({"name": model_name, "load": True})

    async def unload(self, request: web.Request) -> web.Response:
        model_name = request.match_info["model_name"]
        await self.model_repository_extension.unload(model_name)
        return web.json_response({"name": model_name, "unload": True})

    def register(self, app: web.Application) -> None:
        app.router.add_get("/v2", self.metadata)
        app.router.add_get("/v2/health/live", self.live)
        app.router.add_get("/v2/health/ready", self.ready)
        app.router.add_get("/v2/models/{model_name}", self.model_metadata)
        app.router.add_get("/v2/models/{model_name}/ready", self.model_ready)
        app.router.add_post("/v2/models/{model_name}/infer", self.infer)
        app.router.add_post(
            "/v2/models/{model_name}/versions/{model_version}/infer", self.infer
        )
        if self.model_repository_extension is not None:
            app.router.add_post("/v2/repository/models/{model_name}/load", self.load)
            app.router.add_post("/v2/repository/models/{model_name}/unload", self.unload)
