"""Repository (load/unload) extension shared by the V2 REST and gRPC heads.

Parity: reference python/kserve/kserve/protocol/model_repository_extension.py.
"""

from __future__ import annotations

import asyncio

from ..errors import ModelNotFound
from ..model_repository import ModelRepository


class ModelRepositoryExtension:
    def __init__(self, model_registry: ModelRepository):
        self._model_registry = model_registry

    async def load(self, model_name: str) -> None:
        loaded = await asyncio.get_event_loop().run_in_executor(
            None, self._model_registry.load, model_name
        )
        if not loaded:
            raise ModelNotFound(model_name)

    async def unload(self, model_name: str) -> None:
        try:
            self._model_registry.unload(model_name)
        except KeyError:
            raise ModelNotFound(model_name)
