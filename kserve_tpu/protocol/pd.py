"""Prefill/decode disaggregation wire protocol.

A prefill-role generative server exposes `POST /v1/prefill/{model}`: body is
JSON `{"prompt_ids": [...], "params": {...SamplingParams fields...}}`, the
response is the raw KV bytes (application/octet-stream) with an `X-KV-Meta`
header carrying shape/dtype/first_token.  A decode-role server calls it via
`PrefillClient`, then continues generation from the transferred KV.

Parity: the KV-connector / disaggregated-serving contract of the reference
(pkg/apis/serving/v1alpha2/llm_inference_service_types.go:105-110,
llmisvc workload_kvcache reconciliation); the transfer rides DCN as one
HTTP round-trip per request instead of a sidecar connector.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Tuple

import numpy as np

from ..engine.sampling import SamplingParams
from ..errors import InvalidInput
from ..logging import logger

KV_META_HEADER = "X-KV-Meta"
# bump when the on-wire KV axis order changes (kvcache.py layout)
KV_WIRE_LAYOUT = "page-major-v2"


def serialize_kv(kv: np.ndarray, first_token: int) -> Tuple[str, bytes]:
    """(meta-json, payload) for one sequence's KV [L, P, 2, n_kv, ps, d]."""
    meta = {
        "shape": list(kv.shape),
        "dtype": str(kv.dtype),
        "first_token": int(first_token),
        # wire-layout version: a mixed-version P/D pair must fail loudly,
        # not scatter axis-swapped KV that happens to pass the shape check
        "layout": KV_WIRE_LAYOUT,
    }
    return json.dumps(meta), kv.tobytes()


def deserialize_kv(meta_json: str, payload: bytes) -> Tuple[np.ndarray, int]:
    meta = json.loads(meta_json)
    layout = meta.get("layout")
    if layout != KV_WIRE_LAYOUT:
        raise RuntimeError(
            f"prefill peer sent KV wire layout {layout!r}, this server needs "
            f"{KV_WIRE_LAYOUT!r}; upgrade the P/D pair together"
        )
    name = meta["dtype"]
    if name == "bfloat16":
        import ml_dtypes

        dtype = np.dtype(ml_dtypes.bfloat16)
    else:
        dtype = np.dtype(name)
    kv = np.frombuffer(payload, dtype=dtype).reshape(meta["shape"])
    return kv, int(meta["first_token"])


def sampling_params_to_dict(params: SamplingParams) -> dict:
    return dataclasses.asdict(params)


def sampling_params_from_dict(data: dict) -> SamplingParams:
    fields = {f.name for f in dataclasses.fields(SamplingParams)}
    return SamplingParams(**{k: v for k, v in data.items() if k in fields})


class PDEndpoints:
    """Registers the prefill route for models exposing `handle_prefill`."""

    def __init__(self, model_registry):
        self.model_registry = model_registry

    def register(self, app) -> None:
        app.router.add_post("/v1/prefill/{model_name}", self.prefill)

    async def prefill(self, request):
        from aiohttp import web

        name = request.match_info["model_name"]
        model = self.model_registry.get_model(name)
        if model is None or not hasattr(model, "handle_prefill"):
            raise InvalidInput(f"model {name!r} does not serve prefill")
        body = await request.json()
        prompt_ids = body.get("prompt_ids")
        if not isinstance(prompt_ids, list) or not prompt_ids:
            raise InvalidInput("prompt_ids must be a non-empty list")
        params = sampling_params_from_dict(body.get("params") or {})
        adapter = body.get("adapter")
        meta_json, payload = await model.handle_prefill(
            prompt_ids, params, adapter=adapter
        )
        return web.Response(
            body=payload,
            content_type="application/octet-stream",
            headers={KV_META_HEADER: meta_json},
        )


class PrefillClient:
    """Decode-side client for a prefill-role peer (one aiohttp session,
    created lazily inside the server event loop)."""

    def __init__(self, base_url: str, timeout_s: float = 120.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self._session = None

    async def _get_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout_s)
            )
        return self._session

    async def prefill(
        self, model_name: str, prompt_ids, params: SamplingParams,
        adapter: Optional[str] = None,
    ) -> Tuple[np.ndarray, int]:
        """Returns (kv [L, P, 2, n_kv, ps, d], first_token)."""
        session = await self._get_session()
        url = f"{self.base_url}/v1/prefill/{model_name}"
        async with session.post(
            url,
            json={
                "prompt_ids": list(prompt_ids),
                "params": sampling_params_to_dict(params),
                "adapter": adapter,
            },
        ) as resp:
            if resp.status != 200:
                text = await resp.text()
                raise RuntimeError(f"prefill peer {url} -> {resp.status}: {text[:200]}")
            meta_json = resp.headers.get(KV_META_HEADER)
            if not meta_json:
                raise RuntimeError(f"prefill peer {url} response missing {KV_META_HEADER}")
            payload = await resp.read()
        return deserialize_kv(meta_json, payload)

    async def close(self):
        if self._session is not None:
            await self._session.close()
            self._session = None
