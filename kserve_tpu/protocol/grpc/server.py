"""grpc.aio server hosting the Open Inference Protocol service.

Parity: reference python/kserve/kserve/protocol/grpc/server.py.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional

import grpc

from ...logging import logger
from .servicer import InferenceServicer, add_inference_servicer_to_server

if TYPE_CHECKING:
    from ..dataplane import DataPlane
    from ..model_repository_extension import ModelRepositoryExtension

MAX_GRPC_MESSAGE_LENGTH = 8388608  # 8 MiB, matching the reference default


class GRPCServer:
    def __init__(
        self,
        port: int,
        data_plane: "DataPlane",
        model_repository_extension: "ModelRepositoryExtension" = None,
        kwargs: Optional[dict] = None,
    ):
        self._port = port
        self._data_plane = data_plane
        self._mre = model_repository_extension
        self._server: Optional[grpc.aio.Server] = None
        self._kwargs = kwargs or {}

    async def start(self, max_workers: int = 10) -> None:
        options = self._kwargs.get(
            "options",
            [
                ("grpc.max_send_message_length", MAX_GRPC_MESSAGE_LENGTH),
                ("grpc.max_receive_message_length", MAX_GRPC_MESSAGE_LENGTH),
            ],
        )
        self._server = grpc.aio.server(options=options)
        servicer = InferenceServicer(self._data_plane, self._mre)
        add_inference_servicer_to_server(servicer, self._server)
        listen_addr = f"[::]:{self._port}"
        self._server.add_insecure_port(listen_addr)
        logger.info("gRPC server listening on %s", listen_addr)
        await self._server.start()
        await self._server.wait_for_termination()

    async def stop(self, sig: Optional[int] = None) -> None:
        if self._server is not None:
            await self._server.stop(grace=10)
            self._server = None
