"""gRPC Open Inference Protocol servicer bridging to the DataPlane.

The image has protoc but no grpc python plugin, so instead of generated
`*_pb2_grpc` stubs the service is wired with
`grpc.method_handlers_generic_handler` — identical wire behaviour, one less
codegen step.

Parity: reference python/kserve/kserve/protocol/grpc/servicer.py (ModelInfer
bridging at :109).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import grpc

from ...errors import InferenceError, InvalidInput, ModelNotFound, ModelNotReady
from ...infer_type import InferRequest, InferResponse
from . import open_inference_pb2 as pb

if TYPE_CHECKING:
    from ..dataplane import DataPlane
    from ..model_repository_extension import ModelRepositoryExtension

SERVICE_NAME = "inference.GRPCInferenceService"


def to_grpc_headers(context: grpc.aio.ServicerContext) -> dict:
    return {k: v for k, v in (context.invocation_metadata() or [])}


class InferenceServicer:
    def __init__(
        self,
        data_plane: "DataPlane",
        model_repository_extension: "ModelRepositoryExtension" = None,
    ):
        self._data_plane = data_plane
        self._mre = model_repository_extension

    @staticmethod
    async def _abort(context, code: grpc.StatusCode, details: str):
        await context.abort(code, details)

    async def ServerLive(self, request, context) -> pb.ServerLiveResponse:
        status = await self._data_plane.live()
        return pb.ServerLiveResponse(live=status["status"] == "alive")

    async def ServerReady(self, request, context) -> pb.ServerReadyResponse:
        return pb.ServerReadyResponse(ready=await self._data_plane.ready())

    async def ModelReady(self, request, context) -> pb.ModelReadyResponse:
        try:
            ready = await self._data_plane.model_ready(request.name)
            return pb.ModelReadyResponse(ready=ready)
        except ModelNotFound as e:
            await self._abort(context, grpc.StatusCode.NOT_FOUND, e.reason)

    async def ServerMetadata(self, request, context) -> pb.ServerMetadataResponse:
        metadata = self._data_plane.metadata()
        return pb.ServerMetadataResponse(
            name=metadata["name"],
            version=metadata["version"],
            extensions=metadata["extensions"],
        )

    async def ModelMetadata(self, request, context) -> pb.ModelMetadataResponse:
        try:
            metadata = await self._data_plane.model_metadata(request.name)
            return pb.ModelMetadataResponse(
                name=metadata["name"],
                platform=metadata["platform"],
                inputs=[
                    pb.ModelMetadataResponse.TensorMetadata(
                        name=t.get("name", ""),
                        datatype=t.get("datatype", ""),
                        shape=t.get("shape", []),
                    )
                    for t in metadata.get("inputs", [])
                ],
                outputs=[
                    pb.ModelMetadataResponse.TensorMetadata(
                        name=t.get("name", ""),
                        datatype=t.get("datatype", ""),
                        shape=t.get("shape", []),
                    )
                    for t in metadata.get("outputs", [])
                ],
            )
        except ModelNotFound as e:
            await self._abort(context, grpc.StatusCode.NOT_FOUND, e.reason)

    async def ModelInfer(self, request, context) -> pb.ModelInferResponse:
        headers = to_grpc_headers(context)
        try:
            infer_request = InferRequest.from_grpc(request)
            response, _ = await self._data_plane.infer(
                model_name=request.model_name, request=infer_request, headers=headers
            )
            if isinstance(response, InferResponse):
                return response.to_grpc()
            if isinstance(response, pb.ModelInferResponse):
                return response
            raise InvalidInput(
                f"model {request.model_name} returned {type(response).__name__}, "
                "expected InferResponse for gRPC"
            )
        except InvalidInput as e:
            await self._abort(context, grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except ModelNotFound as e:
            await self._abort(context, grpc.StatusCode.NOT_FOUND, e.reason)
        except ModelNotReady as e:
            await self._abort(context, grpc.StatusCode.UNAVAILABLE, e.error_msg)
        except InferenceError as e:
            await self._abort(context, grpc.StatusCode.INTERNAL, str(e))

    async def RepositoryModelLoad(self, request, context) -> pb.RepositoryModelLoadResponse:
        try:
            await self._mre.load(request.model_name)
            return pb.RepositoryModelLoadResponse(model_name=request.model_name, isLoaded=True)
        except ModelNotFound as e:
            await self._abort(context, grpc.StatusCode.NOT_FOUND, e.reason)

    async def RepositoryModelUnload(self, request, context) -> pb.RepositoryModelUnloadResponse:
        try:
            await self._mre.unload(request.model_name)
            return pb.RepositoryModelUnloadResponse(
                model_name=request.model_name, isUnloaded=True
            )
        except ModelNotFound as e:
            await self._abort(context, grpc.StatusCode.NOT_FOUND, e.reason)


_METHODS = {
    "ServerLive": (pb.ServerLiveRequest, pb.ServerLiveResponse),
    "ServerReady": (pb.ServerReadyRequest, pb.ServerReadyResponse),
    "ModelReady": (pb.ModelReadyRequest, pb.ModelReadyResponse),
    "ServerMetadata": (pb.ServerMetadataRequest, pb.ServerMetadataResponse),
    "ModelMetadata": (pb.ModelMetadataRequest, pb.ModelMetadataResponse),
    "ModelInfer": (pb.ModelInferRequest, pb.ModelInferResponse),
    "RepositoryModelLoad": (pb.RepositoryModelLoadRequest, pb.RepositoryModelLoadResponse),
    "RepositoryModelUnload": (pb.RepositoryModelUnloadRequest, pb.RepositoryModelUnloadResponse),
}


def add_inference_servicer_to_server(servicer: InferenceServicer, server) -> None:
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req.FromString,
            response_serializer=res.SerializeToString,
        )
        for name, (req, res) in _METHODS.items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


def build_stub_multicallables(channel: grpc.aio.Channel) -> dict:
    """Client-side: method name -> unary_unary multicallable (used by
    InferenceGRPCClient; replaces the generated Stub class)."""
    return {
        name: channel.unary_unary(
            f"/{SERVICE_NAME}/{name}",
            request_serializer=req.SerializeToString,
            response_deserializer=res.FromString,
        )
        for name, (req, res) in _METHODS.items()
    }
