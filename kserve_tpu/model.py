"""Model lifecycle + request flow.

`Model` is the user-facing base class: subclass it, override `load()` and
`predict()` (and optionally `preprocess`/`postprocess`/`explain`), register it
with a `ModelServer`.  `__call__` runs the staged pipeline with per-stage
Prometheus timing.  When `predictor_config.predictor_host` is set the model
acts as a transformer: `predict` forwards to a remote predictor over REST or
gRPC.

Parity: reference python/kserve/kserve/model.py (Model.__call__ at :197,
_http_predict :385, _grpc_predict :405); rebuilt on httpx/grpc.aio with the
same stage semantics.
"""

from __future__ import annotations

import inspect
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Dict, Optional, Union

from .errors import InvalidInput
from .infer_type import InferRequest, InferResponse
from .logging import trace_logger
from .metrics import (
    EXPLAIN_HIST_TIME,
    POST_HIST_TIME,
    PRE_HIST_TIME,
    PREDICT_HIST_TIME,
    get_labels,
)

PREDICTOR_HOST_SUFFIX = "-predictor"


class ModelType(Enum):
    EXPLAINER = 1
    PREDICTOR = 2


class InferenceVerb(Enum):
    EXPLAIN = 1
    PREDICT = 2


class PredictorProtocol(Enum):
    REST_V1 = "v1"
    REST_V2 = "v2"
    GRPC_V2 = "grpc-v2"


def get_latency_ms(start: float, end: float) -> float:
    return round((end - start) * 1000, 9)


def is_v2(protocol: PredictorProtocol) -> bool:
    return protocol != PredictorProtocol.REST_V1


@dataclass
class PredictorConfig:
    """Where (and how) a transformer forwards to its predictor."""

    predictor_host: str = ""
    predictor_protocol: str = PredictorProtocol.REST_V1.value
    predictor_use_ssl: bool = False
    predictor_request_timeout_seconds: int = 600
    predictor_request_retries: int = 0
    predictor_health_check: bool = False
    extra_headers: Dict[str, str] = field(default_factory=dict)


class BaseModel:
    """Minimal lifecycle every servable object implements."""

    def __init__(self, name: str):
        self.name = name
        self.ready: bool = False
        self.engine_paused: bool = False

    async def healthy(self) -> bool:
        """Liveness beyond `ready` — engine models override to reflect the
        health of their background loop."""
        return self.ready

    async def live(self) -> bool:
        """Process liveness: False means the pod should be RESTARTED (vs
        healthy/ready which gate traffic).  Engine models return False once
        their device loop is wedged (a fetch blew its deadline)."""
        return True

    def load(self) -> bool:
        """Synchronously load weights/artifacts; set and return `self.ready`."""
        self.ready = True
        return self.ready

    def start(self) -> None:
        """Hook called when the server starts."""

    def stop(self) -> None:
        """Hook called when the server shuts down."""

    def start_engine(self) -> None:
        """Engine models (continuous-batching generative runtimes) override to
        launch their background decode loop inside the server's event loop."""


class Model(BaseModel):
    def __init__(
        self,
        name: str,
        predictor_config: Optional[PredictorConfig] = None,
        return_response_headers: bool = False,
    ):
        super().__init__(name)
        self.predictor_config = predictor_config
        self.return_response_headers = return_response_headers
        self._rest_client = None
        self._grpc_client = None

    # ---------- config helpers ----------

    @property
    def predictor_host(self) -> str:
        return self.predictor_config.predictor_host if self.predictor_config else ""

    @property
    def protocol(self) -> str:
        return (
            self.predictor_config.predictor_protocol
            if self.predictor_config
            else PredictorProtocol.REST_V1.value
        )

    def _predict_url(self, payload) -> str:
        scheme = "https" if self.predictor_config.predictor_use_ssl else "http"
        host = self.predictor_config.predictor_host
        if self.protocol == PredictorProtocol.REST_V1.value:
            return f"{scheme}://{host}/v1/models/{self.name}:predict"
        return f"{scheme}://{host}/v2/models/{self.name}/infer"

    def _explain_url(self) -> str:
        scheme = "https" if self.predictor_config.predictor_use_ssl else "http"
        host = self.predictor_config.predictor_host
        return f"{scheme}://{host}/v1/models/{self.name}:explain"

    # ---------- request pipeline ----------

    async def __call__(
        self,
        body: Union[Dict, bytes, InferRequest],
        verb: InferenceVerb = InferenceVerb.PREDICT,
        headers: Optional[Dict[str, str]] = None,
        response_headers: Optional[Dict[str, str]] = None,
    ):
        request_id = headers.get("x-request-id", "N.A.") if headers else "N.A."

        with PRE_HIST_TIME.labels(**get_labels(self.name)).time():
            t0 = time.perf_counter()
            payload = await _maybe_await(self.preprocess(body, headers))
            t1 = time.perf_counter()
        payload = self.validate(payload)

        if verb == InferenceVerb.EXPLAIN:
            with EXPLAIN_HIST_TIME.labels(**get_labels(self.name)).time():
                t2 = time.perf_counter()
                response = await _maybe_await(self.explain(payload, headers))
                t3 = time.perf_counter()
            trace_logger.info(
                "requestId: %s, preprocess_ms: %s, explain_ms: %s",
                request_id,
                get_latency_ms(t0, t1),
                get_latency_ms(t2, t3),
            )
        else:
            with PREDICT_HIST_TIME.labels(**get_labels(self.name)).time():
                t2 = time.perf_counter()
                response = await _maybe_await(
                    _call_with_optional_headers(self.predict, payload, headers, response_headers)
                )
                t3 = time.perf_counter()
            with POST_HIST_TIME.labels(**get_labels(self.name)).time():
                t4 = time.perf_counter()
                response = await _maybe_await(
                    _call_with_optional_headers(
                        self.postprocess, response, headers, response_headers
                    )
                )
                t5 = time.perf_counter()
            trace_logger.info(
                "requestId: %s, preprocess_ms: %s, predict_ms: %s, postprocess_ms: %s",
                request_id,
                get_latency_ms(t0, t1),
                get_latency_ms(t2, t3),
                get_latency_ms(t4, t5),
            )
        return response

    def validate(self, payload):
        if isinstance(payload, (InferRequest, InferResponse)):
            return payload
        if isinstance(payload, dict):
            if self.protocol == PredictorProtocol.REST_V1.value:
                if "instances" in payload and not isinstance(payload["instances"], list):
                    raise InvalidInput('Expected "instances" to be a list')
            elif "inputs" in payload and not isinstance(payload["inputs"], list):
                raise InvalidInput('Expected "inputs" to be a list')
        return payload

    # ---------- stages (override points) ----------

    async def preprocess(self, payload, headers: Optional[Dict[str, str]] = None):
        return payload

    async def predict(self, payload, headers: Optional[Dict[str, str]] = None, response_headers=None):
        """Default behaviour: transformer mode (forward to predictor_host)."""
        if not self.predictor_host:
            raise NotImplementedError("Could not find predictor_host.")
        if self.protocol == PredictorProtocol.GRPC_V2.value:
            return await self._grpc_predict(payload, headers)
        return await self._http_predict(payload, headers)

    async def explain(self, payload, headers: Optional[Dict[str, str]] = None):
        if not self.predictor_host:
            raise NotImplementedError("Could not find predictor_host.")
        from .inference_client import InferenceRESTClient, RESTConfig

        if self._rest_client is None:
            self._rest_client = InferenceRESTClient(
                RESTConfig(
                    protocol=self.protocol,
                    timeout=self.predictor_config.predictor_request_timeout_seconds,
                    retries=self.predictor_config.predictor_request_retries,
                )
            )
        return await self._rest_client.explain(self._explain_url(), data=payload, headers=headers)

    async def postprocess(self, result, headers: Optional[Dict[str, str]] = None, response_headers=None):
        return result

    # ---------- transformer forwarding ----------

    async def _http_predict(self, payload, headers=None):
        from .inference_client import InferenceRESTClient, RESTConfig

        if self._rest_client is None:
            self._rest_client = InferenceRESTClient(
                RESTConfig(
                    protocol=self.protocol,
                    timeout=self.predictor_config.predictor_request_timeout_seconds,
                    retries=self.predictor_config.predictor_request_retries,
                )
            )
        predict_headers = dict(self.predictor_config.extra_headers) if self.predictor_config else {}
        if headers:
            for h in ("x-request-id", "x-b3-traceid"):
                if h in headers:
                    predict_headers[h] = headers[h]
            if headers.get("content-type", "").startswith("application/cloudevents+json"):
                predict_headers["content-type"] = "application/json"
        return await self._rest_client.infer(
            self._predict_url(payload), data=payload, headers=predict_headers, model_name=self.name
        )

    async def _grpc_predict(self, payload: InferRequest, headers=None):
        from .inference_client import InferenceGRPCClient

        if self._grpc_client is None:
            self._grpc_client = InferenceGRPCClient(
                url=self.predictor_host,
                use_ssl=self.predictor_config.predictor_use_ssl,
                timeout=self.predictor_config.predictor_request_timeout_seconds,
            )
        meta = []
        if headers:
            for h in ("x-request-id", "x-b3-traceid"):
                if h in headers:
                    meta.append((h, headers[h]))
        return await self._grpc_client.infer(payload, headers=meta)

    def get_input_types(self) -> list:
        return []

    def get_output_types(self) -> list:
        return []


async def _maybe_await(value):
    if inspect.isawaitable(value):
        return await value
    return value


def _call_with_optional_headers(fn: Callable, payload, headers, response_headers):
    """Call a stage fn, passing response_headers only if its signature takes
    it — keeps simple user overrides (payload, headers) working."""
    try:
        sig = inspect.signature(fn)
        if "response_headers" in sig.parameters:
            return fn(payload, headers, response_headers=response_headers)
    except (ValueError, TypeError):
        pass
    return fn(payload, headers)
