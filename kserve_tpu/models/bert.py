"""BERT-family encoder in functional JAX: embeddings, rerank (cross-encoder),
sequence classification, fill-mask.

Role parity: the reference huggingfaceserver encoder path
(python/huggingfaceserver/huggingfaceserver/encoder_model.py:71 — BERT-style
tasks at :402-687) runs torch on CPU/GPU; here the whole encoder is one
jitted XLA program with bucketed sequence lengths.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.norms import layer_norm

Params = Dict[str, Any]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    num_labels: int = 2
    dtype: str = "float32"

    @staticmethod
    def tiny(**overrides) -> "BertConfig":
        base = dict(
            vocab_size=128,
            hidden_size=32,
            num_hidden_layers=2,
            num_attention_heads=4,
            intermediate_size=64,
            max_position_embeddings=64,
        )
        base.update(overrides)
        return BertConfig(**base)

    @staticmethod
    def from_hf_config(path_or_dict) -> "BertConfig":
        if isinstance(path_or_dict, str):
            with open(path_or_dict) as f:
                cfg = json.load(f)
        else:
            cfg = dict(path_or_dict)
        return BertConfig(
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            num_hidden_layers=cfg["num_hidden_layers"],
            num_attention_heads=cfg["num_attention_heads"],
            intermediate_size=cfg["intermediate_size"],
            max_position_embeddings=cfg.get("max_position_embeddings", 512),
            type_vocab_size=cfg.get("type_vocab_size", 2),
            layer_norm_eps=cfg.get("layer_norm_eps", 1e-12),
        )


def init_params(config: BertConfig, rng: jax.Array, scale: float = 0.02) -> Params:
    h = config.hidden_size
    keys = iter(jax.random.split(rng, 8 * config.num_hidden_layers + 8))

    def dense(shape):
        return jax.random.normal(next(keys), shape, jnp.float32) * scale

    def ln():
        return {"weight": jnp.ones((h,)), "bias": jnp.zeros((h,))}

    layers = []
    for _ in range(config.num_hidden_layers):
        layers.append(
            {
                "q": {"w": dense((h, h)), "b": jnp.zeros((h,))},
                "k": {"w": dense((h, h)), "b": jnp.zeros((h,))},
                "v": {"w": dense((h, h)), "b": jnp.zeros((h,))},
                "o": {"w": dense((h, h)), "b": jnp.zeros((h,))},
                "attn_ln": ln(),
                "ffn_in": {"w": dense((h, config.intermediate_size)),
                           "b": jnp.zeros((config.intermediate_size,))},
                "ffn_out": {"w": dense((config.intermediate_size, h)), "b": jnp.zeros((h,))},
                "ffn_ln": ln(),
            }
        )
    return {
        "word_embeddings": dense((config.vocab_size, h)),
        "position_embeddings": dense((config.max_position_embeddings, h)),
        "token_type_embeddings": dense((config.type_vocab_size, h)),
        "embed_ln": ln(),
        "layers": layers,
        "pooler": {"w": dense((h, h)), "b": jnp.zeros((h,))},
        "classifier": {"w": dense((h, config.num_labels)), "b": jnp.zeros((config.num_labels,))},
        "mlm_transform": {"w": dense((h, h)), "b": jnp.zeros((h,))},
        "mlm_ln": ln(),
        "mlm_bias": jnp.zeros((config.vocab_size,)),
    }


def encode(
    params: Params,
    config: BertConfig,
    input_ids: jnp.ndarray,  # [B, T]
    attention_mask: jnp.ndarray,  # [B, T]
    token_type_ids: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Encoder stack -> hidden states [B, T, H]."""
    B, T = input_ids.shape
    h = config.hidden_size
    nh = config.num_attention_heads
    hd = h // nh
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(input_ids)
    x = (
        params["word_embeddings"][input_ids]
        + params["position_embeddings"][jnp.arange(T)][None]
        + params["token_type_embeddings"][token_type_ids]
    )
    x = layer_norm(x, params["embed_ln"]["weight"], params["embed_ln"]["bias"],
                   config.layer_norm_eps)
    mask_bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, -1e30)
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    for layer in params["layers"]:
        q = (x @ layer["q"]["w"] + layer["q"]["b"]).reshape(B, T, nh, hd)
        k = (x @ layer["k"]["w"] + layer["k"]["b"]).reshape(B, T, nh, hd)
        v = (x @ layer["v"]["w"] + layer["v"]["b"]).reshape(B, T, nh, hd)
        scores = jnp.einsum("bthd,bshd->bhts", q, k) * scale + mask_bias
        weights = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhts,bshd->bthd", weights, v).reshape(B, T, h)
        attn = attn @ layer["o"]["w"] + layer["o"]["b"]
        x = layer_norm(x + attn, layer["attn_ln"]["weight"], layer["attn_ln"]["bias"],
                       config.layer_norm_eps)
        ffn = jax.nn.gelu(x @ layer["ffn_in"]["w"] + layer["ffn_in"]["b"], approximate=False)
        ffn = ffn @ layer["ffn_out"]["w"] + layer["ffn_out"]["b"]
        x = layer_norm(x + ffn, layer["ffn_ln"]["weight"], layer["ffn_ln"]["bias"],
                       config.layer_norm_eps)
    return x


def embed(params, config, input_ids, attention_mask, normalize: bool = True) -> jnp.ndarray:
    """Mean-pooled sentence embeddings [B, H]."""
    hidden = encode(params, config, input_ids, attention_mask)
    mask = attention_mask[..., None].astype(hidden.dtype)
    pooled = (hidden * mask).sum(axis=1) / jnp.clip(mask.sum(axis=1), 1e-9)
    if normalize:
        pooled = pooled / jnp.clip(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12)
    return pooled


def classify(params, config, input_ids, attention_mask, token_type_ids=None) -> jnp.ndarray:
    """Sequence classification logits [B, num_labels] (CLS + pooler)."""
    hidden = encode(params, config, input_ids, attention_mask, token_type_ids)
    cls = jnp.tanh(hidden[:, 0] @ params["pooler"]["w"] + params["pooler"]["b"])
    return cls @ params["classifier"]["w"] + params["classifier"]["b"]


def fill_mask_logits(params, config, input_ids, attention_mask) -> jnp.ndarray:
    """MLM logits [B, T, vocab] (transform + tied decoder)."""
    hidden = encode(params, config, input_ids, attention_mask)
    t = jax.nn.gelu(
        hidden @ params["mlm_transform"]["w"] + params["mlm_transform"]["b"],
        approximate=False,
    )
    t = layer_norm(t, params["mlm_ln"]["weight"], params["mlm_ln"]["bias"],
                   config.layer_norm_eps)
    return t @ params["word_embeddings"].T + params["mlm_bias"]


# ---------------- HF checkpoint loading ----------------


def load_hf_weights(model_dir: str, config: BertConfig) -> Params:
    """Local BERT safetensors checkpoint -> param pytree (torch-free)."""
    from safetensors import safe_open

    tensors: Dict[str, np.ndarray] = {}
    for f in sorted(os.listdir(model_dir)):
        if f.endswith(".safetensors"):
            with safe_open(os.path.join(model_dir, f), framework="numpy") as sf:
                for name in sf.keys():
                    tensors[name.removeprefix("bert.")] = sf.get_tensor(name)

    def t(name, transpose=False):
        arr = tensors[name]
        return jnp.asarray(arr.T if transpose else arr, jnp.float32)

    def maybe(name, default, transpose=False):
        if name in tensors:
            return t(name, transpose)
        return default

    params: Params = {
        "word_embeddings": t("embeddings.word_embeddings.weight"),
        "position_embeddings": t("embeddings.position_embeddings.weight"),
        "token_type_embeddings": t("embeddings.token_type_embeddings.weight"),
        "embed_ln": {"weight": t("embeddings.LayerNorm.weight"),
                     "bias": t("embeddings.LayerNorm.bias")},
        "layers": [],
        "pooler": {
            "w": maybe("pooler.dense.weight", jnp.zeros((config.hidden_size, config.hidden_size)), True),
            "b": maybe("pooler.dense.bias", jnp.zeros((config.hidden_size,))),
        },
        "classifier": {
            "w": maybe("classifier.weight", jnp.zeros((config.hidden_size, config.num_labels)), True),
            "b": maybe("classifier.bias", jnp.zeros((config.num_labels,))),
        },
        "mlm_transform": {
            "w": maybe("cls.predictions.transform.dense.weight",
                       jnp.zeros((config.hidden_size, config.hidden_size)), True),
            "b": maybe("cls.predictions.transform.dense.bias", jnp.zeros((config.hidden_size,))),
        },
        "mlm_ln": {
            "weight": maybe("cls.predictions.transform.LayerNorm.weight",
                            jnp.ones((config.hidden_size,))),
            "bias": maybe("cls.predictions.transform.LayerNorm.bias",
                          jnp.zeros((config.hidden_size,))),
        },
        "mlm_bias": maybe("cls.predictions.bias", jnp.zeros((config.vocab_size,))),
    }
    for i in range(config.num_hidden_layers):
        p = f"encoder.layer.{i}."
        params["layers"].append(
            {
                "q": {"w": t(p + "attention.self.query.weight", True), "b": t(p + "attention.self.query.bias")},
                "k": {"w": t(p + "attention.self.key.weight", True), "b": t(p + "attention.self.key.bias")},
                "v": {"w": t(p + "attention.self.value.weight", True), "b": t(p + "attention.self.value.bias")},
                "o": {"w": t(p + "attention.output.dense.weight", True), "b": t(p + "attention.output.dense.bias")},
                "attn_ln": {"weight": t(p + "attention.output.LayerNorm.weight"),
                            "bias": t(p + "attention.output.LayerNorm.bias")},
                "ffn_in": {"w": t(p + "intermediate.dense.weight", True), "b": t(p + "intermediate.dense.bias")},
                "ffn_out": {"w": t(p + "output.dense.weight", True), "b": t(p + "output.dense.bias")},
                "ffn_ln": {"weight": t(p + "output.LayerNorm.weight"),
                           "bias": t(p + "output.LayerNorm.bias")},
            }
        )
    return params
