"""Sparse Mixture-of-Experts MLP (Mixtral-style) with expert parallelism.

Top-k softmax router + SwiGLU experts.  Experts live on a stacked weight
tensor [n_experts, ...] sharded over the `model` (or a dedicated `expert`)
mesh axis; compute is dense-per-expert with routing masks — static shapes,
no host-side token shuffling, XLA inserts the psum when expert outputs are
combined across shards.  (Capacity-based dispatch kicks in next round for
large expert counts; dense-masked compute is the right trade below ~16
experts at decode batch sizes.)

Role parity: vLLM's fused MoE path behind `--enable-expert-parallel`
(SURVEY.md §2.3 Expert parallel row).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp


@dataclass
class MoEConfig:
    n_experts: int = 8
    top_k: int = 2
    hidden_size: int = 64
    intermediate_size: int = 128


def init_moe_params(config: MoEConfig, rng: jax.Array, scale: float = 0.02,
                    dtype=jnp.float32) -> Dict[str, Any]:
    k = jax.random.split(rng, 4)
    E, h, f = config.n_experts, config.hidden_size, config.intermediate_size

    def dense(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

    return {
        "router": dense(k[0], (h, E)),
        "w_gate": dense(k[1], (E, h, f)),
        "w_up": dense(k[2], (E, h, f)),
        "w_down": dense(k[3], (E, f, h)),
    }


def moe_mlp(params: Dict[str, Any], x: jnp.ndarray, config: MoEConfig) -> jnp.ndarray:
    """x: [B, T, h] -> [B, T, h].  Dense-masked top-k routing."""
    B, T, h = x.shape
    E, top_k = config.n_experts, config.top_k
    logits = (x @ params["router"]).astype(jnp.float32)  # [B, T, E]
    weights, selected = jax.lax.top_k(logits, top_k)  # [B, T, k]
    weights = jax.nn.softmax(weights, axis=-1)
    # dense mask [B, T, E]: routing weight if selected else 0
    onehot = jax.nn.one_hot(selected, E, dtype=jnp.float32)  # [B, T, k, E]
    combine = jnp.einsum("btk,btke->bte", weights, onehot)
    # all experts compute (static shapes); outputs combined by routing weight
    gate = jax.nn.silu(jnp.einsum("bth,ehf->btef", x, params["w_gate"]))
    up = jnp.einsum("bth,ehf->btef", x, params["w_up"])
    expert_out = jnp.einsum("btef,efh->bteh", gate * up, params["w_down"])
    out = jnp.einsum("bteh,bte->bth", expert_out, combine.astype(expert_out.dtype))
    return out.astype(x.dtype)


def moe_param_pspecs():
    """Expert-parallel shardings: experts over the `model` axis (EP==TP axis
    on a single slice; a dedicated `expert` axis drops in the same way)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import MODEL_AXIS

    return {
        "router": P(),
        "w_gate": P(MODEL_AXIS, None, None),
        "w_up": P(MODEL_AXIS, None, None),
        "w_down": P(MODEL_AXIS, None, None),
    }
