"""Int8 weight-only quantization for the generative models.

Decode is weight-bandwidth-bound on TPU (every step streams the full
weight set from HBM), so int8 weights halve the bytes per step — and
halve the resident footprint, which is what lets an 8B-class model fit
a single 16-GB v5e chip next to its KV cache (bf16 8B alone is ~16 GB).

Scheme: symmetric per-output-channel int8.  A quantized weight is a
pytree dict ``{"q": int8 [in, out], "s": float32 [out]}``; the matmul
applies the scale AFTER the contraction (per-output scaling commutes
with the contraction), so the weight is read from HBM as int8 and the
dequant multiply fuses into the matmul epilogue — no bf16 weight copy
ever materializes.  Embeddings quantize per-row ([V, h] with s [V]),
which serves both the gather (row scale) and, for tied embeddings, the
transposed lm_head matmul (output-channel scale) with one tensor.

Parity: the reference delegates weight quantization to vLLM
(--quantization flag surfaced via huggingfaceserver); here it is a
first-class engine knob (EngineConfig.weight_quant) built on the same
per-channel pattern as the int8 KV cache (engine/kvcache.py scales).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

# layer-dict keys eligible for quantization ([in, out] linears)
LINEAR_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def dense(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """``x @ w`` for a plain or int8-quantized weight."""
    if is_quantized(w):
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


def quantize_array(w: jnp.ndarray, axis: int = 0) -> Dict[str, jnp.ndarray]:
    """Symmetric int8 over `axis` (the contraction axis); scales attach to
    the remaining (channel) axis."""
    w32 = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(w32), axis=axis) / 127.0
    s = jnp.maximum(s, 1e-12)
    q = jnp.clip(jnp.round(w32 / jnp.expand_dims(s, axis)), -127, 127)
    return {"q": q.astype(jnp.int8), "s": s}


def quantize_array_np(w: np.ndarray, axis: int = 0) -> Dict[str, np.ndarray]:
    """Host-side twin of quantize_array for the checkpoint loader — an 8B
    checkpoint must quantize tensor-by-tensor on the host, never staging
    the full bf16 pytree on device."""
    w32 = np.asarray(w, np.float32)
    s = np.abs(w32).max(axis=axis) / 127.0
    s = np.maximum(s, 1e-12)
    q = np.clip(np.round(w32 / np.expand_dims(s, axis)), -127, 127)
    return {"q": q.astype(np.int8), "s": s.astype(np.float32)}


def quantize_params(params: Dict[str, Any], config) -> Dict[str, Any]:
    """Quantize a loaded param pytree in place-shape (returns a new tree):
    all layer linears, plus lm_head (untied) or embed (tied — it plays the
    lm_head role transposed).  Norms, biases, routers and LoRA stacks stay
    in the compute dtype.  MoE expert stacks are not quantized yet."""
    if config.n_experts > 0:
        raise NotImplementedError("weight_quant over MoE experts")
    out = dict(params)
    out["layers"] = []
    for layer in params["layers"]:
        qlayer = dict(layer)
        for key in LINEAR_KEYS:
            if key in qlayer and not is_quantized(qlayer[key]):
                qlayer[key] = quantize_array(qlayer[key], axis=0)
        out["layers"].append(qlayer)
    if "lm_head" in params and not is_quantized(params["lm_head"]):
        out["lm_head"] = quantize_array(params["lm_head"], axis=0)
    elif config.tie_word_embeddings and not is_quantized(params["embed"]):
        out["embed"] = quantize_array(params["embed"], axis=1)  # s per row [V]
    return out


def embed_lookup(embed: Any, tokens: jnp.ndarray, dtype) -> jnp.ndarray:
    """Embedding gather for a plain or row-quantized embedding table."""
    if is_quantized(embed):
        rows = embed["q"][tokens].astype(dtype)
        return rows * embed["s"][tokens][..., None].astype(dtype)
    return embed[tokens].astype(dtype)


def tied_head_matmul(x: jnp.ndarray, embed: Any) -> jnp.ndarray:
    """``x @ embed.T`` for the tied lm_head; row scales become output-channel
    scales under the transpose."""
    if is_quantized(embed):
        return (x @ embed["q"].T.astype(x.dtype)) * embed["s"].astype(x.dtype)
    return x @ embed.T


def param_bytes(config, weight_quant: str = "none") -> int:
    """Analytic parameter footprint (bytes) — the arithmetic behind the
    single-chip-fit claim in the bench detail."""
    h, hd = config.hidden_size, config.head_dim
    nq, nkv, f = config.n_heads, config.n_kv_heads, config.intermediate_size
    per_layer = h * (nq * hd) + 2 * h * (nkv * hd) + (nq * hd) * h + 3 * h * f
    linears = config.n_layers * per_layer
    embed = config.vocab_size * h
    head = 0 if config.tie_word_embeddings else config.vocab_size * h
    norms = (2 * config.n_layers + 1) * h
    elt = 2  # bfloat16
    if weight_quant == "int8":
        scales = config.n_layers * (nq * hd + 2 * nkv * hd + h + 2 * f) * 4
        quantized = linears + head
        tied_embed = embed if config.tie_word_embeddings else 0
        if config.tie_word_embeddings:
            scales += config.vocab_size * 4
        plain_embed = 0 if config.tie_word_embeddings else embed
        return (quantized + tied_embed) * 1 + plain_embed * elt + norms * elt + scales
    return (linears + embed + head + norms) * elt
