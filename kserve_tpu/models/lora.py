"""Multi-adapter LoRA for the Llama family, batched S-LoRA style.

All registered adapters live on device as stacked tensors per layer per
projection — A: [n_adapters, in, r], B: [n_adapters, r, out] (alpha/r folded
into B at load).  A request selects its adapter with a per-slot id; the
forward pass applies

    delta = einsum('bth,ahr,aro,ba->bto', x, A, B, onehot(adapter_id))

so one compiled program serves any mix of adapters AND the base model in the
same continuous batch (id -1 -> all-zero one-hot -> exact zero delta).  No
per-request weight swapping, no recompiles, and the adapter math rides the
MXU as two small matmuls.

Parity: the reference's LoRA wiring (workload_lora.go, vLLM --enable-lora);
checkpoint format is HF PEFT (adapter_config.json +
adapter_model.safetensors).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# PEFT target_modules -> our projection names (layer dict keys)
_TARGET_MAP = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
    "gate_proj": "w_gate",
    "up_proj": "w_up",
    "down_proj": "w_down",
}
TARGETS = tuple(_TARGET_MAP.values())


def load_peft_adapter(path: str) -> Tuple[dict, Dict[int, Dict[str, tuple]]]:
    """Read one HF PEFT adapter dir.  Returns (config,
    {layer_index: {proj: (A [in, r], B [r, out])}}) with alpha/r pre-folded
    into B."""
    with open(os.path.join(path, "adapter_config.json")) as f:
        config = json.load(f)
    r = int(config["r"])
    alpha = float(config.get("lora_alpha", r))
    scale = alpha / r
    from safetensors import safe_open

    weights = os.path.join(path, "adapter_model.safetensors")
    tensors: Dict[str, np.ndarray] = {}
    with safe_open(weights, framework="numpy") as f:
        for name in f.keys():
            tensors[name] = f.get_tensor(name)
    layers: Dict[int, Dict[str, tuple]] = {}
    for name, arr in tensors.items():
        # base_model.model.model.layers.{i}.self_attn.q_proj.lora_A.weight
        parts = name.split(".")
        if "layers" not in parts or "weight" != parts[-1]:
            continue
        i = int(parts[parts.index("layers") + 1])
        proj_hf = parts[-3]
        ours = _TARGET_MAP.get(proj_hf)
        if ours is None:
            continue
        kind = parts[-2]  # lora_A | lora_B
        slot = layers.setdefault(i, {}).setdefault(ours, [None, None])
        if kind == "lora_A":
            slot[0] = arr.T  # PEFT stores [r, in] -> ours [in, r]
        elif kind == "lora_B":
            slot[1] = arr.T * scale  # [out, r] -> [r, out], fold alpha/r
    out: Dict[int, Dict[str, tuple]] = {}
    for i, projs in layers.items():
        out[i] = {}
        for proj, (A, B) in projs.items():
            if A is None or B is None:
                raise ValueError(
                    f"adapter {path}: {proj} in layer {i} missing lora_A or lora_B"
                )
            out[i][proj] = (A, B)
    return config, out


def stack_adapters(
    adapter_dirs: Dict[str, str],
    n_layers: int,
    dtype: str = "bfloat16",
) -> Tuple[Dict[str, int], List[Dict[str, Dict[str, jnp.ndarray]]]]:
    """Load and stack adapters into per-layer device tensors.

    Returns (name -> adapter id, per-layer {proj: {"A": [n, in, r_max],
    "B": [n, r_max, out]}}).  Ranks are zero-padded to the max — zero rows
    contribute exactly nothing.  Projections untouched by every adapter are
    omitted entirely (no dead compute)."""
    names = sorted(adapter_dirs)
    loaded = [load_peft_adapter(adapter_dirs[name])[1] for name in names]
    ids = {name: idx for idx, name in enumerate(names)}
    jdtype = jnp.dtype(dtype)

    per_layer: List[Dict[str, Dict[str, jnp.ndarray]]] = []
    for layer_idx in range(n_layers):
        layer_stack: Dict[str, Dict[str, jnp.ndarray]] = {}
        for proj in TARGETS:
            shapes = [
                adapter.get(layer_idx, {}).get(proj)
                for adapter in loaded
            ]
            present = [s for s in shapes if s is not None]
            if not present:
                continue
            in_dim = present[0][0].shape[0]
            out_dim = present[0][1].shape[1]
            r_max = max(ab[0].shape[1] for ab in present)
            A = np.zeros((len(loaded), in_dim, r_max), np.float32)
            B = np.zeros((len(loaded), r_max, out_dim), np.float32)
            for a_idx, ab in enumerate(shapes):
                if ab is None:
                    continue
                r = ab[0].shape[1]
                A[a_idx, :, :r] = ab[0]
                B[a_idx, :r, :] = ab[1]
            layer_stack[proj] = {
                "A": jnp.asarray(A, jdtype),
                "B": jnp.asarray(B, jdtype),
            }
        per_layer.append(layer_stack)
    return ids, per_layer


def lora_delta(
    lora: Dict[str, Dict[str, jnp.ndarray]],
    proj: str,
    x: jnp.ndarray,  # [B, T, in]
    onehot: Optional[jnp.ndarray],  # [B, n_adapters]
) -> jnp.ndarray:
    """Per-slot adapter delta for one projection, or None when no adapter
    touches it — None keeps the no-LoRA program literally unchanged (the
    caller skips the add at trace time).  Rows whose one-hot is all zero
    (base-model rows) get an exact-zero delta."""
    entry = lora.get(proj) if lora else None
    if entry is None or onehot is None:
        return None
    return jnp.einsum(
        "bth,ahr,aro,ba->bto", x, entry["A"], entry["B"], onehot.astype(x.dtype)
    )


def lora_pspecs(layer_stack: Dict[str, Dict[str, jnp.ndarray]]):
    """PartitionSpecs matching one layer's stack: B's output dim follows the
    projection's TP sharding (column-parallel projs shard out over `model`);
    A is replicated (rank dims are tiny)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import MODEL_AXIS

    col_parallel = {"wq", "wk", "wv", "w_gate", "w_up"}
    specs: Dict[str, Dict[str, Any]] = {}
    for proj in layer_stack:
        if proj in col_parallel:
            specs[proj] = {"A": P(), "B": P(None, None, MODEL_AXIS)}
        else:  # row-parallel (wo, w_down): input dim sharded over model
            specs[proj] = {"A": P(None, MODEL_AXIS, None), "B": P()}
    return specs
