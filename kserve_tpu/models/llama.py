"""Llama-family decoder in functional JAX (covers Llama 2/3, Mistral,
Qwen2, Qwen3 and TinyLlama-style variants via config knobs: GQA, RoPE
theta, qkv bias, per-head qk-norm, tied embeddings, optional logit
softcap).

Params are a plain pytree (nested dict of jnp arrays) so sharding is a
matching pytree of NamedShardings (parallel/sharding.py) and jit donation
works without framework indirection.  Two entry points:
- `prefill(params, tokens, valid_len, kv_pages, page_ids)` — causal
  self-attention over the prompt, writes KV pages, returns last-token logits.
- `decode_step(params, tokens, pos, kv_pages, page_table, seq_lens, active)`
  — one token per sequence against the paged cache.

Role parity: the model zoo the reference reaches through vLLM/HF
(python/huggingfaceserver); rebuilt TPU-first rather than wrapped.
"""

from __future__ import annotations

import json
import math
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.kvcache import (
    append_token_kv,
    write_chunk_kv_batch,
    write_prompt_kv_batch,
    write_ragged_kv,
)
from ..ops.attention import (
    causal_prefill_attention,
    chunked_prefill_attention,
    paged_attention,
    ragged_paged_attention,
)
from ..ops.norms import rms_norm, rms_norm_plus_one
from ..ops.rotary import apply_rope
from .lora import lora_delta
from .quant import (
    LINEAR_KEYS,
    dense,
    embed_lookup,
    quantize_array_np,
    tied_head_matmul,
)

Params = Dict[str, Any]


def _map_hidden_act(act) -> str:
    """HF activation name -> ours.  Loud on anything unimplemented: a
    silent silu substitution (e.g. for exact 'gelu') would produce wrong
    logits with no signal."""
    if act in (None, "silu", "swish"):
        return "silu"
    if act in ("gelu_pytorch_tanh", "gelu_tanh"):
        return "gelu_tanh"
    raise ValueError(f"unsupported hidden_act {act!r}")


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    head_dim: Optional[int] = None
    rope_theta: float = 10000.0
    rope_scaling: Optional[dict] = None  # HF rope_scaling (llama3/linear)
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    attention_bias: bool = False
    # per-head RMSNorm on q/k before rope (Qwen3-family)
    qk_norm: bool = False
    # ---- Gemma-2 family knobs (all default to Llama behavior) ----
    hidden_act: str = "silu"  # or "gelu_tanh" (GeGLU)
    norm_plus_one: bool = False  # RMSNorm multiplies by (1 + w)
    embed_scale: bool = False  # inputs scaled by sqrt(hidden_size)
    sandwich_norms: bool = False  # post-attn + post-ffn norms per layer
    attn_logit_softcap: float = 0.0  # tanh cap on ATTENTION scores
    query_pre_attn_scalar: Optional[float] = None  # attn scale = qpas**-0.5
    sliding_window: int = 0  # >0: window on layers marked sliding
    # per-layer attention kind; None = all full attention.  Tuple of
    # "sliding_attention"|"full_attention" (hashable: configs close over
    # jitted programs)
    layer_types: Optional[Tuple[str, ...]] = None
    # final-logit tanh cap (pre-existing knob)
    logit_softcap: float = 0.0
    # Mixture-of-Experts (Mixtral-style): n_experts == 0 => dense MLP.
    # Experts shard over the `model` mesh axis (expert parallelism).
    n_experts: int = 0
    n_experts_per_tok: int = 2
    dtype: str = "bfloat16"

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.n_heads
        if self.layer_types is not None:
            self.layer_types = tuple(self.layer_types)

    def layer_window(self, i: int) -> int:
        """Sliding-window width for layer i (0 = full attention)."""
        if self.sliding_window <= 0:
            return 0
        if self.layer_types is None:
            return self.sliding_window
        return (self.sliding_window
                if self.layer_types[i] == "sliding_attention" else 0)

    @property
    def attn_scale(self) -> Optional[float]:
        """Attention score scale override (None = 1/sqrt(head_dim))."""
        if self.query_pre_attn_scalar is None:
            return None
        return float(self.query_pre_attn_scalar) ** -0.5

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        """Small config for tests/CI meshes."""
        base = dict(
            vocab_size=512,
            hidden_size=64,
            intermediate_size=128,
            n_layers=2,
            n_heads=4,
            n_kv_heads=2,
            max_position_embeddings=256,
        )
        base.update(overrides)
        return LlamaConfig(**base)

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256,
            hidden_size=4096,
            intermediate_size=14336,
            n_layers=32,
            n_heads=32,
            n_kv_heads=8,
            rope_theta=500000.0,
            max_position_embeddings=8192,
        )

    @staticmethod
    def llama3_1b() -> "LlamaConfig":
        """Llama-3.2-1B-shaped config (bench-friendly on one v5e chip)."""
        return LlamaConfig(
            vocab_size=128256,
            hidden_size=2048,
            intermediate_size=8192,
            n_layers=16,
            n_heads=32,
            n_kv_heads=8,
            head_dim=64,
            rope_theta=500000.0,
            max_position_embeddings=8192,
            tie_word_embeddings=True,
        )

    @staticmethod
    def bench_1b() -> "LlamaConfig":
        """1B-class flagship with MXU-native head_dim=128 (the Pallas paged
        attention kernel requires 128-aligned heads; llama3_1b's d=64 takes
        the XLA fallback path until the packed-row kernel variant lands)."""
        return LlamaConfig(
            vocab_size=128256,
            hidden_size=2048,
            intermediate_size=8192,
            n_layers=16,
            n_heads=16,
            n_kv_heads=8,
            head_dim=128,
            rope_theta=500000.0,
            max_position_embeddings=8192,
            tie_word_embeddings=True,
        )

    @staticmethod
    def qwen3_0_6b() -> "LlamaConfig":
        """Qwen3-0.6B shape (qk-norm family; MXU-native head_dim=128)."""
        return LlamaConfig(
            vocab_size=151936,
            hidden_size=1024,
            intermediate_size=3072,
            n_layers=28,
            n_heads=16,
            n_kv_heads=8,
            head_dim=128,
            rope_theta=1000000.0,
            max_position_embeddings=32768,
            tie_word_embeddings=True,
            qk_norm=True,
            rms_norm_eps=1e-6,
        )

    @staticmethod
    def gemma2_2b() -> "LlamaConfig":
        """Gemma-2-2B shape (sandwich norms, GeGLU, softcaps, alternating
        4096-token sliding windows on even layers)."""
        return LlamaConfig(
            vocab_size=256000,
            hidden_size=2304,
            intermediate_size=9216,
            n_layers=26,
            n_heads=8,
            n_kv_heads=4,
            head_dim=256,
            rope_theta=10000.0,
            max_position_embeddings=8192,
            tie_word_embeddings=True,
            hidden_act="gelu_tanh",
            norm_plus_one=True,
            embed_scale=True,
            sandwich_norms=True,
            attn_logit_softcap=50.0,
            logit_softcap=30.0,
            query_pre_attn_scalar=256,
            sliding_window=4096,
            layer_types=tuple(
                "sliding_attention" if i % 2 == 0 else "full_attention"
                for i in range(26)),
            rms_norm_eps=1e-6,
        )

    @staticmethod
    def from_hf_config(path_or_dict) -> "LlamaConfig":
        """Map a HuggingFace config.json (LlamaForCausalLM/MistralForCausalLM/
        Qwen2ForCausalLM) onto LlamaConfig."""
        if isinstance(path_or_dict, str):
            with open(path_or_dict) as f:
                cfg = json.load(f)
        else:
            cfg = dict(path_or_dict)
        rope_scaling = cfg.get("rope_scaling")
        if rope_scaling is not None:
            # Validate eagerly: Llama-3.1/3.2 checkpoints rely on rope_type
            # "llama3" at every position; silently dropping an unsupported
            # variant would load but produce wrong logits.
            from ..ops.rotary import rope_frequencies

            rope_frequencies(
                cfg.get("head_dim") or cfg["hidden_size"] // cfg["num_attention_heads"],
                cfg.get("rope_theta", 10000.0),
                rope_scaling,
            )
        return LlamaConfig(
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            intermediate_size=(
                cfg["intermediate_size"] if "intermediate_size" in cfg
                else cfg["ffn_dim"]  # loud KeyError on unsupported configs
            ),
            n_layers=cfg["num_hidden_layers"],
            n_heads=cfg["num_attention_heads"],
            n_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
            head_dim=cfg.get("head_dim"),
            rope_theta=cfg.get("rope_theta", 10000.0),
            rope_scaling=rope_scaling,
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            max_position_embeddings=cfg.get("max_position_embeddings", 4096),
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
            attention_bias=cfg.get("attention_bias", False),
            # Qwen3 carries q_norm/k_norm weights per layer; model_type is
            # always present in real config.json, architectures often not
            qk_norm=(
                cfg.get("model_type") == "qwen3"
                or any("Qwen3" in a
                       for a in (cfg.get("architectures") or []))),
            # Gemma-2 family (model_type "gemma2")
            hidden_act=_map_hidden_act(
                cfg.get("hidden_act", cfg.get("hidden_activation"))),
            norm_plus_one=cfg.get("model_type") == "gemma2",
            embed_scale=cfg.get("model_type") == "gemma2",
            sandwich_norms=cfg.get("model_type") == "gemma2",
            attn_logit_softcap=cfg.get("attn_logit_softcapping") or 0.0,
            logit_softcap=cfg.get("final_logit_softcapping") or 0.0,
            query_pre_attn_scalar=cfg.get("query_pre_attn_scalar"),
            sliding_window=(
                cfg.get("sliding_window") or 0
                if cfg.get("model_type") == "gemma2" else 0),
            # raw hub config.json for Gemma-2 predates the layer_types
            # key (the alternation lived in modeling code: even layers
            # sliding); synthesize it so full-attention layers are never
            # silently windowed
            layer_types=(
                tuple(cfg["layer_types"]) if cfg.get("layer_types")
                else tuple(
                    "sliding_attention" if i % 2 == 0 else "full_attention"
                    for i in range(cfg["num_hidden_layers"]))
                if cfg.get("model_type") == "gemma2"
                and (cfg.get("sliding_window") or 0) > 0
                else None),
            # MixtralForCausalLM fields
            n_experts=cfg.get("num_local_experts", 0),
            n_experts_per_tok=cfg.get("num_experts_per_tok", 2),
        )


def init_params(config: LlamaConfig, rng: jax.Array, scale: float = 0.02,
                weight_quant: str = "none") -> Params:
    """Random-initialized parameter pytree (bench/tests; real serving loads
    checkpoints via load_hf_weights).

    weight_quant="int8" emits quantized leaves DIRECTLY (random int8 +
    constant scales matching `scale`'s distribution) — an 8B random init
    must never stage the bf16 tree on a 16-GB chip just to quantize it."""
    dtype = jnp.dtype(config.dtype)
    h, hd = config.hidden_size, config.head_dim
    nq, nkv = config.n_heads, config.n_kv_heads
    keys = jax.random.split(rng, config.n_layers + 2)

    def dense_f32(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

    def dense_q(key, shape, channel_axis=-1):
        # uniform int8 has std ~73; s maps that back onto N(0, scale)
        q = jax.random.randint(key, shape, -127, 128, jnp.int8)
        s_shape = (shape[channel_axis],)
        return {"q": q, "s": jnp.full(s_shape, scale / 73.0, jnp.float32)}

    quant = weight_quant == "int8"
    if quant and config.n_experts > 0:
        raise NotImplementedError("weight_quant over MoE experts")
    dense = (lambda key, shape: dense_q(key, shape)) if quant else dense_f32

    layers = []
    for i in range(config.n_layers):
        k = jax.random.split(keys[i], 8)
        norm_init = jnp.zeros if config.norm_plus_one else jnp.ones
        layer = {
            "attn_norm": norm_init((h,), dtype),
            "wq": dense(k[0], (h, nq * hd)),
            "wk": dense(k[1], (h, nkv * hd)),
            "wv": dense(k[2], (h, nkv * hd)),
            "wo": dense(k[3], (nq * hd, h)),
            "mlp_norm": norm_init((h,), dtype),
        }
        if config.n_experts > 0:
            E, f = config.n_experts, config.intermediate_size
            layer["router"] = dense(k[7], (h, E))
            layer["w_gate"] = dense(k[4], (E, h, f))
            layer["w_up"] = dense(k[5], (E, h, f))
            layer["w_down"] = dense(k[6], (E, f, h))
        else:
            layer["w_gate"] = dense(k[4], (h, config.intermediate_size))
            layer["w_up"] = dense(k[5], (h, config.intermediate_size))
            layer["w_down"] = dense(k[6], (config.intermediate_size, h))
        if config.attention_bias:
            layer["bq"] = jnp.zeros((nq * hd,), dtype)
            layer["bk"] = jnp.zeros((nkv * hd,), dtype)
            layer["bv"] = jnp.zeros((nkv * hd,), dtype)
        if config.qk_norm:
            layer["q_norm"] = jnp.ones((hd,), dtype)
            layer["k_norm"] = jnp.ones((hd,), dtype)
        if config.sandwich_norms:
            # Gemma norm weights init to ZERO ((1+w) multiplies by 1)
            layer["post_attn_norm"] = jnp.zeros((h,), dtype)
            layer["post_mlp_norm"] = jnp.zeros((h,), dtype)
        if config.sliding_window > 0:
            layer["attn_window"] = jnp.asarray(
                config.layer_window(i), jnp.int32)
        layers.append(layer)
    params: Params = {
        # tied quantized embeddings carry per-ROW scales (they serve as the
        # transposed lm_head); untied embeddings stay bf16 (gather-only)
        "embed": (
            dense_q(keys[-2], (config.vocab_size, h), channel_axis=0)
            if quant and config.tie_word_embeddings
            else dense_f32(keys[-2], (config.vocab_size, h))
        ),
        "final_norm": (jnp.zeros if config.norm_plus_one else jnp.ones)(
            (h,), dtype),
        "layers": layers,
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = dense(keys[-1], (h, config.vocab_size))
    return params


def _maybe_add(y: jnp.ndarray, delta) -> jnp.ndarray:
    # trace-time decision: the no-LoRA program is unchanged
    return y if delta is None else y + delta


def _qkv(layer: Params, x: jnp.ndarray, config: LlamaConfig, onehot=None):
    B, T, _ = x.shape
    lora = layer.get("lora")
    q = _maybe_add(dense(x, layer["wq"]), lora_delta(lora, "wq", x, onehot))
    k = _maybe_add(dense(x, layer["wk"]), lora_delta(lora, "wk", x, onehot))
    v = _maybe_add(dense(x, layer["wv"]), lora_delta(lora, "wv", x, onehot))
    if config.attention_bias:
        q = q + layer["bq"]
        k = k + layer["bk"]
        v = v + layer["bv"]
    q = q.reshape(B, T, config.n_heads, config.head_dim)
    k = k.reshape(B, T, config.n_kv_heads, config.head_dim)
    v = v.reshape(B, T, config.n_kv_heads, config.head_dim)
    if config.qk_norm:
        # Qwen3: per-head RMSNorm over head_dim before rope
        q = rms_norm(q, layer["q_norm"], config.rms_norm_eps)
        k = rms_norm(k, layer["k_norm"], config.rms_norm_eps)
    return q, k, v


def _mlp(layer: Params, x: jnp.ndarray, config: LlamaConfig, onehot=None) -> jnp.ndarray:
    if config.n_experts > 0:
        from .moe import MoEConfig, moe_mlp

        moe_cfg = MoEConfig(
            n_experts=config.n_experts,
            top_k=config.n_experts_per_tok,
            hidden_size=config.hidden_size,
            intermediate_size=config.intermediate_size,
        )
        return moe_mlp(layer, x, moe_cfg)
    lora = layer.get("lora")
    gate = _act(
        _maybe_add(dense(x, layer["w_gate"]), lora_delta(lora, "w_gate", x, onehot)),
        config,
    )
    up = _maybe_add(dense(x, layer["w_up"]), lora_delta(lora, "w_up", x, onehot))
    h = gate * up
    return _maybe_add(
        dense(h, layer["w_down"]), lora_delta(lora, "w_down", h, onehot)
    )


def _logits(params: Params, x: jnp.ndarray, config: LlamaConfig) -> jnp.ndarray:
    x = _norm(x, params["final_norm"], config)
    head = params.get("lm_head")
    if head is None:
        logits = tied_head_matmul(x, params["embed"]).astype(jnp.float32)
    else:
        logits = dense(x, head).astype(jnp.float32)
    if config.logit_softcap > 0.0:
        logits = jnp.tanh(logits / config.logit_softcap) * config.logit_softcap
    return logits


def _norm(x: jnp.ndarray, weight: jnp.ndarray, config: LlamaConfig) -> jnp.ndarray:
    """Config-dispatched RMSNorm: Gemma's (1+w) variant or the default."""
    if config.norm_plus_one:
        return rms_norm_plus_one(x, weight, config.rms_norm_eps)
    return rms_norm(x, weight, config.rms_norm_eps)


def _embed(params: Params, tokens: jnp.ndarray, config: LlamaConfig) -> jnp.ndarray:
    x = embed_lookup(params["embed"], tokens, jnp.dtype(config.dtype))
    if config.embed_scale:
        # Gemma scales embeddings by sqrt(hidden); the normalizer is cast
        # to the activation dtype first (HF parity)
        x = x * jnp.asarray(config.hidden_size ** 0.5, x.dtype)
    return x


def _act(x: jnp.ndarray, config: LlamaConfig) -> jnp.ndarray:
    if config.hidden_act == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _adapter_onehot(params: Params, adapter_ids, batch: int):
    """[B, n_adapters] one-hot from per-slot adapter ids (-1 -> all-zero row
    -> exact-zero delta -> base model); None when no adapters are loaded.
    Handles both layer layouts: the per-layer list and the pp-stacked dict
    (whose lora leaves carry a leading layer axis)."""
    layers = params["layers"]
    if isinstance(layers, dict):  # pp-stacked
        lora = layers.get("lora")
        if lora:
            n_a = next(iter(lora.values()))["A"].shape[1]  # [L, n, in, r]
            if adapter_ids is None:
                adapter_ids = jnp.full((batch,), -1, jnp.int32)
            return jax.nn.one_hot(adapter_ids, n_a, dtype=jnp.float32)
        return None
    for layer in layers:
        lora = layer.get("lora")
        if lora:
            n_a = next(iter(lora.values()))["A"].shape[0]
            if adapter_ids is None:
                adapter_ids = jnp.full((batch,), -1, jnp.int32)
            return jax.nn.one_hot(adapter_ids, n_a, dtype=jnp.float32)
    return None


def transformer_block(
    layer: Params,
    x: jnp.ndarray,  # [B, T, h]
    positions: jnp.ndarray,  # [B, T]
    valid_len: jnp.ndarray,  # [B]
    config: LlamaConfig,
    onehot=None,  # LoRA adapter one-hot (or None = base weights)
    attention_fn=None,  # (q, k, v, valid_len, softcap) -> attn
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One transformer block (prefill form, pre-cache): returns
    (x_out, k, v) — the caller scatters K/V into its pages (prefill) or
    discards them (the pipeline-parallel layer_fn).  The single source of
    the block math: prefill and parallel/pipeline.py both call this, so
    rope/softcap/LoRA changes cannot drift between them."""
    B, T = x.shape[0], x.shape[1]
    residual = x
    h = _norm(x, layer["attn_norm"], config)
    q, k, v = _qkv(layer, h, config, onehot)
    q = apply_rope(q, positions, config.rope_theta, config.rope_scaling)
    k = apply_rope(k, positions, config.rope_theta, config.rope_scaling)
    if attention_fn is None:
        attn = causal_prefill_attention(
            q, k, v, valid_len, config.attn_logit_softcap,
            scale=config.attn_scale, window=layer.get("attn_window"),
        )
    else:
        # pluggable path (SP ring attention); engines exclude it for
        # windowed/scaled configs at init
        attn = attention_fn(q, k, v, valid_len, config.attn_logit_softcap)
    attn_flat = attn.reshape(B, T, -1)
    attn = _maybe_add(
        dense(attn_flat, layer["wo"]),
        lora_delta(layer.get("lora"), "wo", attn_flat, onehot),
    )
    if config.sandwich_norms:
        attn = _norm(attn, layer["post_attn_norm"], config)
    x = residual + attn
    residual = x
    h = _norm(x, layer["mlp_norm"], config)
    out = _mlp(layer, h, config, onehot)
    if config.sandwich_norms:
        out = _norm(out, layer["post_mlp_norm"], config)
    return residual + out, k, v


def prefill(
    params: Params,
    config: LlamaConfig,
    tokens: jnp.ndarray,  # [B, T] padded prompt
    valid_len: jnp.ndarray,  # [B]
    kv_pages: List[jnp.ndarray],  # per layer [num_pages, 2, nkv, ps, d]
    page_ids: jnp.ndarray,  # [B, max_pages] pages owned by each sequence
    page_size: int,
    attention_fn=None,  # (q, k, v, valid_len, softcap) -> attn; SP engines
    # pass a shard_map-wrapped ring_attention here (parallel/ring_attention)
    adapter_ids: Optional[jnp.ndarray] = None,  # [B] LoRA ids (-1 = base)
) -> Tuple[jnp.ndarray, List[jnp.ndarray]]:
    """Process prompts, write their KV into the cache, return logits at the
    last valid token of each row: [B, vocab]."""
    # attention_fn=None flows through to transformer_block, whose default
    # branch passes scale= and window= — substituting the bare default here
    # would silently drop both (sliding-window layers would attend globally)
    B, T = tokens.shape
    onehot = _adapter_onehot(params, adapter_ids, B)
    positions = jnp.arange(T, dtype=jnp.int32)[None, :].repeat(B, axis=0)
    x = _embed(params, tokens, config)
    new_pages = []
    for layer, pages in zip(params["layers"], kv_pages):
        x, k, v = transformer_block(
            layer, x, positions, valid_len, config,
            onehot=onehot, attention_fn=attention_fn,
        )
        # scatter the whole batch's K/V into its pages in one op
        pages = write_prompt_kv_batch(pages, k, v, page_ids, valid_len, page_size)
        new_pages.append(pages)
    last = jnp.maximum(valid_len - 1, 0)
    x_last = x[jnp.arange(B), last]  # [B, h]
    return _logits(params, x_last[:, None], config)[:, 0], new_pages


def chunk_transformer_block(
    layer: Params,
    pages,  # this layer's KV pages
    x: jnp.ndarray,  # [B, C, h]
    chunk_start: jnp.ndarray,  # [B]
    valid_len: jnp.ndarray,  # [B]
    page_ids: jnp.ndarray,  # [B, W]
    page_size: int,
    config: LlamaConfig,
    onehot=None,
) -> Tuple[jnp.ndarray, Any]:
    """One chunked-prefill transformer block: attend to the cached
    history + the chunk's causal prefix, then write the chunk's KV.  The
    SINGLE source of the chunk math — the sequential path
    (prefill_chunk) and the pipeline-parallel path (_pp_chunk_block)
    both call this, so their numerics cannot drift."""
    B, C = x.shape[0], x.shape[1]
    positions = chunk_start[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
    residual = x
    h = _norm(x, layer["attn_norm"], config)
    q, k, v = _qkv(layer, h, config, onehot)
    q = apply_rope(q, positions, config.rope_theta, config.rope_scaling)
    k = apply_rope(k, positions, config.rope_theta, config.rope_scaling)
    attn = chunked_prefill_attention(
        q, k, v, pages, page_ids, chunk_start, valid_len,
        config.attn_logit_softcap,
        scale=config.attn_scale, window=layer.get("attn_window"),
    )
    attn_flat = attn.reshape(B, C, -1)
    attn = _maybe_add(
        dense(attn_flat, layer["wo"]),
        lora_delta(layer.get("lora"), "wo", attn_flat, onehot),
    )
    if config.sandwich_norms:
        attn = _norm(attn, layer["post_attn_norm"], config)
    x = residual + attn
    residual = x
    h = _norm(x, layer["mlp_norm"], config)
    out = _mlp(layer, h, config, onehot)
    if config.sandwich_norms:
        out = _norm(out, layer["post_mlp_norm"], config)
    x = residual + out
    pages = write_chunk_kv_batch(
        pages, k, v, page_ids, chunk_start, valid_len, page_size
    )
    return x, pages


def prefill_chunk(
    params: Params,
    config: LlamaConfig,
    tokens: jnp.ndarray,  # [B, C] one chunk of the prompt (padded)
    chunk_start: jnp.ndarray,  # [B] tokens already prefilled (history)
    valid_len: jnp.ndarray,  # [B] valid tokens within THIS chunk
    kv_pages: List[jnp.ndarray],
    page_ids: jnp.ndarray,  # [B, max_pages] the sequence's pages
    page_size: int,
    adapter_ids: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, List[jnp.ndarray]]:
    """One chunk of a chunked prefill: attends to the cached history plus
    the chunk's causal prefix, writes the chunk's KV into the cache, and
    returns logits at the chunk's last valid token.  history=0 makes this
    equivalent to (a window of) plain prefill; a prefix-cache hit just
    starts with chunk_start > 0 and the cached pages in page_ids."""
    B, C = tokens.shape
    onehot = _adapter_onehot(params, adapter_ids, B)
    x = _embed(params, tokens, config)
    new_pages = []
    for layer, pages in zip(params["layers"], kv_pages):
        x, pages = chunk_transformer_block(
            layer, pages, x, chunk_start, valid_len, page_ids, page_size,
            config, onehot=onehot,
        )
        new_pages.append(pages)
    last = jnp.maximum(valid_len - 1, 0)
    x_last = x[jnp.arange(B), last]  # [B, h]
    return _logits(params, x_last[:, None], config)[:, 0], new_pages


def decode_step(
    params: Params,
    config: LlamaConfig,
    tokens: jnp.ndarray,  # [B] current tokens
    pos: jnp.ndarray,  # [B] their positions
    kv_pages: List[jnp.ndarray],
    page_table: jnp.ndarray,  # [B, max_pages]
    active: jnp.ndarray,  # [B] bool
    page_size: int,
    use_pallas: Optional[bool] = None,
    adapter_ids: Optional[jnp.ndarray] = None,  # [B] LoRA ids (-1 = base)
    attention_fn=None,  # fn(q,[B,nq,d], pages, page_table, seq_lens) —
    # e.g. ops.attention.make_sharded_paged_attention for tp>1
) -> Tuple[jnp.ndarray, List[jnp.ndarray]]:
    """One decode token per sequence; returns ([B, vocab] logits, new pages)."""
    B = tokens.shape[0]
    onehot = _adapter_onehot(params, adapter_ids, B)
    x = _embed(params, tokens, config)[:, None, :]  # [B,1,h]
    positions = pos[:, None]
    seq_lens = jnp.where(active, pos + 1, 0)
    new_pages = []
    for layer, pages in zip(params["layers"], kv_pages):
        residual = x
        h = _norm(x, layer["attn_norm"], config)
        q, k, v = _qkv(layer, h, config, onehot)
        q = apply_rope(q, positions, config.rope_theta, config.rope_scaling)
        k = apply_rope(k, positions, config.rope_theta, config.rope_scaling)
        pages = append_token_kv(
            pages, k[:, 0], v[:, 0], page_table, pos, active, page_size
        )
        window = layer.get("attn_window")
        if attention_fn is not None:
            attn = attention_fn(q[:, 0], pages, page_table, seq_lens,
                                window if window is not None
                                else jnp.asarray(0, jnp.int32))
        else:
            attn = paged_attention(
                q[:, 0],
                pages,
                page_table,
                seq_lens,
                logit_softcap=config.attn_logit_softcap,
                use_pallas=use_pallas,
                scale=config.attn_scale,
                window=window,
            )
        attn_flat = attn.reshape(B, 1, -1)
        attn = _maybe_add(
            dense(attn_flat, layer["wo"]),
            lora_delta(layer.get("lora"), "wo", attn_flat, onehot),
        )
        if config.sandwich_norms:
            attn = _norm(attn, layer["post_attn_norm"], config)
        x = residual + attn
        residual = x
        h = _norm(x, layer["mlp_norm"], config)
        out = _mlp(layer, h, config, onehot)
        if config.sandwich_norms:
            out = _norm(out, layer["post_mlp_norm"], config)
        x = residual + out
        new_pages.append(pages)
    return _logits(params, x, config)[:, 0], new_pages


def forward_ragged(
    params: Params,
    config: LlamaConfig,
    tokens: jnp.ndarray,  # [T] packed ragged token buffer
    token_seq: jnp.ndarray,  # [T] lane index per token (-1 = padding)
    token_pos: jnp.ndarray,  # [T] absolute position per token
    q_start: jnp.ndarray,  # [B] first packed index of each lane's slice
    q_len: jnp.ndarray,  # [B] slice length (0 = inactive lane)
    kv_start: jnp.ndarray,  # [B] tokens already cached before the slice
    kv_pages: List[jnp.ndarray],
    page_table: jnp.ndarray,  # [B, max_pages]
    page_size: int,
    last_idx: jnp.ndarray,  # [B] packed index of each lane's LAST token
    adapter_ids: Optional[jnp.ndarray] = None,  # [B] LoRA ids (-1 = base)
    attention_fn=None,  # sharded ragged attention for tp>1 (ops/attention)
    use_pallas: Optional[bool] = None,
    logits_at: Optional[jnp.ndarray] = None,  # [N] packed indices: return
    # logits at EVERY listed token instead of one per lane — the
    # speculative-verify surface (docs/kernels.md), where each position of
    # a K+1-token slice needs its own next-token distribution
    dense_stride: Optional[int] = None,  # static dense-packing stride for
    # the Pallas kernel (lanes share blocks; None = solo-block invariant)
) -> Tuple[jnp.ndarray, List[jnp.ndarray]]:
    """The unified mixed-batch forward (docs/kernels.md): every lane
    contributes an arbitrary-length query slice — a whole prompt, a prompt
    chunk, or a single decode token — packed into one [T] buffer.  Each
    layer writes the slice's K/V into the paged cache, then runs ragged
    paged attention over the pages with the causal mask anchored at each
    lane's kv offset.  Returns ([B, vocab] logits at each lane's last
    token, new pages).

    The buffer runs through the stack as a [T, 1, h] token-batch (batch
    axis = packed tokens), which keeps every per-batch mechanism — LoRA
    one-hot selection, biases, qk-norm — per-TOKEN, so lanes with
    different adapters coexist in one mixed dispatch."""
    T = tokens.shape[0]
    valid = token_seq >= 0
    seq_ix = jnp.maximum(token_seq, 0)
    token_adapters = None
    if adapter_ids is not None:
        token_adapters = jnp.where(valid, adapter_ids[seq_ix], -1)
    onehot = _adapter_onehot(params, token_adapters, T)
    x = _embed(params, tokens, config)[:, None, :]  # [T, 1, h]
    positions = token_pos[:, None]
    new_pages = []
    for layer, pages in zip(params["layers"], kv_pages):
        residual = x
        h = _norm(x, layer["attn_norm"], config)
        q, k, v = _qkv(layer, h, config, onehot)
        q = apply_rope(q, positions, config.rope_theta, config.rope_scaling)
        k = apply_rope(k, positions, config.rope_theta, config.rope_scaling)
        pages = write_ragged_kv(
            pages, k[:, 0], v[:, 0], page_table, token_seq, token_pos,
            page_size,
        )
        window = layer.get("attn_window")
        if attention_fn is not None:
            attn = attention_fn(
                q[:, 0], pages, page_table, q_start, q_len, kv_start,
                window if window is not None else jnp.asarray(0, jnp.int32))
        else:
            attn = ragged_paged_attention(
                q[:, 0], pages, page_table, q_start, q_len, kv_start,
                logit_softcap=config.attn_logit_softcap,
                use_pallas=use_pallas,
                scale=config.attn_scale,
                window=window,
                dense_stride=dense_stride,
            )
        attn_flat = attn.reshape(T, 1, -1)
        attn = _maybe_add(
            dense(attn_flat, layer["wo"]),
            lora_delta(layer.get("lora"), "wo", attn_flat, onehot),
        )
        if config.sandwich_norms:
            attn = _norm(attn, layer["post_attn_norm"], config)
        x = residual + attn
        residual = x
        h = _norm(x, layer["mlp_norm"], config)
        out = _mlp(layer, h, config, onehot)
        if config.sandwich_norms:
            out = _norm(out, layer["post_mlp_norm"], config)
        x = residual + out
        new_pages.append(pages)
    if logits_at is not None:
        x_sel = x[logits_at, 0]  # [N, h]
        return _logits(params, x_sel[:, None], config)[:, 0], new_pages
    x_last = x[last_idx, 0]  # [B, h]
    return _logits(params, x_last[:, None], config)[:, 0], new_pages


# ---------------- pipeline-parallel execution (engine pp > 1) ----------------


def stack_layer_params(params: Params) -> Params:
    """Per-layer list -> stacked pytree with leading layer axis (sharded
    over the pipe mesh axis by parallel/sharding.stacked_layer_pspecs)."""
    out = dict(params)
    out["layers"] = jax.tree.map(lambda *xs: jnp.stack(xs), *params["layers"])
    return out


def _pp_prefill_block(config: LlamaConfig, page_size: int):
    """One transformer block + prompt-KV scatter as a pipeline block_fn.
    Invalid (warm-up/drain) microbatches write to the null page (page 0)."""

    def block_fn(layer, pages_l, x, aux, valid):
        B, T = x.shape[0], x.shape[1]
        positions = jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
        valid_len = aux["valid_len"]
        x_out, k, v = transformer_block(
            layer, x, positions, valid_len, config,
            onehot=aux.get("onehot"))
        page_ids = jnp.where(valid, aux["page_ids"], 0)
        pages_l = write_prompt_kv_batch(
            pages_l, k, v, page_ids, valid_len, page_size)
        return x_out, pages_l

    return block_fn


def _pp_decode_block(config: LlamaConfig, page_size: int):
    """One decode step per sequence against this stage's paged cache.
    `live` folds in the pipeline validity mask, so warm-up/drain steps
    append to the null page and read zero-length sequences."""

    def block_fn(layer, pages_l, x, aux, valid):
        B = x.shape[0]
        pos, page_table = aux["pos"], aux["page_table"]
        onehot = aux.get("onehot")
        live = aux["live"] & valid
        positions = pos[:, None]
        residual = x
        h = _norm(x, layer["attn_norm"], config)
        q, k, v = _qkv(layer, h, config, onehot)
        q = apply_rope(q, positions, config.rope_theta, config.rope_scaling)
        k = apply_rope(k, positions, config.rope_theta, config.rope_scaling)
        pages_l = append_token_kv(
            pages_l, k[:, 0], v[:, 0], page_table, pos, live, page_size)
        seq_lens = jnp.where(live, pos + 1, 0)
        attn = paged_attention(
            q[:, 0], pages_l, page_table, seq_lens,
            logit_softcap=config.attn_logit_softcap, use_pallas=False,
            scale=config.attn_scale, window=layer.get("attn_window"),
        )
        attn_flat = attn.reshape(B, 1, -1)
        attn_out = _maybe_add(
            dense(attn_flat, layer["wo"]),
            lora_delta(layer.get("lora"), "wo", attn_flat, onehot),
        )
        if config.sandwich_norms:
            attn_out = _norm(attn_out, layer["post_attn_norm"], config)
        x = residual + attn_out
        residual = x
        h = _norm(x, layer["mlp_norm"], config)
        out = _mlp(layer, h, config, onehot)
        if config.sandwich_norms:
            out = _norm(out, layer["post_mlp_norm"], config)
        return residual + out, pages_l

    return block_fn


def prefill_pp(
    params: Params,
    config: LlamaConfig,
    tokens: jnp.ndarray,  # [B, T]
    valid_len: jnp.ndarray,  # [B]
    kv_pages: jnp.ndarray,  # stacked [L, num_pages, 2, nkv, ps, d]
    page_ids: jnp.ndarray,  # [B, max_pages]
    page_size: int,
    mesh,
    n_microbatches: int,
    adapter_ids: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pipeline-parallel prefill: params["layers"] is the stacked pytree,
    stages stream microbatches GPipe-style (parallel/pipeline.py).
    Embedding and logits run pipe-replicated outside the staged stack."""
    from ..parallel.pipeline import pipeline_blocks

    B = tokens.shape[0]
    x = _embed(params, tokens, config)
    aux = {"valid_len": valid_len, "page_ids": page_ids}
    onehot = _adapter_onehot(params, adapter_ids, B)
    if onehot is not None:
        aux["onehot"] = onehot
    x, new_pages = pipeline_blocks(
        params["layers"], kv_pages, x, aux,
        _pp_prefill_block(config, page_size), mesh, n_microbatches,
    )
    last = jnp.maximum(valid_len - 1, 0)
    x_last = x[jnp.arange(B), last]
    return _logits(params, x_last[:, None], config)[:, 0], new_pages


def _pp_chunk_block(config: LlamaConfig, page_size: int):
    """One chunked-prefill transformer block as a pipeline block_fn: the
    chunk attends to this stage's cached history plus its own causal
    prefix, then writes its KV.  Warm-up/drain microbatches write to the
    null page and read zero history."""

    def block_fn(layer, pages_l, x, aux, valid):
        chunk_start = jnp.where(valid, aux["chunk_start"], 0)
        page_ids = jnp.where(valid, aux["page_ids"], 0)
        return chunk_transformer_block(
            layer, pages_l, x, chunk_start, aux["valid_len"], page_ids,
            page_size, config, onehot=aux.get("onehot"),
        )

    return block_fn


def prefill_chunk_pp(
    params: Params,
    config: LlamaConfig,
    tokens: jnp.ndarray,  # [B, C]
    chunk_start: jnp.ndarray,  # [B]
    valid_len: jnp.ndarray,  # [B]
    kv_pages: jnp.ndarray,  # stacked [L, ...]
    page_ids: jnp.ndarray,  # [B, W]
    page_size: int,
    mesh,
    n_microbatches: int,
    adapter_ids: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pipeline-parallel chunked prefill (engine pp>1): unlocks prompts
    beyond max_prefill_len AND prefix-cache hits under pp."""
    from ..parallel.pipeline import pipeline_blocks

    B = tokens.shape[0]
    x = _embed(params, tokens, config)
    aux = {"chunk_start": chunk_start, "valid_len": valid_len,
           "page_ids": page_ids}
    onehot = _adapter_onehot(params, adapter_ids, B)
    if onehot is not None:
        aux["onehot"] = onehot
    x, new_pages = pipeline_blocks(
        params["layers"], kv_pages, x, aux,
        _pp_chunk_block(config, page_size), mesh, n_microbatches,
    )
    last = jnp.maximum(valid_len - 1, 0)
    x_last = x[jnp.arange(B), last]
    return _logits(params, x_last[:, None], config)[:, 0], new_pages


def decode_step_pp(
    params: Params,
    config: LlamaConfig,
    tokens: jnp.ndarray,  # [B]
    pos: jnp.ndarray,  # [B]
    kv_pages: jnp.ndarray,  # stacked [L, ...]
    page_table: jnp.ndarray,  # [B, max_pages]
    active: jnp.ndarray,  # [B] bool
    page_size: int,
    mesh,
    n_microbatches: int,
    adapter_ids: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pipeline-parallel decode step (engine pp>1)."""
    from ..parallel.pipeline import pipeline_blocks

    x = _embed(params, tokens, config)[:, None, :]
    aux = {"pos": pos, "page_table": page_table, "live": active}
    onehot = _adapter_onehot(params, adapter_ids, tokens.shape[0])
    if onehot is not None:
        aux["onehot"] = onehot
    x, new_pages = pipeline_blocks(
        params["layers"], kv_pages, x, aux,
        _pp_decode_block(config, page_size), mesh, n_microbatches,
    )
    return _logits(params, x, config)[:, 0], new_pages


# ---------------- HF checkpoint loading ----------------

_HF_LAYER_MAP = {
    "input_layernorm.weight": "attn_norm",
    "self_attn.q_proj.weight": "wq",
    "self_attn.k_proj.weight": "wk",
    "self_attn.v_proj.weight": "wv",
    "self_attn.o_proj.weight": "wo",
    "self_attn.q_proj.bias": "bq",
    "self_attn.k_proj.bias": "bk",
    "self_attn.v_proj.bias": "bv",
    "self_attn.q_norm.weight": "q_norm",
    "self_attn.k_norm.weight": "k_norm",
    "post_attention_layernorm.weight": "mlp_norm",
    # Gemma-2 sandwich norms: HF's post_attention_layernorm is the
    # POST-attn norm and pre_feedforward_layernorm the pre-ffn norm; the
    # loader remaps below when the config is sandwich
    "pre_feedforward_layernorm.weight": "pre_ffn_norm_hf",
    "post_feedforward_layernorm.weight": "post_mlp_norm",
    "mlp.gate_proj.weight": "w_gate",
    "mlp.up_proj.weight": "w_up",
    "mlp.down_proj.weight": "w_down",
}

_TRANSPOSED = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}


def load_hf_weights_streamed(model_dir: str, config: LlamaConfig,
                             weight_quant: str = "none",
                             stats: Optional[dict] = None) -> Params:
    """Streaming twin of :func:`load_hf_weights`: tensors are read from the
    safetensors shards ONE AT A TIME, transposed/quantized on the host and
    placed on device immediately, so peak host staging stays ~one tensor
    instead of the whole checkpoint (docs/coldstart.md).  With
    ``weight_quant="int8"`` the device only ever sees int8 + scales — an 8B
    load peaks near the QUANTIZED resident size plus one bf16 tensor,
    which is what makes cold start weight-I/O-bound on a warmed
    LocalModelCache volume instead of host-RAM-bound.

    `stats` (optional dict) is filled with the accounting the coldstart
    bench records: ``peak_host_bytes`` (largest simultaneous raw staging
    footprint), ``read_bytes`` (total checkpoint bytes streamed) and
    ``n_tensors``.

    MoE expert stacks are the one exception to strict streaming: a
    layer's experts buffer on the host until all E are seen (they must
    stack into one [E, in, out] tensor), then free."""
    from safetensors import safe_open

    if weight_quant == "int8" and config.n_experts > 0:
        raise NotImplementedError("weight_quant over MoE experts")
    dtype = jnp.dtype(config.dtype)
    quant = weight_quant == "int8"
    files = sorted(
        os.path.join(model_dir, f)
        for f in os.listdir(model_dir)
        if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {model_dir}")

    acct = {"peak_host_bytes": 0, "read_bytes": 0, "n_tensors": 0}
    held = {"bytes": 0}  # raw host staging currently alive (MoE buffers)

    def charge(nbytes: int) -> None:
        held["bytes"] += nbytes
        acct["peak_host_bytes"] = max(acct["peak_host_bytes"], held["bytes"])

    def to_jnp(arr: np.ndarray, transpose: bool) -> jnp.ndarray:
        if transpose:
            arr = arr.T
        return jnp.asarray(arr).astype(dtype)

    def to_jnp_q(arr: np.ndarray, transpose: bool, channel_axis: int = -1):
        if transpose:
            arr = arr.T
        axis = 1 - (channel_axis % 2)
        qd = quantize_array_np(arr, axis=axis)
        return {"q": jnp.asarray(qd["q"]), "s": jnp.asarray(qd["s"])}

    params: Params = {"layers": [dict() for _ in range(config.n_layers)]}
    # MoE staging: (layer, proj) -> {expert_index: raw np tensor}
    moe_pending: Dict[tuple, Dict[int, np.ndarray]] = {}
    layer_re = re.compile(r"^model\.layers\.(\d+)\.(.+)$")
    expert_re = re.compile(r"^block_sparse_moe\.experts\.(\d+)\.(w[123])\.weight$")
    _MOE_PROJ = {"w1": "w_gate", "w3": "w_up", "w2": "w_down"}

    def place(name: str, arr: np.ndarray) -> bool:
        """Route ONE checkpoint tensor to its pytree slot, on device.
        Returns True when the raw host tensor was RETAINED (an MoE expert
        buffered until its stack completes) — the caller keeps its bytes
        charged against the staging footprint."""
        if name == "model.embed_tokens.weight":
            params["embed"] = (
                to_jnp_q(arr, False, channel_axis=0)
                if quant and config.tie_word_embeddings
                else to_jnp(arr, False)
            )
            return False
        if name == "model.norm.weight":
            params["final_norm"] = to_jnp(arr, False)
            return False
        if name == "lm_head.weight":
            if not config.tie_word_embeddings:
                params["lm_head"] = (
                    to_jnp_q(arr, True) if quant else to_jnp(arr, True))
            return False
        m = layer_re.match(name)
        if m is None:
            return False  # rotary inv_freq etc.: derived, never loaded
        i, suffix = int(m.group(1)), m.group(2)
        if i >= config.n_layers:
            return False
        layer = params["layers"][i]
        if config.n_experts > 0:
            if suffix == "block_sparse_moe.gate.weight":
                layer["router"] = to_jnp(arr, True)
                return False
            em = expert_re.match(suffix)
            if em is not None:
                e, proj = int(em.group(1)), _MOE_PROJ[em.group(2)]
                pending = moe_pending.setdefault((i, proj), {})
                pending[e] = arr
                if len(pending) == config.n_experts:
                    stacked = np.stack(
                        [pending[k].T for k in range(config.n_experts)])
                    layer[proj] = jnp.asarray(stacked).astype(dtype)
                    # release every buffered expert INCLUDING this one —
                    # hence retained=True so the caller doesn't re-release
                    held["bytes"] -= sum(t.nbytes for t in pending.values())
                    del moe_pending[(i, proj)]
                return True
        ours = _HF_LAYER_MAP.get(suffix)
        if ours is None:
            return False
        if quant and ours in LINEAR_KEYS:
            layer[ours] = to_jnp_q(arr, True)
        else:
            layer[ours] = to_jnp(arr, ours in _TRANSPOSED)
        return False

    for path in files:
        with safe_open(path, framework="numpy") as f:
            for name in f.keys():
                arr = f.get_tensor(name)
                acct["read_bytes"] += arr.nbytes
                acct["n_tensors"] += 1
                charge(arr.nbytes)
                retained = place(name, arr)
                if not retained:
                    held["bytes"] -= arr.nbytes
                del arr

    if moe_pending:
        missing = sorted(moe_pending)
        raise ValueError(
            f"checkpoint is missing MoE experts for (layer, proj): {missing[:4]}")
    for i, layer in enumerate(params["layers"]):
        if config.sandwich_norms:
            layer["post_attn_norm"] = layer.pop("mlp_norm")
            layer["mlp_norm"] = layer.pop("pre_ffn_norm_hf")
        else:
            layer.pop("pre_ffn_norm_hf", None)
            layer.pop("post_mlp_norm", None)
        if config.sliding_window > 0:
            layer["attn_window"] = jnp.asarray(
                config.layer_window(i), jnp.int32)
    if stats is not None:
        stats.update(acct)
    return params


def load_hf_weights(model_dir: str, config: LlamaConfig,
                    weight_quant: str = "none") -> Params:
    """Load a local HuggingFace safetensors checkpoint (no torch needed:
    safetensors.numpy) into the functional param pytree.  HF Linear stores
    [out, in]; our layout is [in, out], hence the transposes.

    weight_quant="int8" quantizes tensor-by-tensor ON THE HOST before
    device placement, so an 8B load peaks at one bf16 tensor of host RAM
    extra — the device only ever sees int8 + scales."""
    from safetensors import safe_open

    if weight_quant == "int8" and config.n_experts > 0:
        raise NotImplementedError("weight_quant over MoE experts")
    dtype = jnp.dtype(config.dtype)
    files = sorted(
        os.path.join(model_dir, f)
        for f in os.listdir(model_dir)
        if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {model_dir}")
    tensors: Dict[str, np.ndarray] = {}
    for path in files:
        with safe_open(path, framework="numpy") as f:
            for name in f.keys():
                tensors[name] = f.get_tensor(name)

    def to_jnp(arr: np.ndarray, transpose: bool) -> jnp.ndarray:
        if transpose:
            arr = arr.T
        return jnp.asarray(arr).astype(dtype)

    def to_jnp_q(arr: np.ndarray, transpose: bool, channel_axis: int = -1):
        """Host-quantize, then place: int8 + float32 scale on device."""
        if transpose:
            arr = arr.T
        axis = 1 - (channel_axis % 2)  # reduce over the non-channel axis
        qd = quantize_array_np(arr, axis=axis)
        return {"q": jnp.asarray(qd["q"]), "s": jnp.asarray(qd["s"])}

    quant = weight_quant == "int8"
    params: Params = {
        "embed": (
            to_jnp_q(tensors["model.embed_tokens.weight"], False, channel_axis=0)
            if quant and config.tie_word_embeddings
            else to_jnp(tensors["model.embed_tokens.weight"], False)
        ),
        "final_norm": to_jnp(tensors["model.norm.weight"], False),
        "layers": [],
    }
    if "lm_head.weight" in tensors and not config.tie_word_embeddings:
        params["lm_head"] = (
            to_jnp_q(tensors["lm_head.weight"], True) if quant
            else to_jnp(tensors["lm_head.weight"], True)
        )
    for i in range(config.n_layers):
        prefix = f"model.layers.{i}."
        layer: Params = {}
        for hf_suffix, ours in _HF_LAYER_MAP.items():
            key = prefix + hf_suffix
            if key in tensors:
                if quant and ours in LINEAR_KEYS:
                    layer[ours] = to_jnp_q(tensors[key], True)
                else:
                    layer[ours] = to_jnp(tensors[key], ours in _TRANSPOSED)
        if config.sandwich_norms:
            # Gemma-2 norm remap: HF post_attention_layernorm is the
            # POST-attn norm (our "post_attn_norm"); pre_feedforward is
            # the pre-ffn norm (our "mlp_norm" slot)
            layer["post_attn_norm"] = layer.pop("mlp_norm")
            layer["mlp_norm"] = layer.pop("pre_ffn_norm_hf")
        else:
            layer.pop("pre_ffn_norm_hf", None)
            layer.pop("post_mlp_norm", None)
        if config.sliding_window > 0:
            layer["attn_window"] = jnp.asarray(
                config.layer_window(i), jnp.int32)
        if config.n_experts > 0:
            # MixtralForCausalLM: block_sparse_moe.gate + per-expert w1/w3/w2
            # (HF w1=gate, w3=up, w2=down; Linear stores [out, in] -> stack
            # experts then transpose to our [E, in, out] layout)
            moe_prefix = prefix + "block_sparse_moe."
            layer["router"] = to_jnp(tensors[moe_prefix + "gate.weight"], True)
            for hf_name, ours in (("w1", "w_gate"), ("w3", "w_up"), ("w2", "w_down")):
                stacked = np.stack(
                    [
                        tensors[f"{moe_prefix}experts.{e}.{hf_name}.weight"].T
                        for e in range(config.n_experts)
                    ]
                )
                layer[ours] = jnp.asarray(stacked).astype(dtype)
        params["layers"].append(layer)
    return params
