"""One retry policy for every outbound hop.

Replaces the ad-hoc retries that used to live in three places (httpx
transport retries in `inference_client.py`, a gRPC retryPolicy dict, a
bare for-loop in the graph router) with a single calculator: exponential
backoff with FULL jitter (AWS architecture-blog shape — jitter over the
whole interval, not +/- a fraction, so synchronized clients decorrelate),
`Retry-After` aware, capped by both a per-request retry budget and the
propagated deadline.  The policy computes delays; callers own the loop,
which keeps it usable from async httpx code, sync urllib code, and the
gRPC service-config translation alike.
"""

from __future__ import annotations

import datetime
import random
from dataclasses import dataclass
from email.utils import parsedate_to_datetime
from typing import FrozenSet, Optional

RETRYABLE_STATUSES: FrozenSet[int] = frozenset({429, 502, 503, 504})


def parse_retry_after(value) -> Optional[float]:
    """Seconds to wait from a Retry-After header value: delta-seconds
    (`"2"`, `"1.5"`) or an HTTP-date.  None for absent/malformed — a bad
    header must never break the retry loop."""
    if value is None:
        return None
    text = str(value).strip()
    if not text:
        return None
    try:
        return max(float(text), 0.0)
    except ValueError:
        # not delta-seconds; try HTTP-date below
        pass
    try:
        when = parsedate_to_datetime(text)
    except (TypeError, ValueError):
        return None
    if when.tzinfo is None:
        when = when.replace(tzinfo=datetime.timezone.utc)
    now = datetime.datetime.now(datetime.timezone.utc)
    return max((when - now).total_seconds(), 0.0)


@dataclass
class RetryPolicy:
    """Exponential backoff + full jitter with hard spend limits.

    `max_attempts` counts TOTAL tries (1 = no retries).  `retry_budget_s`
    bounds the cumulative wall time one request may spend across retries,
    independent of attempt count — a slow backend must not hold a caller
    hostage for attempts x timeout.  A `seed` makes the jitter stream
    deterministic for chaos tests.
    """

    max_attempts: int = 3
    base_backoff_s: float = 0.1
    max_backoff_s: float = 2.0
    multiplier: float = 2.0
    retry_budget_s: float = 30.0
    retryable_statuses: FrozenSet[int] = RETRYABLE_STATUSES
    seed: Optional[int] = None

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def retryable(self, status: int) -> bool:
        return status in self.retryable_statuses

    def next_delay(
        self,
        attempt: int,
        *,
        retry_after: Optional[float] = None,
        elapsed: float = 0.0,
        deadline=None,
    ) -> Optional[float]:
        """Backoff before the next try, or None when retrying must stop.

        `attempt` is the number of tries already made (>= 1).  Stops when
        attempts are exhausted, when the delay would blow `retry_budget_s`
        (given `elapsed` seconds already spent), or when the propagated
        `deadline` cannot cover the wait — retrying past a dead deadline
        only burns backend capacity on an answer nobody will read.
        A server-sent `retry_after` floors the computed delay (the server
        knows its own recovery horizon better than our jitter does).
        """
        if attempt >= self.max_attempts:
            return None
        try:
            grown = self.base_backoff_s * (self.multiplier ** (attempt - 1))
        except OverflowError:
            # float exponent overflow at attempt ~1025 with multiplier 2:
            # the cap is what matters, not the astronomically grown value
            grown = self.max_backoff_s
        cap = min(self.max_backoff_s, grown)
        delay = self._rng.uniform(0.0, max(cap, 0.0))
        if retry_after is not None:
            delay = max(delay, retry_after)
        if elapsed + delay > self.retry_budget_s:
            return None
        if deadline is not None and deadline.remaining() <= delay:
            return None
        return delay
