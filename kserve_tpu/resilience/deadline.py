"""Request deadline: one budget, propagated across every hop.

The wire form (`x-request-deadline` header) is the REMAINING budget in
seconds, not an absolute timestamp — peers do not share a clock, and a
relative budget can only shrink as it crosses hops (each hop re-anchors
it against its own monotonic clock, so network transit time is charged
automatically).  In-process the deadline rides a contextvar so the REST
middleware can set it once and the engine admission path, the inference
client, and the graph router all see it without plumbing a parameter
through every call signature.
"""

from __future__ import annotations

import contextlib
from contextvars import ContextVar
from typing import Optional

from .clock import MONOTONIC, Clock

DEADLINE_HEADER = "x-request-deadline"


class DeadlineExceededError(TimeoutError):
    """The request's deadline expired before (or while) it could be served.
    Maps to HTTP 504 at the protocol layer."""

    def __init__(self, detail: str = "request deadline exceeded"):
        super().__init__(detail)


class Deadline:
    """An absolute expiry point on a monotonic clock."""

    __slots__ = ("expires_at", "clock")

    def __init__(self, expires_at: float, clock: Clock = MONOTONIC):
        self.expires_at = expires_at
        self.clock = clock

    @classmethod
    def after(cls, seconds: float, clock: Clock = MONOTONIC) -> "Deadline":
        return cls(clock.now() + seconds, clock)

    def remaining(self) -> float:
        return self.expires_at - self.clock.now()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def to_header(self) -> str:
        """Remaining budget for the next hop (clamped at 0: a dead budget
        still propagates, so the receiver rejects instead of working)."""
        return f"{max(self.remaining(), 0.0):.3f}"

    @classmethod
    def from_header(
        cls, value: Optional[str], clock: Clock = MONOTONIC
    ) -> Optional["Deadline"]:
        """Parse a remaining-seconds header; malformed values are ignored
        (None) rather than failing the request — a deadline is an
        optimization contract, not an input schema."""
        if not value:
            return None
        try:
            seconds = float(value)
        except (TypeError, ValueError):
            return None
        return cls.after(seconds, clock)

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining():.3f}s)"


_current_deadline: ContextVar[Optional[Deadline]] = ContextVar(
    "kserve_tpu_request_deadline", default=None
)


def current_deadline() -> Optional[Deadline]:
    """The deadline governing the current async context (None = unbounded)."""
    return _current_deadline.get()


@contextlib.contextmanager
def deadline_scope(deadline: Optional[Deadline]):
    """Bind `deadline` as the current deadline for the enclosed block."""
    token = _current_deadline.set(deadline)
    try:
        yield deadline
    finally:
        _current_deadline.reset(token)
