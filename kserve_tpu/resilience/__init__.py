"""Unified resilience layer: deadline propagation, retry policy, circuit
breakers, load shedding, and deterministic fault injection.

One policy surface for the scattered defenses the serving stack needs at
scale (docs/resilience.md): the REST server parses and enforces the
`x-request-deadline` budget and sheds load past a queue watermark; the
graph router and inference client retry through one `RetryPolicy`; the
router and EPP picker consult per-backend `CircuitBreaker`s; and a
seeded `FaultPlan` makes every one of those behaviors provable in CI
without real sleeps (clock injection throughout).
"""

from .breaker import (  # noqa: F401
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerConfig,
    BreakerRegistry,
    CircuitBreaker,
)
from .clock import MONOTONIC, Clock, FakeClock  # noqa: F401
from .deadline import (  # noqa: F401
    DEADLINE_HEADER,
    Deadline,
    DeadlineExceededError,
    current_deadline,
    deadline_scope,
)
from .faults import (  # noqa: F401
    FaultInjectingTransport,
    FaultPlan,
    FaultSpec,
    ReplicaCrashError,
)
from .retry import RETRYABLE_STATUSES, RetryPolicy, parse_retry_after  # noqa: F401
from .shedding import LoadShedder, ShedConfig, shedding_middleware  # noqa: F401
