"""Admission-time load shedding for the REST protocol layer.

When the engine queue crosses a watermark, new inference work is refused
at the door with 429 + `Retry-After` — a fast, cheap rejection the
client's RetryPolicy understands — instead of being queued into latency
that blows every deadline behind it.  A hysteresis band (shed at the
watermark, resume below `resume_fraction` x watermark) prevents flapping
at the boundary.

Only POSTs are shed: health probes, readiness, metrics, and model
listings must keep answering during overload or the system can never be
observed (or healed) while it drowns.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, Optional

from aiohttp import web


@dataclass
class ShedConfig:
    # depth at which new inference POSTs start bouncing; <= 0 disables
    queue_watermark: int = 256
    # stop shedding once depth falls to watermark * resume_fraction
    resume_fraction: float = 0.75
    # the Retry-After hint handed to shed clients
    retry_after_s: float = 1.0

    @classmethod
    def from_env(cls, env=None) -> "ShedConfig":
        env = os.environ if env is None else env
        return cls(
            queue_watermark=int(env.get("KSERVE_TPU_SHED_WATERMARK", "256")),
            resume_fraction=float(
                env.get("KSERVE_TPU_SHED_RESUME_FRACTION", "0.75")
            ),
            retry_after_s=float(env.get("KSERVE_TPU_SHED_RETRY_AFTER_S", "1.0")),
        )


class LoadShedder:
    """Hysteresis watermark over an externally-supplied queue depth."""

    def __init__(
        self,
        config: Optional[ShedConfig] = None,
        on_shed: Optional[Callable[[], None]] = None,
    ):
        self.config = config or ShedConfig()
        self.on_shed = on_shed
        self._shedding = False
        self.shed_count = 0

    @property
    def enabled(self) -> bool:
        return self.config.queue_watermark > 0

    @property
    def shedding(self) -> bool:
        return self._shedding

    @property
    def retry_after_s(self) -> float:
        return self.config.retry_after_s

    def should_shed(self, depth: int) -> bool:
        """The admission decision for one request at the given depth."""
        if not self.enabled:
            return False
        if self._shedding:
            if depth <= self.config.queue_watermark * self.config.resume_fraction:
                self._shedding = False
        elif depth >= self.config.queue_watermark:
            self._shedding = True
        if self._shedding:
            self.shed_count += 1
            if self.on_shed is not None:
                self.on_shed()
        return self._shedding


def is_inference_path(path: str) -> bool:
    """POST paths that enqueue engine/model work (v1 predict/explain, v2
    infer, OpenAI heads, timeseries forecast, P/D prefill).  Admin POSTs —
    repository load/unload in particular, the very actions an operator
    uses to HEAL an overload — must never be shed."""
    return (
        ":predict" in path
        or ":explain" in path
        or path.endswith("/infer")
        or path.startswith("/openai/")
        or path.startswith("/v1/timeseries/")
        or path.startswith("/v1/prefill/")
    )


def shedding_middleware(
    shedder: LoadShedder,
    queue_depth: Callable[[], int],
    path_filter: Callable[[str], bool] = is_inference_path,
):
    """aiohttp middleware bouncing inference POSTs while past the
    watermark; everything else (probes, GETs, metrics, repository admin)
    always passes."""

    @web.middleware
    async def middleware(request: web.Request, handler):
        if (
            request.method == "POST"
            and path_filter(request.path)
            and shedder.should_shed(queue_depth())
        ):
            return web.json_response(
                {"error": "server overloaded, shedding load"},
                status=429,
                headers={"Retry-After": f"{shedder.retry_after_s:g}"},
            )
        return await handler(request)

    return middleware
