"""Per-backend circuit breakers: fast failure isolation.

Classic three-state machine over a rolling outcome window:

- **closed** — traffic flows; outcomes are recorded.  When the failure
  rate over the last `window` outcomes reaches `failure_threshold` (and
  at least `min_volume` outcomes exist — two early failures must not
  condemn a backend), the breaker opens.
- **open** — traffic is refused locally (`allow()` is False) for
  `open_for_s`; the broken backend gets silence to recover instead of a
  retry storm.
- **half_open** — after the cooldown, a SINGLE probe is admitted per
  cooldown period (`allow()` grants it; concurrent callers are refused,
  and an unreported probe re-grants after another `open_for_s` so a
  dropped probe cannot wedge the state machine).  The first recorded
  success closes the breaker (window reset), the first failure re-opens
  it for another cooldown.

`allow()` consumes the half-open probe and is for the call site that
actually SENDS; pick/candidate filtering must use the non-consuming
`available()` (open = excluded, half-open = eligible) or it would burn
the probe on requests routed elsewhere.

State reads perform the time-based open -> half_open move, so no timer
task is needed and a `FakeClock` makes the whole machine a pure function
of recorded outcomes + advanced time.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, Optional

from .clock import MONOTONIC, Clock

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# (backend, new_state) -> None; the metrics hook signature
TransitionHook = Callable[[str, str], None]


@dataclass
class BreakerConfig:
    window: int = 20
    failure_threshold: float = 0.5
    min_volume: int = 5
    open_for_s: float = 30.0


class CircuitBreaker:
    def __init__(
        self,
        config: Optional[BreakerConfig] = None,
        clock: Clock = MONOTONIC,
        on_transition: Optional[TransitionHook] = None,
        name: str = "",
    ):
        self.config = config or BreakerConfig()
        self.clock = clock
        self.name = name
        self.on_transition = on_transition
        self._outcomes: Deque[bool] = deque(maxlen=self.config.window)
        self._state = CLOSED
        self._opened_at = 0.0
        self._probe_granted_at: Optional[float] = None

    @property
    def state(self) -> str:
        if (
            self._state == OPEN
            and (self.clock.now() - self._opened_at) >= self.config.open_for_s
        ):
            self._probe_granted_at = None
            self._transition(HALF_OPEN)
        return self._state

    def available(self) -> bool:
        """Non-consuming eligibility read for pick/candidate filtering:
        open = excluded, closed/half-open = eligible."""
        return self.state != OPEN

    def allow(self) -> bool:
        """May a request be SENT to this backend right now?  Open refuses;
        half-open grants one probe per cooldown period — concurrent
        callers are refused so a recovering backend sees one request, not
        a thundering herd of them."""
        st = self.state
        if st == OPEN:
            return False
        if st == HALF_OPEN:
            now = self.clock.now()
            if (
                self._probe_granted_at is not None
                and now - self._probe_granted_at < self.config.open_for_s
            ):
                return False
            self._probe_granted_at = now
        return True

    def record_success(self) -> None:
        if self.state == HALF_OPEN:
            self._outcomes.clear()
            self._probe_granted_at = None
            self._transition(CLOSED)
            return
        self._outcomes.append(True)

    def record_failure(self) -> None:
        if self.state == HALF_OPEN:
            self._reopen()
            return
        self._outcomes.append(False)
        if self._state == CLOSED and self._should_open():
            self._reopen()

    def _should_open(self) -> bool:
        n = len(self._outcomes)
        if n < self.config.min_volume:
            return False
        failures = sum(1 for ok in self._outcomes if not ok)
        return failures / n >= self.config.failure_threshold

    def _reopen(self) -> None:
        self._outcomes.clear()
        self._opened_at = self.clock.now()
        self._transition(OPEN)

    def _transition(self, new_state: str) -> None:
        if new_state == self._state:
            return
        self._state = new_state
        if self.on_transition is not None:
            self.on_transition(self.name, new_state)


class BreakerRegistry:
    """Per-backend breakers, created on first sight and keyed by whatever
    backend identifier the caller uses (replica base url, host:port)."""

    def __init__(
        self,
        config: Optional[BreakerConfig] = None,
        clock: Clock = MONOTONIC,
        on_transition: Optional[TransitionHook] = None,
    ):
        self.config = config or BreakerConfig()
        self.clock = clock
        self.on_transition = on_transition
        self._breakers: Dict[str, CircuitBreaker] = {}

    def get(self, backend: str) -> CircuitBreaker:
        breaker = self._breakers.get(backend)
        if breaker is None:
            breaker = CircuitBreaker(
                self.config, self.clock, self.on_transition, name=backend
            )
            self._breakers[backend] = breaker
        return breaker

    def allow(self, backend: str) -> bool:
        return self.get(backend).allow()

    def available(self, backend: str) -> bool:
        return self.get(backend).available()

    def record_success(self, backend: str) -> None:
        self.get(backend).record_success()

    def record_failure(self, backend: str) -> None:
        self.get(backend).record_failure()

    def state(self, backend: str) -> str:
        return self.get(backend).state

    def forget(self, backend: str) -> None:
        """Drop a backend's breaker (pod churn: a recycled ip:port must not
        inherit the dead pod's state, and the registry must not grow
        unboundedly under replica turnover)."""
        self._breakers.pop(backend, None)

    def snapshot(self) -> Dict[str, str]:
        return {name: b.state for name, b in self._breakers.items()}
