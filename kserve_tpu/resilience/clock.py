"""Injectable monotonic clock shared by every resilience primitive.

Deadlines, breakers, retries, and fault injection all reason about time;
threading one clock object through them is what makes the chaos suite
deterministic — a test advances a `FakeClock` instead of sleeping, so
backoff schedules, breaker cooldowns, and deadline expiry are provable
in milliseconds of wall time.
"""

from __future__ import annotations

import asyncio
import time
from typing import List


class Clock:
    """Real monotonic time + asyncio sleep (the production default)."""

    def now(self) -> float:
        return time.monotonic()

    async def sleep(self, seconds: float) -> None:
        await asyncio.sleep(seconds)


class FakeClock(Clock):
    """Deterministic clock for chaos tests: `sleep` advances virtual time
    instantly (one event-loop yield), and `advance` moves time without any
    await — breaker cooldowns and deadline expiry become pure state."""

    def __init__(self, start: float = 0.0):
        self._now = start
        self.sleeps: List[float] = []  # every sleep requested, in order

    def now(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        self._now += seconds

    async def sleep(self, seconds: float) -> None:
        self.sleeps.append(seconds)
        self._now += max(seconds, 0.0)
        await asyncio.sleep(0)


MONOTONIC = Clock()
