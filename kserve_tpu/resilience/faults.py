"""Deterministic fault injection: the chaos suite's source of failure.

A `FaultPlan` is a seeded, ordered list of `FaultSpec`s.  Every call site
that honors faults asks `plan.decide(target)` with a stable target string
(the backend host for HTTP calls, `"engine.fetch"` for the engine's
device fetch); the plan matches by substring, counts matching calls, and
deterministically decides whether to inject.  Same plan + same call
sequence = same faults, which is what lets CI *prove* breakers trip and
deadlines fire instead of asserting they probably would.

Fault kinds:

- ``latency``        sleep `latency_s` on the plan's clock, then proceed
- ``connect_error``  the backend is unreachable (httpx.ConnectError)
- ``http_status``    a served error (5xx/429), optional Retry-After
- ``wedge``          the call hangs until the caller's deadline (httpx
                     ReadTimeout; the engine maps it to a wedged fetch)
- ``partial_stream`` a 200 whose body dies mid-stream
- ``preempt``        the engine forcibly requeues its newest active
                     sequence — the deterministic stand-in for spot/KV
                     preemption the drain/resume chaos tests fire
- ``replica_crash``  the process died mid-flight: the transport raises a
                     connect error (nothing is listening anymore) and the
                     engine's fetch path raises `ReplicaCrashError`, which
                     kills the run loop WITHOUT a drain — every in-flight
                     stream fails, nothing is checkpointed (the crash/churn
                     half of the fleet simulator, kserve_tpu/sim)
- ``clock_skew``     a slow replica: the injected `latency_s` is scaled by
                     ``skew`` (transport), and the simulator's stub device
                     multiplies its compute costs by the same factor — the
                     deterministic stand-in for thermal throttling or a
                     noisy neighbor

Gray-fault kinds (docs/resilience.md — failures that are NOT binary:
the backend stays up, answers probes, and quietly stops doing useful
work; detected by the engine watchdog + fleet health scoring, not by
liveness or breakers):

- ``slow_decode``    the backend serves, ``skew`` times slower: the
                     transport sleeps ``latency_s * skew`` then proceeds;
                     the simulator's stub device multiplies decode costs
                     (a degraded host that still answers everything)
- ``wedged_fetch``   the backend's fetch worker stops making progress
                     while the process stays alive: the transport raises
                     ReadTimeout; the simulator parks the replica's async
                     device fetches until a heal (liveness stays green —
                     the engine watchdog is what catches it)
- ``flapping``       alternates healthy and sick per matching call:
                     odd injections raise ConnectError, even ones sleep
                     ``latency_s * skew`` and proceed (a flapping NIC /
                     link that defeats naive consecutive-failure counts)

Peer-fabric kinds (docs/kv_hierarchy.md "Cross-replica page serving" —
the failure modes of fetching a KV page from another replica; the
client must verify, degrade to miss, and never fail admission):

- ``peer_corrupt``   the LYING peer: the real response is served with a
                     200 but its body has a byte flipped in transit (or
                     by bad peer disk/memory) — distinct from a 5xx,
                     only digest verification can catch it
- ``peer_partition`` the peer is unreachable (httpx.ConnectError): the
                     network partition / dead-pod case the breaker must
                     absorb so the fetcher degrades to local-only
- ``peer_slow``      the peer serves, ``latency_s * skew`` late: the
                     straggler the client's deadline cap bounds
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import httpx

from .clock import MONOTONIC, Clock


class ReplicaCrashError(RuntimeError):
    """An injected `replica_crash` fault fired inside the engine: the
    process is gone.  Unlike a wedge (liveness flips, pod restarts) or a
    drain (checkpoints flow), a crash loses everything in flight — the
    failure mode retry-from-scratch and token-exact accounting must
    survive, which is exactly what the fleet simulator injects it for."""


@dataclass
class FaultSpec:
    target: str  # substring matched against the call target
    # latency | connect_error | http_status | wedge | partial_stream |
    # preempt | replica_crash | clock_skew | slow_decode | wedged_fetch |
    # flapping | peer_corrupt | peer_partition | peer_slow
    kind: str
    status: int = 503
    latency_s: float = 0.0
    retry_after_s: Optional[float] = None
    probability: float = 1.0  # <1.0 draws from the plan's seeded RNG
    after: int = 0  # skip the first N matching calls
    count: Optional[int] = None  # inject at most N times (None = forever)
    # clock_skew multiplier: scales latency_s in the transport and the
    # stub device's compute costs in the fleet simulator
    skew: float = 1.0


class FaultPlan:
    """Seeded decision engine over an ordered spec list (first match wins)."""

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self._rng = random.Random(seed)
        self._seen: Dict[int, int] = {}
        self._injected: Dict[int, int] = {}
        self.log: List[Tuple[str, str]] = []  # (target, kind) per injection

    def decide(self, target: str) -> Optional[FaultSpec]:
        for i, spec in enumerate(self.specs):
            if spec.target not in target:
                continue
            seen = self._seen.get(i, 0)
            self._seen[i] = seen + 1
            if seen < spec.after:
                continue
            done = self._injected.get(i, 0)
            if spec.count is not None and done >= spec.count:
                continue
            if spec.probability < 1.0 and self._rng.random() >= spec.probability:
                continue
            self._injected[i] = done + 1
            self.log.append((target, spec.kind))
            return spec
        return None

    def injected(self, kind: Optional[str] = None) -> int:
        if kind is None:
            return len(self.log)
        return sum(1 for _, k in self.log if k == kind)

    def disarm(self, spec: FaultSpec) -> None:
        """Stop `spec` from injecting again WITHOUT removing it from the
        list — per-spec counters are keyed by list index, so removal would
        silently corrupt every later spec's state.  Used by callers that
        arm a one-shot fault against an event that may never come (the
        fleet simulator's crash-on-idle-replica case: an unconsumed
        replica_crash spec must not kill the restarted process)."""
        for i, s in enumerate(self.specs):
            if s is spec:
                s.count = self._injected.get(i, 0)
                return


class _TruncatedStream(httpx.AsyncByteStream):
    """A body that emits one partial JSON chunk then dies mid-read."""

    async def __aiter__(self):
        yield b'{"partial":'
        raise httpx.ReadError("injected partial stream")


class FaultInjectingTransport(httpx.AsyncBaseTransport):
    """httpx transport honoring a FaultPlan in front of a real handler.

    `handler(request) -> (status, payload)` serves pass-through calls
    (the in-memory stub idiom the router tests already use); a `bytes`
    payload becomes a binary octet-stream body (the peer page-server
    stub), anything else a JSON one.  Alternatively wrap an `inner`
    transport.  The target string handed to the plan is the request host
    (or the full url when host-less), with `target_suffix` appended —
    transports sharing one FaultPlan namespace themselves so a spec
    aimed at the peer-fetch path (target ``"replica-1/kv"``) can never
    collide with the client path's ``"replica-1/proxy"`` specs.
    """

    def __init__(
        self,
        plan: FaultPlan,
        handler: Optional[Callable] = None,
        inner: Optional[httpx.AsyncBaseTransport] = None,
        clock: Clock = MONOTONIC,
        target_suffix: str = "",
    ):
        self.plan = plan
        self.handler = handler
        self.inner = inner
        self.clock = clock
        self.target_suffix = target_suffix
        self.calls: List[str] = []  # pass-through + faulted targets, in order
        # flapping state: per-spec injection parity (odd = sick leg)
        self._flaps: Dict[int, int] = {}

    async def handle_async_request(self, request: httpx.Request) -> httpx.Response:
        target = (request.url.host or str(request.url)) + self.target_suffix
        self.calls.append(target)
        spec = self.plan.decide(target)
        if spec is not None:
            if spec.kind == "latency":
                await self.clock.sleep(spec.latency_s)
            elif spec.kind in ("clock_skew", "slow_decode", "peer_slow"):
                # a slow backend, not a dead one: the latency is the spec's
                # latency scaled by the skew factor, then the call proceeds
                await self.clock.sleep(spec.latency_s * spec.skew)
            elif spec.kind == "flapping":
                # alternates per injection: odd = link down, even = slow
                # but serving — the gray shape that defeats consecutive-
                # failure thresholds (it keeps resetting them)
                n = self._flaps[id(spec)] = self._flaps.get(id(spec), 0) + 1
                if n % 2:
                    raise httpx.ConnectError(
                        "injected flapping (down leg)", request=request)
                await self.clock.sleep(spec.latency_s * spec.skew)
            elif spec.kind == "wedged_fetch":
                # the backend's worker is stuck while the process lives:
                # from the network's view the read never completes
                raise httpx.ReadTimeout(
                    "injected wedged fetch", request=request)
            elif spec.kind == "connect_error":
                raise httpx.ConnectError("injected connect error", request=request)
            elif spec.kind == "peer_partition":
                # the peer side of the fence is unreachable; the page
                # client's breaker must open and degrade to local-only
                raise httpx.ConnectError(
                    "injected peer partition", request=request)
            elif spec.kind == "peer_corrupt":
                # the lying peer: serve the REAL response with one byte
                # flipped and a confident 200 — only the client's digest
                # verification stands between this and adopted garbage
                response = await self._serve(request, target)
                body = bytearray(await response.aread())
                if not body:
                    body = bytearray(b"\x00")
                body[len(body) // 2] ^= 0xFF
                return httpx.Response(
                    200, content=bytes(body),
                    headers={"content-type": "application/octet-stream"},
                    request=request,
                )
            elif spec.kind == "replica_crash":
                # the process is gone: connection refused from here on
                raise httpx.ConnectError(
                    "injected replica crash", request=request)
            elif spec.kind == "wedge":
                raise httpx.ReadTimeout("injected wedge", request=request)
            elif spec.kind == "partial_stream":
                return httpx.Response(
                    200, stream=_TruncatedStream(), request=request
                )
            elif spec.kind == "http_status":
                headers = {}
                if spec.retry_after_s is not None:
                    headers["Retry-After"] = f"{spec.retry_after_s:g}"
                return httpx.Response(
                    spec.status,
                    json={"error": f"injected {spec.status}"},
                    headers=headers,
                    request=request,
                )
            else:
                raise ValueError(f"unknown fault kind {spec.kind!r}")
        return await self._serve(request, target)

    async def _serve(self, request: httpx.Request, target: str) -> httpx.Response:
        """The pass-through leg (also the base response peer_corrupt flips)."""
        if self.inner is not None:
            return await self.inner.handle_async_request(request)
        if self.handler is None:
            return httpx.Response(
                200, json={"ok": True, "target": target}, request=request
            )
        status, payload = self.handler(request)
        if isinstance(payload, (bytes, bytearray)):
            return httpx.Response(
                status, content=bytes(payload),
                headers={"content-type": "application/octet-stream"},
                request=request,
            )
        return httpx.Response(status, json=payload, request=request)
