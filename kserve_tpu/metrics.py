"""Prometheus metrics for the request path.

Parity: reference python/kserve/kserve/metrics.py (per-stage latency
histograms labeled by model name); extended with engine-level counters used
by the JAX generative engine (tokens generated, batch occupancy) so KPA-style
tokens/sec autoscaling has a native signal.
"""

from __future__ import annotations

from prometheus_client import Counter, Gauge, Histogram

PRE_HIST_TIME = Histogram(
    "request_preprocess_seconds", "pre-process request latency", ["model_name"]
)
POST_HIST_TIME = Histogram(
    "request_postprocess_seconds", "post-process request latency", ["model_name"]
)
PREDICT_HIST_TIME = Histogram(
    "request_predict_seconds", "predict request latency", ["model_name"]
)
EXPLAIN_HIST_TIME = Histogram(
    "request_explain_seconds", "explain request latency", ["model_name"]
)

# Generative engine metrics (no reference analogue; vLLM keeps these internal).
GENERATED_TOKENS = Counter(
    "engine_generated_tokens_total", "decode tokens generated", ["model_name"]
)
PROMPT_TOKENS = Counter(
    "engine_prompt_tokens_total", "prompt tokens prefill-processed", ["model_name"]
)
ENGINE_BATCH_OCCUPANCY = Gauge(
    "engine_batch_occupancy", "active sequences in the decode batch", ["model_name"]
)
ENGINE_QUEUE_DEPTH = Gauge(
    "engine_queue_depth", "requests waiting for admission", ["model_name"]
)
ENGINE_KV_PAGES_FREE = Gauge(
    "engine_kv_pages_free", "free KV cache pages", ["model_name"]
)
ENGINE_WEDGED = Gauge(
    "engine_wedged", "1 once a device fetch blew the step deadline "
    "(liveness fails; pod restart expected)", ["model_name"]
)
ENGINE_PREEMPTIONS = Counter(
    "engine_preemptions_total",
    "sequences preempted back to the queue on KV pressure", ["model_name"],
)
ENGINE_KV_OFFLOAD_BYTES = Gauge(
    "engine_kv_offload_bytes",
    "KV bytes currently parked in the host-RAM tier", ["model_name"],
)
ENGINE_KV_DISK_BYTES = Gauge(
    "engine_kv_disk_bytes",
    "KV bytes currently parked in the disk tier", ["model_name"],
)

# Hierarchical KV store (kserve_tpu/kvstore — docs/kv_hierarchy.md).
# `tier` is the closed tier set (host | disk | persist — HBM never emits
# tier events: its eviction IS the host demote); `event` the closed
# movement enum.  No digest/request labels — per-digest detail lives in
# the /state prefix_store block.
KV_TIER_EVENTS = Counter(
    "kv_tier_events_total",
    "hierarchical KV store page movements (demote | pagein | drop | "
    "store | corrupt), by tier",
    ["tier", "event"],
)
# `tier` is the closed source set: hbm counts admission hits served from
# the device-resident prefix cache; host/disk/persist count tokens paged
# in from that tier (and therefore served as hits instead of prefilled);
# peer counts tokens paged in over the network from another replica's
# persistent store (kvstore/peer.py)
KV_PREFIX_HIT_TOKENS = Counter(
    "kv_prefix_hit_tokens_total",
    "prompt tokens served from cached prefix pages instead of being "
    "prefilled, by the tier that held them "
    "(hbm | host | disk | persist | peer)",
    ["model_name", "tier"],
)
KV_PAGEIN_SECONDS = Histogram(
    "kv_pagein_seconds",
    "wall time of one async prefix page-in: tier read scheduled -> pages "
    "uploaded and adopted into the HBM prefix cache",
    ["model_name"],
    buckets=(
        0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
        0.5, 1.0, 2.5, 5.0, float("inf"),
    ),
)

# Cross-replica KV page fabric (kvstore/peer.py — docs/kv_hierarchy.md
# "Cross-replica page serving").  `outcome` is the closed fetch-result
# enum; peer identity is a pod ip:port (unbounded under churn — the
# cardinality policy below) and lives in the scheduler_state() peer
# block and the EPP snapshots, never in a label.
KV_PEER_FETCH_TOTAL = Counter(
    "kv_peer_fetch_total",
    "cross-replica KV page fetch attempts by outcome: hit = verified and "
    "adopted, miss = peer answered 404, corrupt = payload failed digest "
    "verification (lying peer — also health evidence), timeout = "
    "transport failure / deadline / retries exhausted, breaker_open = "
    "skipped because the peer's circuit was open",
    ["outcome"],
)
KV_PEER_FETCH_SECONDS = Histogram(
    "kv_peer_fetch_seconds",
    "wall time of one peer page fetch: request issued -> payload "
    "digest-verified (successful fetches only)",
    buckets=(
        0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
        0.5, 1.0, 2.5, 5.0, float("inf"),
    ),
)
KV_PEER_PAGES_SERVED = Counter(
    "kv_peer_pages_served_total",
    "persisted px- pages this replica served to peers over "
    "GET /v1/internal/kv/pages/{digest}",
)

# Resilience layer (kserve_tpu/resilience — docs/resilience.md).
# Labeled by state only: backend identity is a pod ip:port, an unbounded
# label cardinality under replica churn (prometheus label children are
# never freed); per-backend state lives in the picker/router snapshots.
BREAKER_TRANSITIONS = Counter(
    "resilience_breaker_transitions_total",
    "circuit breaker state transitions",
    ["state"],
)
SHED_REQUESTS = Counter(
    "resilience_shed_requests_total",
    "requests bounced with 429 + Retry-After at admission",
    ["component"],
)
DEADLINE_REJECTED = Counter(
    "resilience_deadline_rejected_total",
    "requests rejected because their propagated deadline had expired",
    ["component"],
)
# Retry amplification: every retry a client loop issues BEYOND the first
# attempt.  rate(request_retry_attempts_total) / rate(first attempts)
# is the fleet's amplification factor; the simulator asserts it stays
# bounded (<= 2x) under churn, and production dashboards alarm on the
# same series.  Components are the literal set of in-repo retry loops:
# rest (inference_client REST), grpc (inference_client gRPC), graph
# (graph router steps), cluster (api.http_transport flow control), sim
# (the fleet simulator's client loop).
RETRY_ATTEMPTS = Counter(
    "request_retry_attempts_total",
    "retry attempts issued beyond a request's first try, per client loop",
    ["component"],
)

# Lifecycle layer (kserve_tpu/lifecycle — docs/lifecycle.md): graceful
# drain + preemption-safe resumable generation.
LIFECYCLE_STATE = Gauge(
    "replica_lifecycle_state",
    "1 for the replica's current lifecycle state "
    "(STARTING/READY/DRAINING/TERMINATING), 0 otherwise",
    ["state"],
)
DRAIN_DURATION = Histogram(
    "lifecycle_drain_duration_seconds",
    "wall time from drain start (SIGTERM / POST /admin/drain) until every "
    "in-flight generation finished or was checkpointed",
)
GENERATION_CHECKPOINTS = Counter(
    "generation_checkpoints_total",
    "live generations snapshotted into portable checkpoints",
    ["model_name", "reason"],
)
GENERATION_RESUMES = Counter(
    "generation_resumes_total",
    "generations resumed from a checkpoint on this replica",
    ["model_name"],
)
TOKENS_SALVAGED = Counter(
    "generation_tokens_salvaged_total",
    "decoded tokens carried across a drain/preemption via checkpoint "
    "instead of being re-decoded from scratch",
    ["model_name"],
)

# Gray-failure immune system (engine/watchdog.py + scheduler/health.py —
# docs/resilience.md).  `stat` and `transition` are closed enums; replica
# identity is deliberately NOT a label (unbounded under churn — the
# cardinality policy above): per-replica scores/status ride the picker
# snapshot and EPP /state.  `reason` comes from the closed checkpoint
# reason set ("stall" = watchdog self-drain rescued the stream, "hedge" =
# the client's inter-token hedge migrated it off a slow replica).
REPLICA_HEALTH_SCORE = Gauge(
    "replica_health_score",
    "fleet health-score distribution at the latest poll (min | median | "
    "max over replicas; per-replica scores live in the EPP /state)",
    ["stat"],
)
QUARANTINE_TRANSITIONS = Counter(
    "replica_quarantine_transitions_total",
    "gray-failure health state transitions "
    "(quarantine | reintroduce | degrade | restore)",
    ["transition"],
)
GENERATION_MIGRATIONS = Counter(
    "generation_migrations_total",
    "live generations migrated off a sick replica and resumed elsewhere, "
    "by trigger (stall = watchdog self-drain checkpoint, hedge = "
    "client-side inter-token-gap hedge)",
    ["reason"],
)


def record_quarantine_transition(transition: str) -> None:
    """FleetHealth transition hook; replica identity stays in /state."""
    QUARANTINE_TRANSITIONS.labels(transition=transition).inc()


def record_generation_migration(reason: str) -> None:
    GENERATION_MIGRATIONS.labels(reason=reason).inc()

# Request-lifecycle telemetry (kserve_tpu/observability — the serving
# metrics that matter per the vLLM/TGI comparative study, arXiv:2511.17593).
# Sub-millisecond buckets on ITL because decode steps on-chip are ~1-10ms;
# TTFT/e2e reach minutes because long-prompt prefill + queueing legitimately
# do.  All observations come from the engine's injectable Clock.
_TTFT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, float("inf"),
)
_ITL_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, float("inf"),
)
REQUEST_TTFT = Histogram(
    "request_ttft_seconds",
    "time to first token: request received by the engine -> first token "
    "emitted (queue wait included — the client experiences it)",
    ["model_name"], buckets=_TTFT_BUCKETS,
)
REQUEST_ITL = Histogram(
    "request_inter_token_seconds",
    "inter-token latency: gap between consecutive emitted tokens",
    ["model_name"], buckets=_ITL_BUCKETS,
)
REQUEST_QUEUE_WAIT = Histogram(
    "request_queue_wait_seconds",
    "received -> admitted into a decode slot (first admission)",
    ["model_name"], buckets=_TTFT_BUCKETS,
)
REQUEST_E2E = Histogram(
    "request_e2e_seconds",
    "received -> finished (full generation wall time)",
    ["model_name"], buckets=_TTFT_BUCKETS,
)
ENGINE_STEP_DURATION = Histogram(
    "engine_decode_step_seconds",
    "wall time of one decode step: a steps_per_sync-token chunk dispatched "
    "and its tokens fetched",
    ["model_name"], buckets=_ITL_BUCKETS,
)
ENGINE_PREFILL_CHUNK_DURATION = Histogram(
    "engine_prefill_chunk_seconds",
    "wall time of one compiled prefill call (batched admission or one "
    "long-prompt chunk)",
    ["model_name"], buckets=_TTFT_BUCKETS,
)
# `program` is the fixed compiled-program name set (engine/compiled.py),
# bounded by construction — NOT a shape signature (unbounded under bucket
# drift) nor a request attribute
XLA_COMPILES = Counter(
    "engine_xla_compiles_total",
    "XLA compilations observed (jit cache misses incl. retraces), by "
    "compiled engine program",
    ["program"],
)
# `role` is a closed enum (decoding/prefilling/free): batch composition per
# engine step without per-request labels
ENGINE_STEP_BATCH_COMPOSITION = Gauge(
    "engine_step_batch_composition",
    "decode-batch slots by role at the latest engine step "
    "(decoding | prefilling | free); under the unified ragged program the "
    "roles are token counts (prefill_tokens | decode_tokens), and with "
    "speculative decoding additionally spec_accepted_tokens — the latest "
    "dispatch's accepted-draft length",
    ["model_name", "role"],
)
# Speculative decoding (docs/kernels.md): `outcome` is the closed
# drafted | accepted | rejected set.  accepted/drafted is the fleet's
# live acceptance rate; every ACCEPTED token is also counted in
# engine_generated_tokens_total (these series classify drafts, they do
# not double-count output).
SPEC_TOKENS = Counter(
    "engine_spec_tokens_total",
    "speculative-decoding draft tokens by outcome (drafted | accepted | "
    "rejected); bonus target samples are ordinary generated tokens and "
    "are not counted here",
    ["model_name", "outcome"],
)

# Replica startup phases (kserve_tpu/engine/aot_cache.py — docs/coldstart.md).
# `phase` is the closed STARTUP_PHASES enum; buckets reach minutes because a
# cold 8B compile + weight load legitimately does.
STARTUP_PHASES = ("trace", "compile", "aot_load", "weights", "ready")
_STARTUP_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0, float("inf"),
)
ENGINE_STARTUP = Histogram(
    "engine_startup_seconds",
    "replica startup wall time by phase: trace (jaxpr+lowering), compile "
    "(XLA), aot_load (executable deserialization from the AOT cache), "
    "weights (checkpoint read + device placement), ready (total "
    "construct->serving)",
    ["model_name", "phase"], buckets=_STARTUP_BUCKETS,
)
# `program` is the fixed compiled-program name set (same bound as
# engine_xla_compiles_total); `event` is a closed enum
AOT_CACHE_EVENTS = Counter(
    "engine_aot_cache_events_total",
    "persistent AOT executable cache events (hit | miss | store | invalid), "
    "by compiled engine program",
    ["program", "event"],
)


# Autoscaler (kserve_tpu/autoscale — docs/autoscaling.md).  `action` and
# `reason` come from the closed ACTIONS/REASONS sets in autoscale/policy.py
# (every decision is explained in the same vocabulary dashboards see);
# `signal` is the fixed FleetSignals field enum; `outcome` the closed
# hold-queue terminal set.  No per-replica/backend labels — per-replica
# detail lives in the EPP /state snapshot.
AUTOSCALER_DECISIONS = Counter(
    "autoscaler_decisions_total",
    "scaling decisions taken by the EPP-signal autoscaler loop, by action "
    "and policy reason",
    ["action", "reason"],
)
AUTOSCALER_TARGET_REPLICAS = Gauge(
    "autoscaler_target_replicas",
    "replica count the autoscaler currently wants (post-clamp)",
)
AUTOSCALER_SIGNAL = Gauge(
    "autoscaler_signal",
    "fleet-wide autoscaling signals at the latest decision tick "
    "(ready_replicas | queue_depth | inflight | shed_rate_per_s | "
    "arrival_rate_per_s | held_requests | ttft_p99_s)",
    ["signal"],
)
GATEWAY_HOLDS = Counter(
    "gateway_hold_outcomes_total",
    "zero-window hold-and-replay outcomes at the gateway "
    "(replayed | expired | overflow | failed)",
    ["outcome"],
)


def observe_startup_phase(model_name: str, phase: str, seconds: float) -> None:
    """Record one engine_startup_seconds observation (phase must be in
    STARTUP_PHASES; anything else is a programming error worth raising)."""
    if phase not in STARTUP_PHASES:
        raise ValueError(f"unknown startup phase {phase!r}")
    ENGINE_STARTUP.labels(model_name=model_name, phase=phase).observe(seconds)


def observe_request_timeline(model_name: str, timeline) -> None:
    """Export one finished RequestTimeline to the Prometheus histograms
    (observability/timeline.py keeps the ring-buffer/percentile view)."""
    if timeline.queue_wait_s is not None:
        REQUEST_QUEUE_WAIT.labels(model_name=model_name).observe(
            timeline.queue_wait_s)
    if timeline.ttft_s is not None:
        REQUEST_TTFT.labels(model_name=model_name).observe(timeline.ttft_s)
    if timeline.e2e_s is not None:
        REQUEST_E2E.labels(model_name=model_name).observe(timeline.e2e_s)
    itl = REQUEST_ITL.labels(model_name=model_name)
    for gap in timeline.itls:
        itl.observe(gap)


_LIFECYCLE_STATES = ("STARTING", "READY", "DRAINING", "TERMINATING")


def set_lifecycle_state(state: str) -> None:
    """One-hot the lifecycle gauge (the PromQL-friendly enum idiom)."""
    for s in _LIFECYCLE_STATES:
        LIFECYCLE_STATE.labels(state=s).set(1.0 if s == state else 0.0)


def record_breaker_transition(backend: str, state: str) -> None:
    """The BreakerRegistry on_transition hook (resilience/breaker.py);
    `backend` is part of the hook signature but deliberately not a label."""
    BREAKER_TRANSITIONS.labels(state=state).inc()


def get_labels(model_name: str) -> dict:
    return {"model_name": model_name}
