"""Cycle-accurate stub device for the fleet simulator.

`build_stub_programs()` returns an object with the exact attribute
surface of `engine.compiled.CompiledPrograms`, so a real `LLMEngine`
runs its real admission, batching, chunked prefill, preemption, drain
and checkpoint logic against it — only the device math is replaced:

- tokens come from a deterministic chain (`stub_first_token` /
  `stub_next_token`) that is a pure function of prompt length and
  position, so the SAME stream continues token-exactly across
  preemption, checkpoint and cross-replica resume — which is what lets
  the goodput report prove zero lost / zero duplicated tokens without
  comparing against a second uninterrupted run;
- compute costs are configurable virtual durations (`StubCosts`)
  charged to a per-replica `StubDevice` timeline, paid when the engine
  fetches the result: the decode hot loop awaits them on the SimClock
  (fleet compute overlaps), sync prefill fetches jump the clock
  (conservative, one call per admission batch);
- a `clock_skew` FaultSpec targeting ``<replica>.compute`` (or a direct
  `device.skew` knob) multiplies costs — the deterministic slow-replica
  stand-in.

`SimFetcher` replaces the engine's daemon fetch worker with an
event-loop-thread implementation: thread handoff order is the one piece
of nondeterminism a byte-identical simulation cannot keep.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import numpy as np

# token ids the stub emits: a printable-ASCII band, clear of BOS/EOS/PAD
# (ByteTokenizer reserves 256..258) so streams never hit an accidental
# EOS and detokenize to readable text
SAFE_LO = 32
SAFE_BAND = 64


def stub_first_token(prompt_len: int) -> int:
    """First sampled token for a prompt of `prompt_len` tokens."""
    return SAFE_LO + (prompt_len * 31 + 17) % SAFE_BAND


def stub_next_token(prev: int, pos: int) -> int:
    """Decode chain: the token decoded at KV position `pos` given the
    previous token.  Depending only on (prev, pos) is what makes the
    chain resumable: a checkpointed stream re-seated anywhere continues
    with exactly the token the uninterrupted stream would have had."""
    return SAFE_LO + ((prev - SAFE_LO) * 7 + pos * 13 + 29) % SAFE_BAND


def stub_spec_accept(prev: int, pos: int, k_drafts: int) -> int:
    """Tokens emitted by one speculative verify round for a lane whose
    chain state is (prev token, kv position): 1..k_drafts+1, a pure
    function of the CHAIN STATE — not of wall time, lane index or batch
    composition — so the acceptance pattern is byte-identical per seed
    AND resumes token-exactly: a checkpointed stream re-seated anywhere
    replays the same accept/reject sequence the uninterrupted stream had
    (the `expected_stream` oracle keeps holding with speculation on)."""
    return 1 + (prev * 7 + pos * 11 + 3) % (k_drafts + 1)


def expected_stream(prompt_len: int, n_tokens: int) -> List[int]:
    """The exact token stream a request with `prompt_len` prompt tokens
    generates — the goodput report's token-accounting oracle."""
    if n_tokens <= 0:
        return []
    out = [stub_first_token(prompt_len)]
    for k in range(1, n_tokens):
        out.append(stub_next_token(out[-1], prompt_len + k - 1))
    return out


@dataclass
class StubCosts:
    """Virtual compute costs, per compiled-program dispatch."""

    prefill_base_s: float = 2e-3  # fixed launch cost per prefill call
    prefill_per_token_s: float = 2e-5  # per prompt token in the call
    decode_step_s: float = 2e-3  # per decode step (chunk = steps_per_sync)
    inject_s: float = 1e-3  # per KV-injection scatter
    # replica-start costs (the AOT-cache story, docs/coldstart.md): a COLD
    # build pays compile_s (XLA-compiling the program set before ready — on
    # a chip this is tens of seconds); a WARM build pays aot_load_s
    # (deserializing persisted executables — orders of magnitude cheaper).
    # Charged once at StubPrograms build, so the cold/warm ready-time delta
    # is assertable in tier-1.  Default 0 keeps pre-AOT scenarios unchanged.
    compile_s: float = 0.0
    aot_load_s: float = 0.0
    # speculative decoding (docs/kernels.md): each mixed_decode round
    # costs one decode step PLUS this much per draft token verified — the
    # ragged multi-token chunk is more compute than a single-token step,
    # but far less than K separate dispatches.  With the stub's seeded
    # acceptance pattern (avg (K+2)/2 tokens per round) the default makes
    # decode-heavy spec traffic >2x tok/s in virtual time at K=4.
    spec_verify_per_token_s: float = 2e-4
    # kernel block-granularity modeling (docs/kernels.md dense packing):
    # on the modeled TPU the ragged kernel walks this-many-token query
    # blocks, so a mixed dispatch pays (align-1) wasted token-slots of
    # step-0 compute PER DECODE LANE (each single-token lane burns a
    # whole block), which the dense mixed_decode packing avoids.  0 (the
    # default) disables the charge — every pre-dense scenario's virtual
    # timeline stays byte-identical; bench --mode spec sets 8 (RAGGED_BQ)
    # to price the K=0 dense-packing win in sim terms.
    ragged_align_tokens: int = 0

    @classmethod
    def from_oracle(cls, budgets: dict, decode_step_s: float = 2e-3,
                    variant: str = "tp1", **overrides) -> "StubCosts":
        """Derive cost RATIOS from the HLO perf oracle's committed
        budgets (analysis/hlo_oracle, perf_budgets.json) instead of
        inventing them: anchor one wall-clock number — `decode_step_s`,
        a measured (or assumed) per-decode-step latency — and scale the
        other program costs by their oracle-extracted FLOP/byte ratios
        (ROADMAP 5b: sim SLO numbers become predictions, not fictions).

        - prefill_per_token_s: decode's seconds-per-flop times the
          largest prefill bucket's flops-per-token;
        - inject_s: decode_step_s scaled by the inject/decode-step
          bytes-accessed ratio (the scatter is bandwidth-, not
          flop-bound);
        - spec_verify_per_token_s: the extra flops a K-draft
          mixed_decode round carries over a plain decode step, divided
          by K, priced at decode's seconds-per-flop.

        Programs missing from the budgets keep the dataclass defaults;
        `overrides` pin any field explicitly.  Raises ValueError when
        the decode anchor itself is missing — a cost model silently
        built from nothing would be the old fiction with better
        branding."""
        programs = budgets.get("programs", budgets)

        def _norm(entry, field, default=1):
            return max(int(entry.get("norm", {}).get(field, default)), 1)

        decode = programs.get(f"{variant}/decode")
        if not decode or not decode.get("flops"):
            raise ValueError(
                f"from_oracle: no usable {variant}/decode entry in the "
                "budgets (run `python -m kserve_tpu.analysis.hlo_oracle "
                "update`)")
        steps = _norm(decode, "steps")
        flops_per_step = float(decode["flops"]) / steps
        bytes_per_step = float(decode.get("bytes_accessed", 0.0)) / steps
        s_per_flop = decode_step_s / flops_per_step
        fields: dict = {"decode_step_s": decode_step_s}

        prefills = sorted(
            (k, e) for k, e in programs.items()
            if k.startswith(f"{variant}/prefill/b") and e.get("flops"))
        if prefills:
            _, pf = prefills[-1]  # largest bucket: the steady-state shape
            fields["prefill_per_token_s"] = s_per_flop * (
                float(pf["flops"]) / _norm(pf, "tokens"))

        inject = programs.get(f"{variant}/inject")
        if inject and inject.get("bytes_accessed") and bytes_per_step:
            fields["inject_s"] = decode_step_s * (
                float(inject["bytes_accessed"]) / bytes_per_step)

        spec = [
            e for k, e in programs.items()
            if f"/mixed_decode/k" in k and k.startswith(variant)
            and e.get("norm", {}).get("k") and e.get("flops")
        ]
        if spec:
            e = spec[0]
            k = int(e["norm"]["k"])
            round_flops = float(e["flops"]) / _norm(e, "steps")
            extra = max(round_flops - flops_per_step, 0.0)
            fields["spec_verify_per_token_s"] = s_per_flop * extra / k
        fields.update(overrides)
        return cls(**fields)


class StubDevice:
    """One replica's device timeline: dispatches accumulate `busy_until`,
    fetches wait for it.  `skew` (set directly or via a clock_skew /
    slow_decode fault targeting ``<name>.compute``) multiplies every
    subsequent cost.

    Gray-failure knobs (docs/resilience.md — the replica stays alive and
    pollable through all of these; detection belongs to the engine
    watchdog and fleet health scoring, never to liveness):

    - ``wedge_fetch_until(t)`` parks the ASYNC fetch path until virtual
      time `t`: dispatches land, the fetch worker just never delivers —
      the stall shape the engine watchdog exists to confirm.  Sync
      fetches (batched-prefill admission) ignore it: a sync clock jump
      to the wedge horizon would drag the whole fleet's virtual time
      forward.
    - ``flap(period_s, skew)`` alternates compute between normal and
      ``skew``-slow in `period_s` windows — a flapping host that defeats
      consecutive-failure counting.
    """

    def __init__(self, name: str, costs: StubCosts, clock):
        self.name = name
        self.costs = costs
        self.clock = clock
        self.busy_until = 0.0
        self.skew = 1.0
        self.wedged_until = 0.0
        self.flap_period_s = 0.0
        self.flap_skew = 1.0
        # resilience.FaultPlan shared with the engine (SimReplica wires it)
        self.fault_plan = None
        self.dispatches = 0

    def wedge_fetch_until(self, until_s: float) -> None:
        self.wedged_until = max(self.wedged_until, until_s)

    def flap(self, period_s: float, skew: float) -> None:
        self.flap_period_s = period_s
        self.flap_skew = skew

    def heal_gray(self) -> None:
        """Clear every gray-failure knob (the heal_skew churn leg)."""
        self.skew = 1.0
        self.wedged_until = 0.0
        self.flap_period_s = 0.0
        self.flap_skew = 1.0

    def _effective_skew(self, now: float) -> float:
        s = self.skew
        if self.flap_period_s > 0 and int(now / self.flap_period_s) % 2:
            s *= self.flap_skew
        return s

    def dispatch(self, cost_s: float) -> None:
        now = self.clock.now()
        cost = cost_s * self._effective_skew(now)
        if self.fault_plan is not None:
            spec = self.fault_plan.decide(f"{self.name}.compute")
            if spec is not None and spec.kind in ("clock_skew", "slow_decode"):
                cost *= spec.skew
        self.dispatches += 1
        self.busy_until = max(self.busy_until, now) + cost

    def reset(self) -> None:
        """Fresh device for a restarted replica."""
        self.busy_until = 0.0
        self.heal_gray()


class SimFetcher:
    """Duck-type of engine.types._DeadlineFetcher that runs the fetch thunk
    on the event-loop thread and pays the stub device's accumulated compute
    time in virtual seconds: the async path parks on the SimClock (other
    replicas keep running — fleet overlap), the sync path jumps the clock
    (the engine's batched-prefill fetch is synchronous by design)."""

    def __init__(self, device: StubDevice, clock):
        self.device = device
        self.clock = clock

    def fetch(self, fn, timeout_s: float):
        # sync fetches deliberately ignore the gray wedge (see
        # StubDevice.wedge_fetch_until): jumping the shared clock to the
        # wedge horizon would fast-forward the whole fleet
        out = fn()
        self.clock.advance_to(self.device.busy_until)
        return out

    async def fetch_async(self, fn, timeout_s: float):
        out = fn()
        # a gray-wedged fetch worker: the result exists on the "device",
        # it just never gets delivered until the wedge lifts — liveness
        # stays green, the step deadline never fires (the sim fetcher
        # has no wedge deadline by design), and only the engine
        # watchdog's no-progress detection catches it
        await self.clock.sleep_until(
            max(self.device.busy_until, self.device.wedged_until))
        return out

    def close(self) -> None:
        pass


class StubPrograms:
    """CompiledPrograms-shaped set of host-math device programs.

    Every function matches the jitted signature it replaces (see
    engine/compiled.py) and returns plain numpy arrays — the engine's
    `_fetch`/`_fetch_async` np.asarray conversion is then a no-op and all
    cost accounting lives on the StubDevice timeline."""

    def __init__(self, engine_config, device: StubDevice,
                 vocab_size: int = 512, warm: bool = False):
        self._cfg = engine_config
        self._device = device
        self._vocab = vocab_size
        self._K = engine_config.max_logprobs
        # replica-start cost (mirrors engine.aot_warmup running BEFORE the
        # replica turns ready): a cold build XLA-compiles the program set,
        # a warm build deserializes it from the node's AOT cache
        self.warm = warm
        self.startup_cost_s = (
            device.costs.aot_load_s if warm else device.costs.compile_s)
        if self.startup_cost_s > 0:
            device.dispatch(self.startup_cost_s)
        self.prefill = self._make_prefill(False)
        self.prefill_lp = self._make_prefill(True)
        self.prefill_chunk = self._prefill_chunk
        self.sample_first = self._make_sample_first(False)
        self.sample_first_lp = self._make_sample_first(True)
        self.decode = self._make_decode(False, False)
        self.decode_lp = self._make_decode(False, True)
        self.decode_penalized = self._make_decode(True, False)
        self.decode_penalized_lp = self._make_decode(True, True)
        self.inject = self._inject
        self.inject_q = self._inject_q
        self.mixed = self._mixed
        # the dense/speculative decode program exists only when the
        # engine config asks for it — pre-spec scenarios keep their
        # byte-identical traces (the engine falls back to mixed-only
        # when the attribute is absent)
        if getattr(engine_config, "spec_decode_k", None) is not None:
            self.mixed_decode = self._mixed_decode

    # ---------------- prefill ----------------

    def _charge_prefill(self, valid: np.ndarray) -> None:
        c = self._device.costs
        self._device.dispatch(
            c.prefill_base_s + c.prefill_per_token_s * int(valid.sum()))

    def _lp_zeros(self, *lead):
        lp = np.zeros(lead, np.float32)
        tv = np.zeros(lead + (self._K,), np.float32)
        ti = np.zeros(lead + (self._K,), np.int32)
        return lp, tv, ti

    def _make_prefill(self, with_logprobs: bool):
        def fn(params, tokens, valid_len, kv_pages, page_ids, state, rng,
               adapters):
            valid = np.asarray(valid_len)
            self._charge_prefill(valid)
            # fused prefill carries the whole (uncached) sequence per row,
            # so the row's total length IS its valid count
            first = np.asarray(
                [stub_first_token(int(v)) for v in valid], np.int32)
            if with_logprobs:
                return first, self._lp_zeros(valid.shape[0]), kv_pages
            return first, kv_pages

        return fn

    def _prefill_chunk(self, params, tokens, chunk_start, valid_len,
                       kv_pages, page_ids, adapters):
        start = np.asarray(chunk_start)
        valid = np.asarray(valid_len)
        self._charge_prefill(valid)
        # "logits" carry each row's total prefilled length so sample_first
        # reproduces the fused path's first token exactly: chunk_start +
        # valid == full sequence length on the final chunk, whether the
        # prefix came from the cache, earlier chunks, or both
        return _StubLogits(start + valid), kv_pages

    def _make_sample_first(self, with_logprobs: bool):
        def fn(logits, state, rng, in_prompt):
            totals = logits.totals
            first = np.asarray(
                [stub_first_token(int(t)) for t in totals], np.int32)
            if with_logprobs:
                return first, self._lp_zeros(first.shape[0])
            return first

        return fn

    # ---------------- decode ----------------

    def _make_decode(self, with_penalties: bool, with_logprobs: bool):
        def fn(params, tokens, pos, kv_pages, page_table, active, capacity,
               counters, state, rng, adapters, *penalty_arrays):
            steps = self._cfg.steps_per_sync
            tok = np.asarray(tokens)
            pos_np = np.asarray(pos)
            act = np.asarray(active)
            cap = np.asarray(capacity)
            B = tok.shape[0]
            self._device.dispatch(self._device.costs.decode_step_s * steps)
            chunk = np.zeros((steps, B), np.int32)
            for i in range(B):
                if not act[i]:
                    continue
                prev = int(tok[i])
                p = int(pos_np[i])
                limit = int(cap[i])
                for s in range(steps):
                    if p + s < limit:
                        # capacity-capped lanes freeze at their last real
                        # token (mirrors the jitted program's mask), so a
                        # chained chunk's tokens_dev row is always the
                        # correct chain predecessor
                        prev = stub_next_token(prev, p + s)
                    chunk[s, i] = prev
            out = chunk
            if with_logprobs:
                out = (chunk,) + self._lp_zeros(steps, B)
            if with_penalties:
                # counts array rides through untouched (host penalty state
                # is refreshed from slot lists, never read back)
                return out, kv_pages, penalty_arrays[1]
            return out, kv_pages

        return fn

    # ---------------- unified ragged (mixed) program ----------------

    def _mixed(self, params, q_tokens, token_seq, token_pos, q_start,
               q_len, kv_start, last_idx, kv_pages, page_table, joins,
               scan_tok0, scan_pos0, step0_emits, capacity, counters,
               state, rng, adapters):
        """Host-math twin of engine/compiled.py's mixed program, emitting
        the SAME deterministic token chain as the legacy stub paths so
        checkpoint/resume stays token-exact across both program sets and
        `expected_stream()` remains the oracle.

        Step-0 discrimination mirrors the engine's packing contract: a
        lane sampling its FIRST token has counters==0 (stub_first_token of
        its full sequence length); a decode lane has counters>=1 and
        continues the chain from its packed token; a resume boundary
        (step0_emits==0 with scan_tok0>=0) re-enters the chain at its
        checkpointed token."""
        steps = self._cfg.steps_per_sync
        toks = np.asarray(q_tokens)
        qs = np.asarray(q_start)
        ql = np.asarray(q_len)
        ks = np.asarray(kv_start)
        jn = np.asarray(joins)
        st0 = np.asarray(scan_tok0)
        sp0 = np.asarray(scan_pos0)
        emits0 = np.asarray(step0_emits)
        cap = np.asarray(capacity)
        cnt = np.asarray(counters)
        B = qs.shape[0]
        # cost: the ragged step pays prefill for every packed prompt
        # token (non-decode lanes) + the scan pays the decode chunk
        c = self._device.costs
        n_prefill = int(sum(
            int(ql[i]) for i in range(B)
            if ql[i] > 0 and not (emits0[i] == 1 and cnt[i] >= 1)
        ))
        cost = c.decode_step_s * steps
        if n_prefill:
            cost += c.prefill_base_s + c.prefill_per_token_s * n_prefill
        if c.ragged_align_tokens > 1:
            # block-granularity waste: every decode lane's single-token
            # slice burns a whole align-token kernel block in step 0 —
            # the cost the dense mixed_decode packing exists to avoid
            n_decode = int(sum(
                1 for i in range(B)
                if ql[i] > 0 and emits0[i] == 1 and cnt[i] >= 1))
            cost += (n_decode * (c.ragged_align_tokens - 1)
                     * c.prefill_per_token_s)
        self._device.dispatch(cost)
        chunk = np.zeros((steps, B), np.int32)
        for i in range(B):
            if ql[i] <= 0:
                continue
            decode_lane = emits0[i] == 1 and cnt[i] >= 1
            if decode_lane:
                # packed token is generated[-1] at position kv_start
                s0 = stub_next_token(int(toks[qs[i]]), int(ks[i]))
            else:
                # a completed (or still-chunking: discarded) prompt slice
                s0 = stub_first_token(int(ks[i]) + int(ql[i]))
            chunk[0, i] = s0
            prev = int(st0[i]) if st0[i] >= 0 else s0
            p = int(sp0[i])
            limit = int(cap[i])
            for s in range(1, steps):
                if jn[i] and p < limit:
                    prev = stub_next_token(prev, p)
                    p += 1
                chunk[s, i] = prev
        return chunk, kv_pages

    # ---------------- dense / speculative decode (mixed_decode) ----------------

    def _mixed_decode(self, params, tokens, pos, kv_pages, page_table,
                      live, capacity, counters, draft_table, state, rng,
                      adapters):
        """Host-math twin of engine/compiled.py's mixed_decode: every
        round each live lane with page capacity for a full (K+1)-token
        slice emits `stub_spec_accept(prev, pos)` tokens of the SAME
        deterministic chain the other stub programs emit — acceptance
        varies, the token stream never does, so `expected_stream()` stays
        the oracle and the goodput report's zero-lost/zero-duplicated
        accounting covers speculative traffic.  Returns the engine
        contract: ([rounds, B, K+1] tokens, [rounds, B] emit counts,
        kv_pages, draft_table, and the final (token, pos, counters)
        carry for depth-2 chaining)."""
        cfg = self._cfg
        K = cfg.spec_decode_k or 0
        Kp = K + 1
        rounds = cfg.steps_per_sync
        tok = np.array(np.asarray(tokens), np.int64)
        p = np.array(np.asarray(pos), np.int64)
        cnt = np.array(np.asarray(counters), np.int64)
        lv = np.asarray(live)
        cap = np.asarray(capacity)
        B = tok.shape[0]
        c = self._device.costs
        self._device.dispatch(
            rounds * (c.decode_step_s + c.spec_verify_per_token_s * K))
        toks = np.zeros((rounds, B, Kp), np.int32)
        n = np.zeros((rounds, B), np.int32)
        for r in range(rounds):
            for i in range(B):
                if not lv[i] or p[i] + Kp > cap[i]:
                    continue  # capacity-starved lanes sit the round out
                acc = stub_spec_accept(int(tok[i]), int(p[i]), K)
                prev = int(tok[i])
                pp = int(p[i])
                for j in range(acc):
                    prev = stub_next_token(prev, pp)
                    pp += 1
                    toks[r, i, j] = prev
                n[r, i] = acc
                tok[i] = prev
                p[i] = pp
                cnt[i] += acc
        return (toks, n, kv_pages, draft_table, tok.astype(np.int32),
                p.astype(np.int32), cnt.astype(np.int32))

    # ---------------- KV injection (P/D, tier-store resume) ----------------

    def _inject(self, kv_pages, kv_data, ids):
        self._device.dispatch(self._device.costs.inject_s)
        return kv_pages

    def _inject_q(self, kv_pages, q, s, ids):
        self._device.dispatch(self._device.costs.inject_s)
        return kv_pages


class _StubLogits:
    """Per-row total prefilled length, standing in for the [B, V] logits
    the real chunked prefill hands to sample_first."""

    __slots__ = ("totals",)

    def __init__(self, totals: np.ndarray):
        self.totals = np.asarray(totals, np.int64)


def build_stub_programs(engine_config, device: StubDevice,
                        vocab_size: int = 512,
                        warm: bool = False) -> StubPrograms:
    return StubPrograms(engine_config, device, vocab_size=vocab_size,
                        warm=warm)
