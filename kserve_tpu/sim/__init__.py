"""Fleet-scale deterministic simulation (docs/simulation.md).

N real `LLMEngine` replicas over a cycle-accurate stub device, routed by
the real EPP picker through the real resilience/lifecycle layers, driven
by one discrete-event SimClock and seeded workload/churn generators —
so SLO goodput under churn (p99 TTFT/ITL, zero lost tokens, bounded
retry amplification) is a per-PR CPU regression test instead of a
live-chip experiment.
"""

from .clock import SimClock, SimDeadlockError  # noqa: F401
from .fleet import ClientRecord, FleetSim, run_scenario  # noqa: F401
from .replica import (  # noqa: F401
    SIM_ADAPTERS,
    SIM_MODEL_NAME,
    ReplicaSpec,
    SimReplica,
)
from .report import (  # noqa: F401
    SLOBudget,
    SLOViolation,
    assert_slo,
    build_report,
    canonical_json,
)
from .scenario import (  # noqa: F401
    AutoscalerSpec,
    ChurnEvent,
    Scenario,
    autoscale_burst_scenario,
    autoscale_smoke_scenario,
    churn_10k_scenario,
    gray_failure_scenario,
    peer_fabric_scenario,
    prefix_store_scenario,
    scale_zero_scenario,
    smoke_scenario,
    spec_decode_scenario,
)
from .stub import (  # noqa: F401
    SimFetcher,
    StubCosts,
    StubDevice,
    StubPrograms,
    build_stub_programs,
    expected_stream,
    stub_first_token,
    stub_next_token,
    stub_spec_accept,
)
from .workload import SimRequest, WorkloadConfig, generate_trace  # noqa: F401
