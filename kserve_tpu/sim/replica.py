"""One simulated serving replica: a REAL LLMEngine over a stub device.

The engine here is the production class, not a model of it — admission,
continuous batching, chunked prefill, KV paging, preemption, drain and
checkpointing all execute the code that serves traffic, against
`stub.StubPrograms` for the device math and `stub.SimFetcher` for the
device fetch path.  The replica adds the per-replica pieces the fleet
layer routes around: a `ReplicaLifecycle` state machine, a `LoadShedder`
admission gate, the shared seeded `FaultPlan`, and crash / drain /
restart transitions scheduled by the churn layer.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import List, Optional

from ..engine.engine import EngineConfig, LLMEngine
from ..engine.tokenizer import ByteTokenizer
from ..lifecycle import ReplicaLifecycle
from ..lifecycle.checkpoint import GenerationCheckpoint
from ..models import llama
from ..resilience import FaultPlan, FaultSpec, LoadShedder, ShedConfig
from .clock import SimClock
from .stub import SimFetcher, StubCosts, StubDevice, build_stub_programs

# one simulated fleet serves one weights identity: checkpoints captured on
# any replica resume on any other
SIM_MODEL_NAME = "sim-llm"

# LoRA adapters the multi-tenant workload selects between.  The stacks are
# empty per-layer dicts — the stub device never reads adapter tensors, but
# the ENGINE still runs its real adapter admission policy (adapter
# requests bypass the shared prefix cache, ride the adapter id through
# seating and checkpoints, and resume by name on another replica).
SIM_ADAPTERS = ("tenant-a", "tenant-b", "tenant-c")


@dataclass
class ReplicaSpec:
    """Sizing + cost knobs for one simulated replica."""

    max_batch_size: int = 4
    page_size: int = 16
    num_pages: int = 256
    max_pages_per_seq: int = 16
    max_prefill_len: int = 64
    prefill_buckets: tuple = (32, 64)
    steps_per_sync: int = 4
    prefill_batch: int = 4
    costs: StubCosts = field(default_factory=StubCosts)
    shed_watermark: int = 24
    shed_resume_fraction: float = 0.5
    shed_retry_after_s: float = 0.25
    drain_grace_s: float = 5.0
    # hierarchical KV store knobs (docs/kv_hierarchy.md): kv_persist gives
    # each replica a node-local persistent prefix directory that SURVIVES
    # restarts/scale-to-zero within the run (the hot-wake leg asserts the
    # woken engine pages hot prefixes back in); kv_host_gib adds the
    # host-RAM spill/demotion tier
    kv_persist: bool = False
    kv_host_gib: float = 0.0
    # gray-failure watchdog (engine/watchdog.py, docs/resilience.md):
    # tight budgets are honest here — stub devices never compile, so any
    # multi-second no-progress window with seated work IS a stall.
    # suspect + confirm + one tick is the sim's detection budget (~2.5s).
    watchdog: bool = False
    watchdog_interval_s: float = 0.25
    watchdog_suspect_s: float = 1.0
    watchdog_confirm_s: float = 1.0
    # speculative decoding + dense decode packing (docs/kernels.md):
    # None = off (byte-identical pre-spec traces); K >= 0 enables the
    # stub's mixed_decode oracle — deterministic chain-state-seeded
    # acceptance, so spec-on traces stay token-exact across preemption,
    # checkpoint and cross-replica resume
    spec_decode_k: Optional[int] = None

    def engine_config(self) -> EngineConfig:
        return EngineConfig(
            max_batch_size=self.max_batch_size,
            page_size=self.page_size,
            num_pages=self.num_pages,
            max_pages_per_seq=self.max_pages_per_seq,
            max_prefill_len=self.max_prefill_len,
            prefill_buckets=tuple(self.prefill_buckets),
            steps_per_sync=self.steps_per_sync,
            prefill_batch=self.prefill_batch,
            dtype="float32",
            use_pallas=False,
            kv_offload="host" if self.kv_host_gib > 0 else "none",
            kv_offload_gib=self.kv_host_gib,
            watchdog=self.watchdog,
            watchdog_interval_s=self.watchdog_interval_s,
            watchdog_suspect_s=self.watchdog_suspect_s,
            watchdog_confirm_s=self.watchdog_confirm_s,
            spec_decode_k=self.spec_decode_k,
        )


def _model_config():
    return llama.LlamaConfig.tiny(dtype="float32")


class SimReplica:
    """A replica the fleet layer can route to, drain, crash and restart."""

    def __init__(self, name: str, clock: SimClock, spec: ReplicaSpec,
                 params=None, build_now: bool = True,
                 node_cache_warm: bool = False):
        self.name = name
        self.url = f"http://{name}:8080"
        self.clock = clock
        self.spec = spec
        self.model_config = _model_config()
        self.tokenizer = ByteTokenizer(self.model_config.vocab_size)
        # one weights pytree shared across every replica of the fleet (the
        # stub never reads it, but sharing keeps N-replica setup cheap and
        # models the "identical weights" resume contract)
        self.params = params
        self.device = StubDevice(name, dataclasses.replace(spec.costs), clock)
        self.fault_plan: Optional[FaultPlan] = None
        self.shedder = LoadShedder(ShedConfig(
            queue_watermark=spec.shed_watermark,
            resume_fraction=spec.shed_resume_fraction,
            retry_after_s=spec.shed_retry_after_s,
        ))
        self.generation = 0  # restart counter (engine identity)
        self.crashes = 0
        # node-local AOT executable cache state (docs/coldstart.md): the
        # first build on this "node" compiles cold and populates the
        # cache; every later build — crash restart, rolling restart, wake
        # from zero — starts warm.  start_records carries the cold/warm
        # ready-cost history into the goodput report.  (True at
        # construction = a prior deployment left executables on the node:
        # the AutoscalerSpec.node_cache_prewarmed scenario knob.)
        self.node_cache_warm = node_cache_warm
        self.start_records: List[dict] = []
        # node-local persistent prefix store (docs/kv_hierarchy.md): like
        # the AOT cache above, the directory belongs to the NODE, so it
        # survives crash restarts, rolling restarts and scale-to-zero
        # wakes within the run — that persistence is what the hot-wake
        # scenario leg measures
        self.persist_dir: Optional[str] = None
        if spec.kv_persist:
            import tempfile

            self.persist_dir = tempfile.mkdtemp(
                prefix=f"kserve-sim-kvpx-{name}-")
        # engine counters survive restarts here (a fresh engine starts at
        # zero; the report wants the replica's lifetime totals)
        self.totals = {
            "preemptions": 0, "checkpointed": 0, "resumes": 0,
            "finished": 0,
        }
        # watchdog counters accumulated across engine lives (summary
        # exports them when spec.watchdog — the gray-failure proof)
        self.watchdog_totals = {"suspected": 0, "confirmed": 0,
                                "cancelled_tasks": 0}
        # speculative-decoding tallies across engine lives (summary
        # exports them when spec.spec_decode_k — the acceptance-rate and
        # spec-actually-engaged evidence the scenarios assert on)
        self.spec_totals = {"drafted": 0, "accepted": 0, "rejected": 0}
        self.prefix_totals = {
            "hits": 0, "misses": 0, "demotions": 0, "pageins": 0,
            "pagein_tokens": 0, "persist_writes": 0, "drops": 0,
            "adopted_hit_tokens": 0,
        }
        # cross-replica page fabric (docs/kv_hierarchy.md): the replica's
        # PeerPageClient — attached by the fleet layer when kv_persist is
        # on — survives engine restarts (it is node/pod infrastructure,
        # like the persist dir), so its fetch-outcome stats are already
        # lifetime totals.  pages_served counts the SERVER side (fabric
        # GETs this replica answered with a page); pagein_tokens_peer is
        # accumulated per engine life like the prefix totals.
        self.peer_client = None
        self.peer_pages_served = 0
        self._peer_pagein_tokens = 0
        # warm-pool cost accounting (docs/autoscaling.md): virtual seconds
        # this replica's process was up — the autoscaler's goodput report
        # charges policies in warm-replica-minutes
        self.up_total_s = 0.0
        self._up_since: Optional[float] = None
        self.engine: Optional[LLMEngine] = None
        self.lifecycle: Optional[ReplicaLifecycle] = None
        # autoscaler-managed fleets defer the build: a replica that has
        # never been scaled up has no engine, no device timeline, and —
        # crucially — a COLD node AOT cache (its first wake pays compile_s)
        if build_now:
            self._build_engine()

    def _build_engine(self) -> None:
        cfg = self.spec.engine_config()
        cfg.kv_persist_dir = self.persist_dir
        programs = build_stub_programs(
            cfg, self.device, vocab_size=self.model_config.vocab_size,
            warm=self.node_cache_warm)
        self.start_records.append({
            "kind": "warm" if programs.warm else "cold",
            "cost_s": programs.startup_cost_s,
        })
        self.node_cache_warm = True
        self.engine = LLMEngine(
            self.model_config,
            cfg,
            self.tokenizer,
            params=self.params,
            metrics_label=SIM_MODEL_NAME,
            checkpoint_label=SIM_MODEL_NAME,
            lora_stacked=(
                {name: i for i, name in enumerate(SIM_ADAPTERS)},
                [{} for _ in range(self.model_config.n_layers)],
            ),
            clock=self.clock,
            compiled_programs=programs,
            fetcher=SimFetcher(self.device, self.clock),
        )
        if self.params is None:
            self.params = self.engine.params
        self.engine.fault_plan = self.fault_plan
        if self.peer_client is not None:
            # rewire the fabric on every build: a restarted engine keeps
            # the node's peer client (and its learned peer index)
            self.engine.set_peer_client(self.peer_client)
        # watchdog readiness flip: a confirmed stall drains the ENGINE
        # internally; this hook flips the replica's lifecycle so the
        # poll loop pulls it from picks (readiness red) while the
        # process — and its checkpoints — stay alive (no hard kill)
        self.engine.on_stall_confirmed = self._on_stall_confirmed
        self.lifecycle = ReplicaLifecycle(
            clock=self.clock, drain_grace_s=self.spec.drain_grace_s)
        self.lifecycle.mark_ready()

    def _on_stall_confirmed(self, reason: str) -> None:
        if self.lifecycle is not None and self.lifecycle.accepting:
            self.lifecycle.begin_drain(0.0)

    # ---------------- fleet-facing state ----------------

    @property
    def alive(self) -> bool:
        """The process answers its port: the engine loop task is running
        (a crashed loop = connection refused to the fleet layer)."""
        return self.engine is not None and self.engine.running

    @property
    def accepting(self) -> bool:
        return self.alive and self.lifecycle.accepting

    def state_payload(self) -> dict:
        """What this replica's /v1/internal/scheduler/state would return —
        fed to the real EndpointPicker by the fleet's poll loop."""
        state = self.engine.scheduler_state()
        state["lifecycle"] = self.lifecycle.state
        # shed signal (protocol/rest/server.py parity): in the sim the
        # shedder gates admission in the fleet's client leg, so its counts
        # live here on the replica
        state["shed"] = {
            "count": self.shedder.shed_count,
            "shedding": self.shedder.shedding,
        }
        return state

    def set_peer_client(self, client) -> None:
        """Attach the node's kvstore.peer.PeerPageClient (fleet layer);
        wired into the live engine now and into every future build."""
        self.peer_client = client
        if self.engine is not None:
            self.engine.set_peer_client(client)

    def wipe_persist_dir(self) -> None:
        """The disk-loss churn leg: the node was replaced and its
        persistent prefix files are GONE (apply while the replica is
        down — the next build indexes an empty store and must page hot
        prefixes in over the peer fabric instead)."""
        if self.persist_dir is None:
            return
        import os

        for name in os.listdir(self.persist_dir):
            try:
                os.unlink(os.path.join(self.persist_dir, name))
            except OSError:
                pass

    def set_fault_plan(self, plan: Optional[FaultPlan]) -> None:
        self.fault_plan = plan
        if self.engine is not None:  # deferred build wires it on build
            self.engine.fault_plan = plan
        self.device.fault_plan = plan

    # ---------------- lifecycle transitions (churn layer) ----------------

    async def start(self) -> None:
        if self.engine is None:
            self._build_engine()
        await self.engine.start()
        self._up_since = self.clock.now()

    async def stop(self) -> None:
        if self._up_since is not None:
            self.up_total_s += self.clock.now() - self._up_since
            self._up_since = None
        if self.engine is not None:
            await self.engine.stop()

    async def drain(
        self, grace_s: Optional[float] = None,
    ) -> List[GenerationCheckpoint]:
        """Graceful drain: lifecycle flips DRAINING (the poll loop pulls
        this replica out of picks), in-flight work gets the drain budget,
        the rest is checkpointed to the waiting client streams."""
        budget = self.lifecycle.begin_drain(grace_s)
        checkpoints = await self.engine.drain(
            deadline=budget, clock=self.clock)
        self.lifecycle.finish_drain()
        return checkpoints

    def cleanup(self) -> None:
        """Remove the node-local persistent prefix directory (end of the
        simulation run — the 'node' is decommissioned)."""
        if self.persist_dir is not None:
            import shutil

            shutil.rmtree(self.persist_dir, ignore_errors=True)
            self.persist_dir = None

    async def crash(self) -> None:
        """Simulated process kill (kill -9 / node loss): every in-flight
        stream dies with ReplicaCrashError-shaped RuntimeErrors, nothing
        drains, nothing is checkpointed.  A replica_crash fault is armed
        first so an engine mid-fetch dies through the real fault seam; the
        stop tears down whatever the fault did not reach, and an UNFIRED
        spec is disarmed afterwards — an idle-replica crash must not leave
        a landmine that kills the restarted process on its first fetch."""
        self.crashes += 1
        spec = None
        if self.fault_plan is not None:
            spec = FaultSpec("engine.fetch", "replica_crash", count=1)
            self.fault_plan.specs.append(spec)
        await self.stop()
        if spec is not None:
            self.fault_plan.disarm(spec)

    def _engine_prefix_stats(self, e) -> dict:
        """This engine life's prefix-store tallies (zeros when the store
        is off) in the prefix_totals key set."""
        out = {k: 0 for k in self.prefix_totals}
        if e is None or e._kv_store is None:
            return out
        stats = e.scheduler_state(max_digests=0).get("prefix_store") or {}
        for k in out:
            out[k] = int(stats.get(k, 0) or 0)
        return out

    def _engine_peer_pagein_tokens(self, e) -> int:
        """Tokens this engine life adopted from PEER-fetched pages (the
        'served tokens it never prefilled and never read off local disk'
        evidence the fabric scenario asserts on)."""
        if e is None or e._kv_store is None:
            return 0
        stats = e.scheduler_state(max_digests=0).get("prefix_store") or {}
        by_tier = stats.get("pagein_tokens_by_tier") or {}
        return int(by_tier.get("peer", 0) or 0)

    def _engine_watchdog_stats(self, e) -> dict:
        out = {k: 0 for k in self.watchdog_totals}
        wd = getattr(e, "_watchdog", None) if e is not None else None
        if wd is None:
            return out
        out["suspected"] = wd.suspected_count
        out["confirmed"] = wd.confirmed_count
        out["cancelled_tasks"] = wd.cancelled_tasks
        return out

    def _engine_spec_stats(self, e) -> dict:
        out = {k: 0 for k in self.spec_totals}
        if e is None:
            return out
        for k in out:
            out[k] = int(getattr(e, "spec_stats", {}).get(k, 0))
        return out

    def _accumulate(self) -> None:
        e = self.engine
        self.totals["preemptions"] += e.preemption_count
        self.totals["checkpointed"] += e.checkpointed_count
        self.totals["resumes"] += e.resume_count
        self.totals["finished"] += e.telemetry.finished_count
        for k, v in self._engine_prefix_stats(e).items():
            self.prefix_totals[k] += v
        self._peer_pagein_tokens += self._engine_peer_pagein_tokens(e)
        for k, v in self._engine_watchdog_stats(e).items():
            self.watchdog_totals[k] += v
        for k, v in self._engine_spec_stats(e).items():
            self.spec_totals[k] += v

    def summary(self) -> dict:
        self_totals = dict(self.totals)
        e = self.engine
        up_s = self.up_total_s
        if self._up_since is not None:
            up_s += self.clock.now() - self._up_since
        out = {
            "name": self.name,
            "restarts": self.generation,
            "crashes": self.crashes,
            "preemptions": self_totals["preemptions"]
            + (e.preemption_count if e is not None else 0),
            "checkpointed": self_totals["checkpointed"]
            + (e.checkpointed_count if e is not None else 0),
            "resumes": self_totals["resumes"]
            + (e.resume_count if e is not None else 0),
            "finished": self_totals["finished"]
            + (e.telemetry.finished_count if e is not None else 0),
            "device_dispatches": self.device.dispatches,
            "lifecycle": (
                self.lifecycle.state if self.lifecycle is not None
                else "SCALED_TO_ZERO"
            ),
            "up_s": round(up_s, 9),
            "starts": [dict(s) for s in self.start_records],
        }
        if self.spec.kv_persist or self.spec.kv_host_gib > 0:
            # lifetime prefix-store tallies (fixed, sorted key set so the
            # report stays canonical-json byte-identical per seed)
            live = self._engine_prefix_stats(e)
            out["prefix_store"] = {
                k: self.prefix_totals[k] + live[k]
                for k in sorted(self.prefix_totals)
            }
        if self.peer_client is not None:
            # peer-fabric block (fixed, sorted key set — canonical-json
            # byte-identical per seed): client-side fetch outcomes +
            # verification failures, server-side pages served, and the
            # tokens adopted from peer pages across engine lives
            stats = self.peer_client.stats
            out["peer"] = {
                "bad_pages": sum(self.peer_client.bad_pages.values()),
                "breaker_open": stats["breaker_open"],
                "corrupt": stats["corrupt"],
                "hit": stats["hit"],
                "miss": stats["miss"],
                "pagein_tokens": (self._peer_pagein_tokens
                                  + self._engine_peer_pagein_tokens(e)),
                "pages_served": self.peer_pages_served,
                "timeout": stats["timeout"],
            }
        if self.spec.watchdog:
            live_wd = self._engine_watchdog_stats(e)
            out["watchdog"] = {
                k: self.watchdog_totals[k] + live_wd[k]
                for k in sorted(self.watchdog_totals)
            }
        if self.spec.spec_decode_k is not None:
            live_sp = self._engine_spec_stats(e)
            out["spec_decode"] = {
                k: self.spec_totals[k] + live_sp[k]
                for k in sorted(self.spec_totals)
            }
        return out

    async def restart(self) -> None:
        """Replace the process on the same url (rolling restart / crash
        recovery / autoscaler scale-up): fresh engine, fresh device
        timeline, READY lifecycle.  A never-built replica (autoscaler
        deferred build) builds COLD here — its node cache is empty.  The
        fleet layer must forget the old pod's breaker state (recycled
        address contract — scheduler/picker.set_replicas)."""
        if self.engine is not None:
            await self.stop()
            self._accumulate()
            self.generation += 1
            self.device.reset()
        self._build_engine()
        await self.engine.start()
        self._up_since = self.clock.now()
