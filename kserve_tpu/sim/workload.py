"""Seeded workload generators: timestamped request traces for the fleet.

Each generator draws from one `random.Random(seed)` stream, so a trace is
a pure function of its config — the first half of the simulator's
determinism contract (the second half is the SimClock's event ordering).

Kinds (mirroring the serving scenarios the repo targets):

- ``chat``          short prompts sharing a common system-prefix (so the
                    EPP's prefix-affinity scoring has something to bite
                    on), small token budgets, per-request deadlines — the
                    SSE-interactive shape
- ``long_context``  prompts past max_prefill_len, forcing the engine's
                    chunked-prefill admission path
- ``lora``          chat-shaped but pinned to a tenant adapter, which
                    bypasses the shared prefix cache and rides adapter
                    identity through checkpoints/resume
- ``batch``         deadline-free bulk generations with larger budgets,
                    arriving in bursts — the queue-pressure generator
                    that makes shed storms and KV preemption happen
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..engine.sampling import SamplingParams
from .replica import SIM_ADAPTERS
from .stub import SAFE_BAND, SAFE_LO

# shared chat system prompt: page-aligned so prefix-cache hits are whole
# pages (page_size 16 in the default ReplicaSpec)
_SYSTEM_PREFIX_LEN = 16


@dataclass
class SimRequest:
    """One trace entry: everything a client needs to submit and verify."""

    rid: str
    kind: str
    arrival_s: float
    prompt_ids: List[int]
    max_tokens: int
    adapter: Optional[str] = None
    deadline_s: Optional[float] = None

    def sampling_params(self) -> SamplingParams:
        # greedy + ignore_eos: the stub chain is deterministic and never
        # emits EOS, so every completed request finishes "length" with
        # exactly max_tokens tokens — the token-accounting invariant
        return SamplingParams(
            max_tokens=self.max_tokens, temperature=0.0, ignore_eos=True)


@dataclass
class WorkloadConfig:
    """Mix + rate for one trace.  `mix` weights must cover every kind
    generated; arrivals spread uniformly over `duration_s` except the
    optional bursts — (at_s, n) spikes of batch requests in one instant,
    used both as shed storms and to guarantee in-flight work exactly when
    a churn event lands (a drain that finds an idle replica proves
    nothing)."""

    n_requests: int = 200
    duration_s: float = 60.0
    mix: Dict[str, float] = field(default_factory=lambda: {
        "chat": 0.55, "long_context": 0.15, "lora": 0.2, "batch": 0.1,
    })
    chat_deadline_s: float = 30.0
    bursts: Optional[List[tuple]] = None  # [(at_s, n), ...]
    # bounds every prompt+max_tokens must respect (ReplicaSpec geometry)
    max_model_len: int = 256
    max_prefill_len: int = 64


def _prompt(rng: random.Random, n: int) -> List[int]:
    return [SAFE_LO + rng.randrange(SAFE_BAND) for _ in range(n)]


def generate_trace(config: WorkloadConfig, seed: int) -> List[SimRequest]:
    """The seeded trace: requests sorted by (arrival, rid)."""
    rng = random.Random(seed)
    system_prefix = _prompt(rng, _SYSTEM_PREFIX_LEN)
    kinds = sorted(config.mix)
    weights = [config.mix[k] for k in kinds]
    out: List[SimRequest] = []

    def build(i: int, kind: str, arrival: float) -> SimRequest:
        if kind == "chat":
            prompt = system_prefix + _prompt(rng, rng.randint(4, 24))
            return SimRequest(
                rid=f"req-{i:05d}-chat", kind=kind, arrival_s=arrival,
                prompt_ids=prompt, max_tokens=rng.randint(8, 24),
                deadline_s=config.chat_deadline_s,
            )
        if kind == "long_context":
            lo = config.max_prefill_len + 8
            hi = min(config.max_model_len - 40, 3 * config.max_prefill_len)
            prompt = _prompt(rng, rng.randint(lo, hi))
            return SimRequest(
                rid=f"req-{i:05d}-long", kind=kind, arrival_s=arrival,
                prompt_ids=prompt, max_tokens=rng.randint(4, 12),
            )
        if kind == "lora":
            prompt = _prompt(rng, rng.randint(6, 24))
            return SimRequest(
                rid=f"req-{i:05d}-lora", kind=kind, arrival_s=arrival,
                prompt_ids=prompt, max_tokens=rng.randint(8, 24),
                adapter=SIM_ADAPTERS[rng.randrange(len(SIM_ADAPTERS))],
                deadline_s=config.chat_deadline_s,
            )
        if kind == "batch":
            prompt = _prompt(rng, rng.randint(8, 32))
            return SimRequest(
                rid=f"req-{i:05d}-batch", kind=kind, arrival_s=arrival,
                prompt_ids=prompt, max_tokens=rng.randint(24, 48),
            )
        raise ValueError(f"unknown workload kind {kind!r}")

    for i in range(config.n_requests):
        kind = rng.choices(kinds, weights=weights)[0]
        arrival = round(rng.uniform(0.0, config.duration_s), 6)
        out.append(build(i, kind, arrival))
    next_id = config.n_requests
    for at_s, n in config.bursts or ():
        for _ in range(n):
            out.append(build(next_id, "batch", float(at_s)))
            next_id += 1
    out.sort(key=lambda r: (r.arrival_s, r.rid))
    for req in out:
        if len(req.prompt_ids) + req.max_tokens > config.max_model_len:
            raise ValueError(
                f"trace bug: {req.rid} exceeds max_model_len "
                f"{config.max_model_len}")
    return out
