"""SLO goodput report + hard assertions for simulator runs.

The report is built from the per-request `RequestTimeline`s the fleet's
client layer stamps on the SimClock (PR 6's observability spine), plus
the client-side accounting the timelines cannot carry (attempt counts,
token-exactness against the stub oracle, shed/error outcomes).  Every
value is a pure function of virtual time and seeded randomness, so
`canonical_json(report)` is byte-identical across runs of the same
scenario + seed — which is itself one of the assertions CI makes.

`assert_slo` turns the report into hard pass/fail: p50/p99 TTFT and ITL
budgets, zero lost / zero duplicated tokens (token-exact accounting
across preemption resumes), bounded retry amplification, and shed/error
budgets.  A violation raises `SLOViolation` listing every breached
budget, not just the first.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..observability import percentiles


class SLOViolation(AssertionError):
    """One or more SLO budgets breached; the message lists all of them."""


@dataclass
class SLOBudget:
    """Hard budgets for one scenario.  None disables a check."""

    p50_ttft_s: Optional[float] = None
    p99_ttft_s: Optional[float] = 5.0
    p99_itl_s: Optional[float] = 1.0
    p99_e2e_s: Optional[float] = None
    # completed-with-exact-tokens / submitted
    min_goodput: float = 0.98
    max_retry_amplification: float = 2.0
    max_shed_fraction: Optional[float] = 0.25
    max_lost_tokens: int = 0
    max_duplicated_tokens: int = 0


def _rounded(stats: Dict[str, Any]) -> Dict[str, Any]:
    # stable float text: the values are already deterministic, rounding
    # just keeps the JSON readable
    return {k: (round(v, 9) if isinstance(v, float) else v)
            for k, v in sorted(stats.items())}


def build_report(scenario_name: str, seed: int, records: List[dict],
                 replicas: List[dict], faults: List[tuple],
                 finished_at_s: float,
                 autoscaler: Optional[Dict[str, Any]] = None,
                 health: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """Aggregate client records (fleet.ClientRecord.to_dict()) into the
    canonical goodput report."""
    outcomes: Dict[str, int] = {}
    for r in records:
        outcomes[r["outcome"]] = outcomes.get(r["outcome"], 0) + 1
    completed = [r for r in records if r["outcome"] == "completed"]
    exact = [r for r in completed if r["token_exact"]]
    ttft = [r["ttft_s"] for r in completed if r["ttft_s"] is not None]
    e2e = [r["e2e_s"] for r in completed if r["e2e_s"] is not None]
    itl: List[float] = []
    for r in completed:
        itl.extend(r["itls"])
    n = len(records)
    attempts = sum(r["attempts"] for r in records)
    sheds = sum(r["sheds"] for r in records)
    report = {
        "scenario": scenario_name,
        "seed": seed,
        "requests": {
            "submitted": n,
            "completed": len(completed),
            "token_exact": len(exact),
            "outcomes": dict(sorted(outcomes.items())),
        },
        "tokens": {
            "delivered": sum(r["n_tokens"] for r in completed),
            "lost": sum(r["lost_tokens"] for r in records),
            "duplicated": sum(r["duplicated_tokens"] for r in records),
            "salvaged_via_resume": sum(r["salvaged_tokens"] for r in records),
        },
        "retries": {
            "attempts": attempts,
            "amplification": round(attempts / n, 9) if n else 0.0,
            "max_attempts_one_request": max(
                (r["attempts"] for r in records), default=0),
            "preempt_resumes": sum(r["resumes"] for r in records),
            "crash_restarts": sum(r["crash_restarts"] for r in records),
            # stall-triggered migrations off gray replicas (hedge fired
            # client-side, or a watchdog self-drain checkpoint resumed)
            "migrations": sum(r.get("migrations", 0) for r in records),
            "sheds_observed": sheds,
            # gateway holds are NOT attempts: a parked request burns no
            # retry budget (the hold-and-replay contract)
            "holds_observed": sum(r.get("held", 0) for r in records),
        },
        "latency": {
            "ttft_s": _rounded(percentiles(ttft)),
            "itl_s": _rounded(percentiles(itl)),
            "e2e_s": _rounded(percentiles(e2e)),
        },
        "goodput": round(len(exact) / n, 9) if n else 0.0,
        "replicas": sorted(replicas, key=lambda r: r["name"]),
        "faults_injected": {
            kind: sum(1 for _, k in faults if k == kind)
            for kind in sorted({k for _, k in faults})
        },
        "finished_at_s": round(finished_at_s, 9),
    }
    if autoscaler is not None:
        # the autoscaler-in-the-loop block (fleet._autoscaler_summary):
        # reason-counted decisions, hold outcomes, warm-pool bill
        report["autoscaler"] = autoscaler
    if health is not None:
        # gray-failure block (fleet._health_summary): quarantine /
        # reintroduce transitions with virtual timestamps — the
        # detection-budget evidence
        report["health"] = health
    return report


def canonical_json(report: Dict[str, Any]) -> str:
    """The byte form CI compares across same-seed runs."""
    return json.dumps(report, sort_keys=True, separators=(",", ":"))


def assert_slo(report: Dict[str, Any], budget: SLOBudget) -> None:
    """Raise SLOViolation listing EVERY breached budget."""
    breaches: List[str] = []

    def check(cond: bool, msg: str) -> None:
        if not cond:
            breaches.append(msg)

    lat = report["latency"]

    def pct(block: str, key: str) -> Optional[float]:
        stats = lat[block]
        return stats.get(key) if stats.get("n") else None

    if budget.p50_ttft_s is not None:
        v = pct("ttft_s", "p50")
        check(v is not None and v <= budget.p50_ttft_s,
              f"p50 TTFT {v} > budget {budget.p50_ttft_s}")
    if budget.p99_ttft_s is not None:
        v = pct("ttft_s", "p99")
        check(v is not None and v <= budget.p99_ttft_s,
              f"p99 TTFT {v} > budget {budget.p99_ttft_s}")
    if budget.p99_itl_s is not None:
        v = pct("itl_s", "p99")
        check(v is not None and v <= budget.p99_itl_s,
              f"p99 ITL {v} > budget {budget.p99_itl_s}")
    if budget.p99_e2e_s is not None:
        v = pct("e2e_s", "p99")
        check(v is not None and v <= budget.p99_e2e_s,
              f"p99 e2e {v} > budget {budget.p99_e2e_s}")
    check(report["goodput"] >= budget.min_goodput,
          f"goodput {report['goodput']} < budget {budget.min_goodput}")
    check(report["tokens"]["lost"] <= budget.max_lost_tokens,
          f"lost tokens {report['tokens']['lost']} > "
          f"{budget.max_lost_tokens}")
    check(report["tokens"]["duplicated"] <= budget.max_duplicated_tokens,
          f"duplicated tokens {report['tokens']['duplicated']} > "
          f"{budget.max_duplicated_tokens}")
    amp = report["retries"]["amplification"]
    check(amp <= budget.max_retry_amplification,
          f"retry amplification {amp} > {budget.max_retry_amplification}")
    if budget.max_shed_fraction is not None:
        n = max(report["requests"]["submitted"], 1)
        frac = report["retries"]["sheds_observed"] / n
        check(frac <= budget.max_shed_fraction,
              f"shed fraction {frac:.4f} > {budget.max_shed_fraction}")
    if breaches:
        raise SLOViolation(
            "SLO budget breached:\n  - " + "\n  - ".join(breaches))
