"""Discrete-event virtual time for the fleet simulator.

`FakeClock` (resilience/clock.py) advances time the moment anything
sleeps — perfect for single-replica chaos tests, wrong for a fleet:
two replicas decoding "concurrently" would serialize, and adding a
replica would make everyone slower in virtual time.  `SimClock` is a
real discrete-event scheduler instead: `sleep()` parks the caller on a
timer heap, and the driver advances time to the earliest pending timer
only once every runnable coroutine has gone quiet.  Two replicas whose
stub devices each take 5 virtual ms therefore finish at t=5ms, not
t=10ms — fleet compute overlaps the way real hardware does.

Determinism: timers fire in (deadline, registration order); the driver
itself runs on the ordinary asyncio loop, whose FIFO scheduling is
deterministic as long as nothing touches real I/O or threads (the
simulator's stub fetcher exists precisely to keep the engine's device
fetches off the fetch worker thread).  Same tasks + same sleeps = same
interleaving = byte-identical reports.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
from typing import Callable, List, Optional, Tuple

from ..resilience.clock import Clock


class SimDeadlockError(RuntimeError):
    """Every task is blocked, no timer is pending, and the scenario is not
    complete: the simulation can never make progress again.  Carries the
    driver's view of what was still outstanding."""


class SimClock(Clock):
    """Virtual monotonic clock with a discrete-event driver."""

    def __init__(self, start: float = 0.0):
        self._now = start
        self._seq = itertools.count()
        # (when, seq, future) — seq breaks ties deterministically in
        # registration order
        self._timers: List[Tuple[float, int, asyncio.Future]] = []

    # ---------------- Clock surface ----------------

    def now(self) -> float:
        return self._now

    async def sleep(self, seconds: float) -> None:
        if seconds <= 0:
            # preserve FakeClock's "one event-loop yield" contract so
            # zero-backoff retries still cede the loop
            await asyncio.sleep(0)
            return
        await self.sleep_until(self._now + seconds)

    async def sleep_until(self, when: float) -> None:
        if when <= self._now:
            await asyncio.sleep(0)
            return
        fut = asyncio.get_running_loop().create_future()
        heapq.heappush(self._timers, (when, next(self._seq), fut))
        await fut

    # ---------------- sync advancement (FakeClock parity) ----------------

    def advance(self, seconds: float) -> None:
        """Jump virtual time forward without yielding (FakeClock parity for
        sync call sites — the stub device's blocking prefill fetch).  Due
        timers fire on the driver's next pass, observing the jumped time."""
        self._now += max(seconds, 0.0)

    def advance_to(self, when: float) -> None:
        if when > self._now:
            self._now = when

    # ---------------- the driver ----------------

    @property
    def pending_timers(self) -> int:
        self._prune()
        return len(self._timers)

    def _prune(self) -> None:
        while self._timers and self._timers[0][2].done():
            heapq.heappop(self._timers)  # cancelled waiter: nothing to wake

    def _fire_due(self) -> bool:
        """Wake every timer whose deadline has been reached (deadline
        order, then registration order).  True when any waiter was woken."""
        fired = False
        while self._timers and self._timers[0][0] <= self._now:
            _, _, fut = heapq.heappop(self._timers)
            if not fut.done():
                fut.set_result(None)
                fired = True
        return fired

    async def _settle(self) -> None:
        """Yield until no other coroutine is runnable.  Uses the loop's
        ready-queue length when available (CPython's default loop); falls
        back to a fixed, deterministic number of yields otherwise."""
        loop = asyncio.get_running_loop()
        ready = getattr(loop, "_ready", None)
        if ready is None:
            for _ in range(64):
                await asyncio.sleep(0)
            return
        while True:
            await asyncio.sleep(0)
            if not ready:
                return

    async def drive(
        self,
        until: Callable[[], bool],
        describe_stuck: Optional[Callable[[], str]] = None,
    ) -> None:
        """Run the simulation until `until()` holds: settle the loop, fire
        due timers, and advance virtual time to the next timer whenever
        everything is parked.  Raises SimDeadlockError when no timer is
        pending, nothing is runnable, and `until()` still fails."""
        while not until():
            await self._settle()
            if until():
                return
            if self._fire_due():
                continue
            self._prune()
            if not self._timers:
                detail = describe_stuck() if describe_stuck else ""
                raise SimDeadlockError(
                    "simulation stalled: no runnable task, no pending "
                    f"timer, and the scenario is not complete. {detail}"
                )
            self._now = self._timers[0][0]
            self._fire_due()

    async def drain_timers(self) -> None:
        """Drive until the timer heap is empty (used after the scenario
        completes to let in-flight engine work quiesce before teardown)."""
        await self.drive(until=lambda: self.pending_timers == 0)
