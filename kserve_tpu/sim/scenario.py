"""Scenario definitions: workload + fleet geometry + scheduled churn.

A `Scenario` is everything a simulation run needs; same scenario + same
seed = byte-identical goodput report.  `ChurnEvent`s are scheduled
against the SimClock and applied by the fleet layer on top of the
resilience FaultPlan machinery:

- ``preempt``        arm N deterministic KV-preemption faults on a replica
- ``crash``          the replica's next device fetch raises
                     ReplicaCrashError (run loop dies, nothing is
                     checkpointed), then the process restarts after
                     `restart_after_s`
- ``drain_restart``  rolling-restart step: graceful drain (checkpoints
                     stream out to clients), stop, restart after
                     `restart_after_s`
- ``breaker_trip``   the fleet's network plan serves N injected 503s from
                     this replica, tripping its breaker in the picker
- ``shed_storm``     scale every replica's shed watermark by `factor`
                     (e.g. 0.1 → sheds start at 10% of normal depth);
                     ``heal_shed`` restores
- ``skew``           multiply a replica's stub compute costs by `factor`
                     (slow replica); ``heal_skew`` restores

Gray-fault kinds (docs/resilience.md — the replica stays alive, polls
green, and passes liveness through all of these; only the watchdog /
health-score / hedge defense catches them):

- ``slow_decode``    the replica serves `factor`x slower (a degraded
                     host); ``heal_skew`` restores
- ``wedged_fetch``   the replica's async device-fetch path delivers
                     nothing for `factor` virtual seconds — dispatches
                     land, tokens never arrive; the engine watchdog
                     must confirm the stall and self-drain
- ``flapping``       compute alternates normal / `factor`-slow in
                     `period_s` windows; ``heal_skew`` restores

Peer-fabric kinds (docs/kv_hierarchy.md "Cross-replica page serving" —
faults on the verified cross-replica KV page-fetch path; always a
performance event, never a correctness one):

- ``peer_corrupt``   fetches TO this replica's page server return the
                     real page with a byte flipped under a 200 — the
                     lying peer only digest verification catches
- ``peer_partition`` fetches TO this replica's page server are refused
                     (the breaker opens; fetchers degrade local-only)
- ``peer_slow``      fetches TO this replica proceed `factor` virtual
                     seconds late (the client deadline caps the damage)
- ``disk_wipe``      the replica's persistent prefix files are deleted
                     (node replacement — apply while it is down; the
                     wake must page hot prefixes in over the fabric)

Canned scenarios back the test suite: `smoke_scenario()` and
`gray_failure_scenario()` run in tier-1 on every PR;
`churn_10k_scenario()` is the acceptance-scale trace (10k requests,
4 replicas, preemptions + rolling restart + breaker trip + shed storm +
a gray slow-replica leg) marked slow.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..autoscale.policy import (
    PredictiveConfig,
    PredictivePolicy,
    ReactiveConfig,
    ReactivePolicy,
    ScalingPolicy,
)
from ..scheduler.health import HealthConfig
from .replica import ReplicaSpec
from .report import SLOBudget
from .stub import StubCosts
from .workload import WorkloadConfig

# canned-scenario device: ~10ms prefill launch + 0.2ms/prompt-token,
# 20ms/decode-step — slow enough that bursts queue, drains catch work in
# flight, and shed watermarks mean something, while 4-lane batches still
# clear ~200 tok/s/replica so a 10k-request trace finishes in ~20 virtual
# minutes.  Virtual slowness is free: wall time scales with EVENTS, not
# with simulated seconds.  Replica starts pay 1s of cold XLA compile or
# 50ms of warm AOT executable load (docs/coldstart.md): every canned
# restart leg now asserts the warm-start delta as a side effect.
_CANNED_COSTS = StubCosts(
    prefill_base_s=0.01, prefill_per_token_s=2e-4, decode_step_s=0.02,
    compile_s=1.0, aot_load_s=0.05)


def _canned_spec() -> ReplicaSpec:
    return ReplicaSpec(costs=_CANNED_COSTS)


@dataclass
class ChurnEvent:
    at_s: float
    # preempt | crash | drain_restart | breaker_trip | shed_storm |
    # heal_shed | skew | heal_skew | slow_decode | wedged_fetch |
    # flapping | peer_corrupt | peer_partition | peer_slow | disk_wipe
    kind: str
    replica: Optional[str] = None  # e.g. "replica-1" (None = fleet-wide)
    count: int = 1
    # skew/slow_decode/flapping: the compute multiplier; wedged_fetch:
    # the wedge duration in virtual seconds; peer_slow: the injected
    # page-fetch latency in virtual seconds
    factor: float = 1.0
    # peer_* fault kinds: skip the first N matching page fetches before
    # injecting (sequences the chaos legs inside one wake's fetch wave)
    after: int = 0
    restart_after_s: float = 2.0
    # drain_restart only: drain-budget override (None = the replica's
    # spec default; 0.0 = checkpoint everything in flight immediately —
    # the hard-preemption end of the rolling-restart spectrum)
    grace_s: Optional[float] = None
    # flapping only: the alternation window (normal for one period,
    # factor-slow for the next)
    period_s: float = 2.0


@dataclass
class AutoscalerSpec:
    """Autoscaler-in-the-loop configuration (docs/autoscaling.md): when a
    Scenario carries one, the fleet's replica count is DRIVEN by a live
    `AutoscalerLoop` instead of being static — `n_replicas` becomes the
    fleet's maximum footprint, only `initial_replicas` start, and requests
    arriving while nothing is up are parked on the hold-and-replay gateway
    (never client-retried).  This is how a policy is expressed as a sim
    scenario first: the goodput report judges it before the reconciler
    ships its config."""

    policy: str = "predictive"  # "reactive" | "predictive"
    min_replicas: int = 0
    max_replicas: Optional[int] = None  # None = scenario.n_replicas
    initial_replicas: int = 1
    interval_s: float = 0.5  # decision tick
    drain_grace_s: float = 0.5  # scale-down drain budget (checkpoints out)
    hold_max: int = 256  # bounded gateway hold queue
    hold_timeout_s: float = 60.0  # default hold budget (deadline-less reqs)
    # signal smoothing: short windows so sim-scale dynamics (tens of
    # virtual seconds) register; production defaults are longer
    arrival_rate_window_s: float = 5.0
    arrival_slope_window_s: float = 4.0
    # True = every node's AOT cache starts populated (a prior deployment
    # left executables on disk — the docs/coldstart.md warmed-PVC recipe),
    # so even FIRST scale-ups pay aot_load_s, not compile_s.  False keeps
    # the honest cold-first-build accounting the smoke asserts.
    node_cache_prewarmed: bool = False
    # wall-clock anchor for the fleet's ArrivalHistory (ROADMAP 1c):
    # epoch seconds corresponding to virtual t=0, so day-scale periodic
    # detection can be FABRICATED in the sim ("t=0 is 03:00 UTC").
    # None = un-anchored (no time-of-day profile, today's behavior).
    wall_anchor_s: Optional[float] = None
    reactive: ReactiveConfig = field(default_factory=ReactiveConfig)
    predictive: PredictiveConfig = field(default_factory=PredictiveConfig)

    def build_policy(self) -> ScalingPolicy:
        reactive = ReactivePolicy(self.reactive)
        if self.policy == "reactive":
            return reactive
        if self.policy == "predictive":
            return PredictivePolicy(reactive=reactive,
                                    config=self.predictive)
        raise ValueError(f"unknown autoscaler policy {self.policy!r}")


@dataclass
class Scenario:
    name: str
    seed: int = 0
    n_replicas: int = 2
    spec: ReplicaSpec = field(default_factory=ReplicaSpec)
    workload: WorkloadConfig = field(default_factory=WorkloadConfig)
    churn: List[ChurnEvent] = field(default_factory=list)
    budget: SLOBudget = field(default_factory=SLOBudget)
    autoscaler: Optional[AutoscalerSpec] = None
    poll_interval_s: float = 0.5
    # stall-triggered migration (docs/resilience.md): an inter-token gap
    # past this deadline checkpoints the stream client-side, cancels it
    # on the (gray-slow) replica, and re-submits it to a healthy one —
    # token-exact via the stub oracle.  None disables (pre-gray behavior).
    hedge_itl_s: Optional[float] = None
    # gray-failure health scoring config for the picker's FleetHealth
    # (scheduler/health.py); None takes the production defaults
    health: Optional[HealthConfig] = None
    # EPP resident-prefix pick term (scheduler/picker.py resident_weight):
    # None takes the picker's production default.  Scenarios that must
    # observe SYMMETRIC traffic (e.g. "every node persists the prefix")
    # pin it to 0.0 — with it on, the picker deliberately concentrates
    # shared-prefix traffic on whichever replica already holds the pages.
    resident_weight: Optional[float] = None
    # generous client persistence: a shed storm resolves in a few virtual
    # seconds, and a client that gives up during one is a goodput loss the
    # scenario is supposed to absorb, not accept
    client_max_attempts: int = 14
    client_retry_budget_s: float = 240.0

    def replica_names(self) -> List[str]:
        return [f"replica-{i}" for i in range(self.n_replicas)]


def smoke_scenario(seed: int = 7) -> Scenario:
    """Small-but-complete: 2 replicas, every workload kind, one
    deterministic preemption, one graceful drain+restart, one breaker
    trip, a shed burst, and a mixed-composition leg (a second burst whose
    long-context chunked prefills overlap live decode lanes inside the
    unified ragged program, with a preemption landing mid-overlap) — fast
    enough for tier-1 on every PR.  The initial builds compile COLD
    (compile_s each) while every churn restart — replica-0's drain
    restart, replica-1's crash recovery — comes back WARM off the node's
    AOT cache (aot_load_s ≪ compile_s), so the cold/warm replica-start
    delta is asserted in tier-1, not just in the slow traces."""
    return Scenario(
        name="smoke",
        seed=seed,
        n_replicas=2,
        spec=_canned_spec(),
        workload=WorkloadConfig(
            n_requests=60, duration_s=30.0,
            # the 16s burst is the mixed-composition leg: its long_context
            # share chunk-prefills while the burst's chat/batch lanes
            # decode, so the stub `mixed` program serves genuinely ragged
            # batches under the preempt below
            bursts=[(8.0, 12), (16.0, 10)],
        ),
        churn=[
            # the burst guarantees in-flight work when the churn lands:
            # preemptions fire mid-decode, and the zero-grace drain
            # checkpoints the backlog, which must resume token-exactly on
            # the other replica
            ChurnEvent(at_s=7.9, kind="shed_storm", factor=0.3),
            ChurnEvent(at_s=8.2, kind="preempt", replica="replica-0",
                       count=2),
            ChurnEvent(at_s=8.6, kind="drain_restart", replica="replica-0",
                       restart_after_s=2.0, grace_s=0.0),
            ChurnEvent(at_s=12.0, kind="heal_shed"),
            ChurnEvent(at_s=14.0, kind="breaker_trip", replica="replica-1",
                       count=6),
            # mixed-composition churn: preempt while the 16s burst has
            # chunked prefills in flight next to decode lanes — the
            # checkpointed streams must still resume token-exactly
            ChurnEvent(at_s=16.4, kind="preempt", replica="replica-0",
                       count=1),
            # replica-1 is the only replica serving the burst backlog while
            # replica-0 drains, so a crash here reliably kills live streams
            # (retry-from-scratch, not resume) and opens a brief full-fleet
            # outage the retry layer must ride out
            ChurnEvent(at_s=9.5, kind="crash", replica="replica-1",
                       restart_after_s=1.5),
        ],
        budget=SLOBudget(
            p99_ttft_s=20.0, p99_itl_s=2.0, min_goodput=0.9,
            # the smoke deliberately opens a FULL-fleet outage (crash mid
            # drain), so its amplification budget is looser than the 2x
            # the 10k acceptance scenario holds the fleet to
            max_retry_amplification=3.0, max_shed_fraction=1.0,
        ),
    )


def gray_failure_scenario(seed: int = 23) -> Scenario:
    """Gray-failure immune system, end to end (tier-1; ISSUE 14,
    docs/resilience.md).  Three replicas serve a mixed trace; mid-burst
    replica-1 turns 15x slow (``slow_decode`` — alive, polls green,
    passes liveness) and replica-2's fetch worker wedges
    (``wedged_fetch`` — dispatches land, tokens never arrive).  The
    defense has three layers, all exercised here:

    - replica-2's engine WATCHDOG confirms the stall inside its
      suspect+confirm budget, flips readiness and self-drains — every
      in-flight token is salvaged into checkpoints (reason="stall") that
      resume token-exactly on healthy replicas (no kubelet-style hard
      kill anywhere in this scenario);
    - the EPP's fleet HEALTH scoring spots replica-1 as a latency
      outlier vs the fleet median and QUARANTINES it (distinct from
      breaker-open: no served errors ever happen), weight-reducing
      first, excluding after;
    - streams already seated on replica-1 are rescued by the client's
      inter-token HEDGE: a gap past hedge_itl_s checkpoints the stream
      client-side, cancels the sick seat, and re-submits — token-exact
      via the stub oracle.

    replica-1 heals at 16s and must be REINTRODUCED by canary re-probes
    (quarantine is reversible); replica-2 stays drained (production
    would restart the pod).  Goodput 1.0, zero lost/duplicated tokens,
    byte-identical per seed."""
    return Scenario(
        name="gray-failure",
        seed=seed,
        n_replicas=3,
        spec=ReplicaSpec(
            costs=_CANNED_COSTS,
            watchdog=True,
            # suspect+confirm+tick ≈ 4.25s detection budget: comfortably
            # above the slowest single slow-replica dispatch (~1.5s at
            # 15x — merely-slow must NOT confirm; quarantine handles it)
            # and far under the client deadlines the stall would burn
            watchdog_suspect_s=2.0,
            watchdog_confirm_s=2.0,
        ),
        workload=WorkloadConfig(
            n_requests=60, duration_s=30.0,
            # burst 1 guarantees in-flight streams on every replica when
            # the gray faults land; burst 2 provides the post-heal
            # traffic that refreshes windows and carries the canaries
            bursts=[(5.0, 10), (14.0, 8)],
        ),
        churn=[
            ChurnEvent(at_s=6.0, kind="slow_decode", replica="replica-1",
                       factor=15.0),
            # mid-burst, so replica-2 has seated streams the moment its
            # fetch worker wedges — the stall clock starts immediately
            # (a wedge on an idle replica stalls nothing until the next
            # request lands)
            ChurnEvent(at_s=5.5, kind="wedged_fetch", replica="replica-2",
                       factor=60.0),
            ChurnEvent(at_s=16.0, kind="heal_skew", replica="replica-1"),
        ],
        hedge_itl_s=1.0,
        health=HealthConfig(
            # sim-scale cadences: canary every 2s so reintroduction fits
            # inside the trace; grace covers the stale-window refresh
            reprobe_interval_s=2.0,
            canary_timeout_s=4.0,
            heal_successes=2,
            reintroduce_grace_s=6.0,
        ),
        budget=SLOBudget(
            # TTFT/ITL absorb detection + migration (a rescued stream
            # pays the hedge gap + one resume re-prefill); what may NOT
            # happen is a drop or duplicate — goodput stays 1.0
            p99_ttft_s=20.0, p99_itl_s=6.0, min_goodput=1.0,
            max_retry_amplification=4.0, max_shed_fraction=1.0,
        ),
    )


def spec_decode_scenario(seed: int = 31, k_drafts: int = 4) -> Scenario:
    """Speculative decoding under churn (tier-1; ISSUE 15,
    docs/kernels.md): two spec-enabled replicas serve a decode-heavy
    trace while the churn layer preempts lanes MID-VERIFY (the faults
    land between dispatches, with speculative verify chunks in flight on
    either side) and zero-grace-drains a replica so its checkpointed
    streams resume token-exactly on the peer.  The stub's chain-state-
    seeded acceptance pattern makes the accept/reject sequence itself
    deterministic AND resume-invariant, so the goodput report proves the
    spec contract end to end: checkpoints carry only ACCEPTED tokens
    (never an unverified draft tail), zero lost / zero duplicated tokens
    across preempt + drain + resume, byte-identical per seed."""
    return Scenario(
        name="spec-decode",
        seed=seed,
        n_replicas=2,
        spec=ReplicaSpec(costs=_CANNED_COSTS, spec_decode_k=k_drafts),
        workload=WorkloadConfig(
            n_requests=50, duration_s=25.0,
            # decode-heavy: mostly chat/batch generation with a burst so
            # the preempts and the drain land on in-flight verify rounds
            mix={"chat": 0.7, "batch": 0.3},
            bursts=[(6.0, 12)],
        ),
        churn=[
            ChurnEvent(at_s=6.3, kind="preempt", replica="replica-0",
                       count=2),
            ChurnEvent(at_s=6.6, kind="preempt", replica="replica-1",
                       count=1),
            # zero-grace drain mid-burst: everything in flight —
            # including lanes whose last dispatch was a verify chunk —
            # checkpoints out and resumes on the peer, token-exact
            ChurnEvent(at_s=7.0, kind="drain_restart", replica="replica-0",
                       restart_after_s=2.0, grace_s=0.0),
        ],
        budget=SLOBudget(
            p99_ttft_s=20.0, p99_itl_s=2.0, min_goodput=0.95,
            max_retry_amplification=3.0, max_shed_fraction=1.0,
        ),
    )


def scale_zero_scenario(seed: int = 11) -> Scenario:
    """Serverless elasticity (ROADMAP item 3, docs/coldstart.md): the
    fleet scales 0→N→0 under deterministic traffic.  Both replicas build
    COLD at t=0 (the node AOT caches populate), are scaled to zero almost
    immediately, wake WARM at ~6s to replay the gateway-held backlog,
    pass through a SECOND zero window mid-traffic, and wake warm again —
    no request may drop across either outage, and the warm ready-cost
    must be a small fraction of the cold one (asserted in tier-1)."""
    costs = StubCosts(
        prefill_base_s=0.01, prefill_per_token_s=2e-4, decode_step_s=0.02,
        # pronounced cold/warm split: 3s of XLA compile vs 0.1s of
        # executable deserialization — the zero-compile replica start
        compile_s=3.0, aot_load_s=0.1)
    return Scenario(
        name="scale-zero",
        seed=seed,
        n_replicas=2,
        spec=ReplicaSpec(costs=costs),
        workload=WorkloadConfig(
            n_requests=30, duration_s=24.0,
            # the burst lands inside the SECOND zero window: those
            # requests are held by the retry layer and replayed on wake
            bursts=[(17.0, 8)],
        ),
        churn=[
            # scale to zero just after launch: cold compiles are wasted
            # work the warm wakes below never repeat
            ChurnEvent(at_s=0.3, kind="scale_down", replica="replica-0",
                       grace_s=0.0),
            ChurnEvent(at_s=0.3, kind="scale_down", replica="replica-1",
                       grace_s=0.0),
            # wake: both replicas come back WARM and replay the backlog
            ChurnEvent(at_s=6.0, kind="scale_up", replica="replica-0"),
            ChurnEvent(at_s=6.2, kind="scale_up", replica="replica-1"),
            # second pass through zero, mid-traffic
            ChurnEvent(at_s=16.0, kind="scale_down", replica="replica-0",
                       grace_s=0.0),
            ChurnEvent(at_s=16.0, kind="scale_down", replica="replica-1",
                       grace_s=0.0),
            ChurnEvent(at_s=20.0, kind="scale_up", replica="replica-0"),
            ChurnEvent(at_s=20.1, kind="scale_up", replica="replica-1"),
        ],
        budget=SLOBudget(
            # TTFT absorbs the zero windows (a request arriving at 0.3
            # waits ~6s for the wake) — that is the scenario's point; what
            # may NOT happen is a drop: goodput 1.0, zero lost tokens
            p99_ttft_s=25.0, p99_itl_s=2.0, min_goodput=1.0,
            # the "gateway hold" is modeled as the client retry loop
            # polling through two multi-second zero windows (0.05-0.8s
            # backoff), so amplification is structurally high here — the
            # budget bounds it without pretending a parked request is one
            # attempt.  Production gateways park on a wake signal instead.
            max_retry_amplification=12.0, max_shed_fraction=1.0,
        ),
        # gateway persistence: requests held across a zero window retry
        # until the fleet wakes
        client_max_attempts=40,
        client_retry_budget_s=240.0,
    )


def prefix_store_scenario(seed: int = 17) -> Scenario:
    """Hot-wake proof (docs/kv_hierarchy.md): chat traffic dominated by
    one shared system prefix rides the fleet through a scale-to-zero
    window.  Life 0 serves the first chat wave — the shared prefix page
    is registered, REUSED, and therefore written through to each node's
    persistent prefix store — then the fleet passes through zero and the
    woken engines page the prefix back in from the node's durable files:
    warm-prefix TTFT with prefix hits from request one, before any
    same-life prefill registered those digests (prefix_store
    adopted_hit_tokens > 0 in the replica summaries is exactly that
    claim).  Goodput 1.0, zero lost/duplicated tokens, byte-identical
    per seed — the tier-1 leg of ISSUE 13's acceptance."""
    costs = StubCosts(
        prefill_base_s=0.01, prefill_per_token_s=2e-4, decode_step_s=0.02,
        compile_s=3.0, aot_load_s=0.1)
    return Scenario(
        name="prefix-store",
        seed=seed,
        n_replicas=2,
        spec=ReplicaSpec(costs=costs, kv_persist=True),
        # this scenario's claim is per-NODE: EVERY node persists the
        # prefix in life 0 and wakes hot off its own durable files.  The
        # resident-prefix pick term would defeat the setup by steering
        # all chat traffic to whichever replica registered the prefix
        # first; locality steering has its own proofs
        # (peer_fabric_scenario, tests/test_epp_scheduler.py
        # TestPickerPeerFabric), so pin it off here.
        resident_weight=0.0,
        workload=WorkloadConfig(
            n_requests=40, duration_s=24.0,
            # chat-dominant: the shared system prefix is the traffic shape
            # the persistent store exists for; the batch leg keeps some
            # non-prefix pressure in the mix
            mix={"chat": 0.85, "batch": 0.15},
            bursts=[(14.0, 6)],
        ),
        churn=[
            # ~8s of life-0 chat (prefix registered + reused + persisted),
            # then the whole fleet scales to zero mid-trace and wakes warm
            ChurnEvent(at_s=8.0, kind="scale_down", replica="replica-0",
                       grace_s=0.0),
            ChurnEvent(at_s=8.0, kind="scale_down", replica="replica-1",
                       grace_s=0.0),
            ChurnEvent(at_s=12.0, kind="scale_up", replica="replica-0"),
            ChurnEvent(at_s=12.2, kind="scale_up", replica="replica-1"),
        ],
        budget=SLOBudget(
            # the zero window is absorbed in TTFT; what may NOT happen is
            # a drop or a duplicated token across the wake
            p99_ttft_s=25.0, p99_itl_s=2.0, min_goodput=1.0,
            # client-retry polling through the zero window (see
            # scale_zero_scenario's note on why this is structurally high)
            max_retry_amplification=12.0, max_shed_fraction=1.0,
        ),
        client_max_attempts=40,
        client_retry_budget_s=240.0,
    )


def peer_fabric_scenario(seed: int = 29) -> Scenario:
    """Cross-replica KV page fabric, end to end (tier-1; docs/
    kv_hierarchy.md "Cross-replica page serving").  Life 0 persists the
    shared chat prefix on both nodes; the fleet scales to zero and
    replica-0's DISK IS WIPED during the window (node replacement).
    replica-1 wakes first and serves off its own durable files; when
    replica-0 wakes — HBM cold AND disk empty — the only place its hot
    prefix exists is the peer, and its first admissions page it in over
    the verified fabric (peer hit + adopted tokens with a local store
    that never held the pages: exactly the fabric's claim).

    replica-0 then cycles down/wipe/up twice more, so the SAME cold
    fetch replays against an increasingly hostile peer — one wave per
    degradation row in docs/kv_hierarchy.md:

    - wave 1 (wake 12.8): clean fetch -> peer HIT, tokens adopted from
      pages the local store never held;
    - wave 2 (wake 17.0): replica-1 serves a lying 200 only digest
      verification catches -> counted corrupt, degraded to a miss +
      local re-prefill, the peer's health score visibly dinged through
      the /state bad-page evidence channel;
    - wave 3 (wake 24.05, deliberately past the 5 s cooldown of the
      breaker the corrupt page opened): the half-open probe meets two
      refused connections (partition), then a slowed-but-honest
      response -> the retry path converges back to a verified HIT and
      the success closes the breaker.

    The contract under fire: the corrupt count equals the injected
    count, nothing corrupt is ever adopted (the stub token oracle would
    catch one token of drift) — and goodput stays 1.0 with zero
    lost/duplicated tokens, byte-identical per seed."""
    costs = StubCosts(
        prefill_base_s=0.01, prefill_per_token_s=2e-4, decode_step_s=0.02,
        compile_s=3.0, aot_load_s=0.1)
    return Scenario(
        name="peer-fabric",
        seed=seed,
        n_replicas=2,
        spec=ReplicaSpec(costs=costs, kv_persist=True),
        workload=WorkloadConfig(
            n_requests=44, duration_s=26.0,
            # chat-dominant: one shared system prefix is the page set the
            # fabric moves; the batch leg keeps non-prefix pressure up
            mix={"chat": 0.85, "batch": 0.15},
            # bursts are pure batch load (no shared prefix): they exist
            # to push the CHAT stream onto the cold node — the EPP
            # resident-prefix term (correctly) steers chat AT the warm
            # peer, so each wave needs the peer busy when a chat
            # arrives.  Wave 3's burst lands at 24.0, while replica-0 is
            # still DOWN: all 12 queue on the warm peer, replica-0 wakes
            # at 24.05, and the trace's next chat arrival (~24.2) spills
            # onto the idle cold node
            bursts=[(13.0, 8), (17.2, 6), (24.0, 12)],
        ),
        churn=[
            # life 0 registers + reuses + persists the prefix, then the
            # fleet passes through zero
            ChurnEvent(at_s=8.0, kind="scale_down", replica="replica-0",
                       grace_s=0.0),
            ChurnEvent(at_s=8.0, kind="scale_down", replica="replica-1",
                       grace_s=0.0),
            # node replacement while down: replica-0 loses its durable
            # prefix files — its wake CANNOT hot-load from local disk
            ChurnEvent(at_s=10.0, kind="disk_wipe", replica="replica-0"),
            # the chaos legs, armed before any fetch.  `after` sequences
            # them across the page-server request stream (specs fall
            # through when skipped, so each wave meets exactly one leg):
            # request 1 clean (wave-1 hit), request 2 corrupt (wave 2),
            # requests 3-4 refused + request 5 slowed (wave 3's retry
            # path: two ConnectErrors, then a late-but-honest hit)
            ChurnEvent(at_s=11.5, kind="peer_corrupt", replica="replica-1",
                       count=1, after=1),
            ChurnEvent(at_s=11.5, kind="peer_partition",
                       replica="replica-1", count=2, after=1),
            ChurnEvent(at_s=11.5, kind="peer_slow", replica="replica-1",
                       factor=0.25, count=1, after=1),
            # replica-1 (disk-warm) wakes first so its digest-set wire is
            # gossiped into replica-0's peer index BEFORE replica-0 takes
            # its first admission
            ChurnEvent(at_s=12.0, kind="scale_up", replica="replica-1"),
            ChurnEvent(at_s=12.8, kind="scale_up", replica="replica-0"),
            # waves 2 + 3: same down/wipe/wake cycle, hostile peer
            ChurnEvent(at_s=16.0, kind="scale_down", replica="replica-0",
                       grace_s=0.0),
            ChurnEvent(at_s=16.4, kind="disk_wipe", replica="replica-0"),
            ChurnEvent(at_s=17.0, kind="scale_up", replica="replica-0"),
            ChurnEvent(at_s=20.0, kind="scale_down", replica="replica-0",
                       grace_s=0.0),
            ChurnEvent(at_s=20.4, kind="disk_wipe", replica="replica-0"),
            # wake AFTER the corrupt-opened breaker's 5 s cooldown (open
            # ~17.4-22.4) so the wave-3 fetch is the half-open probe,
            # and just after the 24.0 burst has pinned the warm peer
            ChurnEvent(at_s=24.05, kind="scale_up", replica="replica-0"),
        ],
        budget=SLOBudget(
            # the zero window + peer chaos are absorbed in TTFT; what may
            # NOT happen is a drop or a duplicated/corrupted token
            p99_ttft_s=25.0, p99_itl_s=2.0, min_goodput=1.0,
            # client-retry polling through the zero window (see
            # scale_zero_scenario's note on why this is structurally high)
            max_retry_amplification=12.0, max_shed_fraction=1.0,
        ),
        client_max_attempts=40,
        client_retry_budget_s=240.0,
    )


def autoscale_smoke_scenario(seed: int = 13,
                             policy: str = "predictive") -> Scenario:
    """Autoscaler-in-the-loop smoke (tier-1): one replica serves light
    traffic, a burst forces a scale-up (the second replica's FIRST build
    is cold — the autoscaler pays real start costs), the fleet idles down
    to ZERO, and a second burst lands inside the zero window — every one
    of those requests is parked on the hold-and-replay gateway (never
    client-retried), wakes the fleet warm, and replays with zero lost or
    duplicated tokens.  Byte-identical per seed like every scenario."""
    return Scenario(
        name=f"autoscale-smoke-{policy}",
        seed=seed,
        n_replicas=2,
        spec=_canned_spec(),
        workload=WorkloadConfig(
            n_requests=30, duration_s=16.0,
            # burst 1: scale-up pressure while replica-1 has never built
            # (cold start under autoscaler control); burst 2 arrives ~4s
            # after the fleet reached zero — the zero-window leg
            bursts=[(6.0, 10), (30.0, 8)],
        ),
        autoscaler=AutoscalerSpec(
            policy=policy,
            min_replicas=0,
            initial_replicas=1,
            interval_s=0.5,
            drain_grace_s=0.5,
            reactive=ReactiveConfig(
                queue_high_per_replica=5.0,
                queue_low_per_replica=1.0,
                idle_to_zero_s=5.0,
                up_cooldown_s=1.0,
                down_cooldown_s=3.0,
            ),
        ),
        budget=SLOBudget(
            # TTFT absorbs the queue behind the cold scale-up and the
            # zero-window hold; what may NOT happen is a drop
            p99_ttft_s=20.0, p99_itl_s=2.0, min_goodput=0.98,
            # holds are NOT retries: the zero window costs no attempts, so
            # the budget stays tight (contrast scale_zero_scenario's 12x
            # retry-polling budget — the contract this subsystem replaces)
            max_retry_amplification=3.0, max_shed_fraction=1.0,
        ),
    )


def autoscale_burst_scenario(policy: str, seed: int = 21,
                             n_requests: int = 10_000) -> Scenario:
    """The policy-judging acceptance trace (slow): a 40-virtual-minute
    10k-request workload with four identical bursts on a strict period.
    Run once per policy over the same seed: the PredictivePolicy's
    periodic learner observes the first three onsets and prewarms the
    pool before the fourth, which the ReactivePolicy only answers after
    the queue exists — the burst TTFT p99 delta (at a bounded
    warm-replica-minute premium) is the number the reconciler defaults
    were chosen on (tests/test_autoscale.py::TestPolicyAcceptance)."""
    period = 480.0
    duration = 2400.0
    # realistic replica-start bill (docs/coldstart.md): an 8B-int8 wake is
    # seconds of AOT executable load + streamed weights even with a warm
    # node cache, not milliseconds — THIS is what makes prewarming a real
    # policy question.  Nodes start cache-prewarmed (warmed-PVC recipe),
    # so every wake pays aot_load_s; a cold node would pay compile_s.
    costs = StubCosts(
        prefill_base_s=0.01, prefill_per_token_s=2e-4, decode_step_s=0.02,
        compile_s=45.0, aot_load_s=8.0)
    return Scenario(
        name=f"autoscale-burst-{policy}",
        seed=seed,
        n_replicas=4,
        spec=ReplicaSpec(costs=costs),
        workload=WorkloadConfig(
            n_requests=n_requests - 320, duration_s=duration,
            bursts=[(period * k, 80) for k in (1, 2, 3, 4)],
        ),
        autoscaler=AutoscalerSpec(
            policy=policy,
            min_replicas=1,
            initial_replicas=1,
            interval_s=0.5,
            drain_grace_s=0.5,
            node_cache_prewarmed=True,
            reactive=ReactiveConfig(
                queue_high_per_replica=6.0,
                queue_low_per_replica=1.0,
                idle_to_zero_s=30.0,
                up_cooldown_s=2.0,
                down_cooldown_s=8.0,
            ),
            predictive=PredictiveConfig(
                # well above background arrival noise (~4 req/s Poisson
                # jitter reaches slope ~2-3); a real 80-request burst
                # registers ~20 — spurious slope prewarms are pure
                # warm-pool waste
                slope_up_per_s2=6.0,
                burst_rate_per_s=12.0,
                min_period_s=60.0,
                period_tolerance_frac=0.2,
                min_intervals=2,
                # the lead must cover the wake bill: replicas prewarmed
                # 12s out are READY when the predicted burst lands, while
                # the reactive policy's post-onset wakes spend their first
                # aot_load_s seconds useless
                prewarm_lead_s=12.0,
                prewarm_hold_s=10.0,
                prewarm_replicas=4,
            ),
        ),
        budget=SLOBudget(
            p99_ttft_s=30.0, p99_itl_s=3.0, min_goodput=0.98,
            max_retry_amplification=2.0, max_shed_fraction=0.25,
        ),
    )


def churn_10k_scenario(seed: int = 1234,
                       spec_decode_k: Optional[int] = None) -> Scenario:
    """The acceptance-scale trace (ISSUE 8): 10k requests over 4 replicas
    with preemptions, a rolling restart, a crash, a breaker trip, a shed
    storm and a slow-replica skew — deterministic on CPU, zero real
    sleeps, assert_slo-hard.  The gray leg (ISSUE 14): late in the trace
    replica-2 turns 15x slow while staying alive and pollable; the
    watchdog + health-quarantine + hedge defense must keep p99 TTFT/ITL
    inside the same SLO budget — the number a binary-only breaker fleet
    fails, because nothing in it ever stops routing to a slow-but-200
    replica.  The peer-fabric leg (ISSUE 19): replica-0's rolling
    restart doubles as a node replacement (disk_wipe), so its wake pages
    hot prefixes in over the verified cross-replica fabric through a
    lying peer and a straggler at 10k scale."""
    return Scenario(
        name="churn-10k",
        seed=seed,
        n_replicas=4,
        # the prefix-store leg: every node persists its hot prefixes, so
        # the rolling-restart/crash recoveries inside the trace come back
        # prefix-HOT (pageins > 0 asserted by the slow acceptance test);
        # watchdog on fleet-wide — the gray leg's backstop, and proof the
        # monitor stays quiet through 10k requests of ordinary churn
        # spec_decode_k=None keeps the canonical trace byte-identical to
        # its pre-spec self; the slow acceptance suite runs a SECOND leg
        # with speculation on fleet-wide (zero lost/duplicated tokens at
        # 10k scale, byte-identical per seed — ISSUE 15)
        spec=ReplicaSpec(costs=_CANNED_COSTS, kv_persist=True,
                         watchdog=True, watchdog_suspect_s=2.0,
                         watchdog_confirm_s=2.0,
                         spec_decode_k=spec_decode_k),
        hedge_itl_s=1.5,
        workload=WorkloadConfig(
            n_requests=10_000, duration_s=1200.0,
            # the 300s burst IS the shed storm's trigger; the later bursts
            # guarantee live streams exactly when the rolling restart's
            # zero-grace drains and the crash land, so checkpoints, resumes
            # and crash retries fire at scale on every run
            bursts=[(300.0, 120), (419.5, 40), (479.5, 40), (659.5, 30)],
        ),
        churn=[
            ChurnEvent(at_s=60.0, kind="preempt", replica="replica-0",
                       count=3),
            ChurnEvent(at_s=150.0, kind="skew", replica="replica-3",
                       factor=3.0),
            ChurnEvent(at_s=240.0, kind="breaker_trip", replica="replica-2",
                       count=12),
            ChurnEvent(at_s=300.0, kind="shed_storm", factor=0.25),
            ChurnEvent(at_s=330.0, kind="heal_shed"),
            # rolling restart: one replica at a time; zero-grace drains
            # force checkpoint+resume, the last one lets short streams
            # finish inside the budget
            ChurnEvent(at_s=420.0, kind="drain_restart", replica="replica-0",
                       restart_after_s=5.0, grace_s=0.0),
            # the peer-fabric leg: replica-0's durable prefix files are
            # lost during its restart window (node replacement), so hot
            # prefixes page in over the fabric — replica-2 serves 0.2s
            # late for a stretch and then turns outright hostile,
            # corrupting two fetches under an honest-looking 200.  The
            # after=2 skip leaves the first reached fetches clean so the
            # corruption lands mid-wave, where verification + prefix
            # truncation must degrade to local re-prefill without losing
            # token-exactness
            ChurnEvent(at_s=422.0, kind="disk_wipe", replica="replica-0"),
            ChurnEvent(at_s=422.0, kind="peer_corrupt", replica="replica-2",
                       count=2, after=2),
            ChurnEvent(at_s=422.0, kind="peer_slow", replica="replica-2",
                       factor=0.2, count=6),
            ChurnEvent(at_s=480.0, kind="drain_restart", replica="replica-1",
                       restart_after_s=5.0, grace_s=0.0),
            ChurnEvent(at_s=540.0, kind="drain_restart", replica="replica-2",
                       restart_after_s=5.0, grace_s=1.0),
            ChurnEvent(at_s=600.0, kind="heal_skew", replica="replica-3"),
            ChurnEvent(at_s=660.0, kind="crash", replica="replica-3",
                       restart_after_s=5.0),
            ChurnEvent(at_s=800.0, kind="preempt", replica="replica-1",
                       count=3),
            # the gray leg: replica-2 degrades 20x while alive and
            # pollable — quarantine + hedge migration must hold the SLO
            # (20x puts its inter-chunk gap ~1.6s, past the 1.5s hedge)
            ChurnEvent(at_s=900.0, kind="slow_decode", replica="replica-2",
                       factor=20.0),
            ChurnEvent(at_s=980.0, kind="heal_skew", replica="replica-2"),
        ],
        budget=SLOBudget(
            p99_ttft_s=30.0, p99_itl_s=3.0, min_goodput=0.98,
            max_retry_amplification=2.0, max_shed_fraction=0.2,
        ),
    )
