"""The fleet simulator: real routing/resilience code over stub replicas.

`FleetSim.run()` plays a seeded workload trace against N `SimReplica`s
through the REAL serving stack: the `EndpointPicker` scores and routes
every request (prefix affinity, queue depth, breaker and lifecycle
exclusion), the resilience `RetryPolicy`/`BreakerRegistry`/`LoadShedder`
decide retries and rejections, and the engines run production admission
/ batching / preemption / drain / checkpoint logic.  Churn events fire
against the same SimClock.  The output is a canonical goodput report
(report.build_report) that is byte-identical for a given scenario+seed.

The client loop mirrors the REST client's retry contract (PR 4/5): a
preempted stream carries its GenerationCheckpoint to the next attempt
and the user-visible stream is the salvage splice + continuation; a
crash retry (no checkpoint) restarts from the prompt and replaces the
stream.  Every retry is counted into `request_retry_attempts_total`
{component="sim"} — the same series production dashboards watch.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..autoscale import (
    AutoscalerLoop,
    FleetSignals,
    HoldExpiredError,
    HoldOverflowError,
    HoldQueue,
    RateTracker,
    ReplicaActuator,
)
from ..autoscale.signals import ArrivalHistory
import httpx

from ..kvstore import PeerPageClient
from ..lifecycle import GenerationPreempted, ReplicaDrainingError
from ..lifecycle.checkpoint import GenerationCheckpoint
from ..logging import logger
from ..metrics import (
    RETRY_ATTEMPTS,
    record_breaker_transition,
    record_generation_migration,
)
from ..observability import RequestTimeline
from ..resilience import (
    BreakerConfig,
    BreakerRegistry,
    Deadline,
    DeadlineExceededError,
    FaultInjectingTransport,
    FaultPlan,
    FaultSpec,
    RetryPolicy,
    deadline_scope,
)
from ..scheduler.health import FleetHealth
from ..scheduler.picker import EndpointPicker
from .clock import SimClock
from .replica import SIM_MODEL_NAME, SimReplica
from .report import build_report
from .scenario import ChurnEvent, Scenario
from .stub import expected_stream
from .workload import SimRequest, generate_trace


@dataclass
class ClientRecord:
    """Client-side accounting for one trace request."""

    rid: str
    kind: str
    index: int
    attempts: int = 0
    sheds: int = 0
    resumes: int = 0
    crash_restarts: int = 0
    migrations: int = 0  # stall-triggered moves off a gray replica
    no_backend: int = 0
    held: int = 0  # times parked on the hold-and-replay gateway
    outcome: str = "pending"
    n_tokens: int = 0
    lost_tokens: int = 0
    duplicated_tokens: int = 0
    salvaged_tokens: int = 0
    token_exact: bool = False
    ttft_s: Optional[float] = None
    e2e_s: Optional[float] = None
    itls: List[float] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "rid": self.rid, "kind": self.kind, "attempts": self.attempts,
            "sheds": self.sheds, "resumes": self.resumes,
            "crash_restarts": self.crash_restarts,
            "migrations": self.migrations,
            "no_backend": self.no_backend, "held": self.held,
            "outcome": self.outcome,
            "n_tokens": self.n_tokens, "lost_tokens": self.lost_tokens,
            "duplicated_tokens": self.duplicated_tokens,
            "salvaged_tokens": self.salvaged_tokens,
            "token_exact": self.token_exact, "ttft_s": self.ttft_s,
            "e2e_s": self.e2e_s, "itls": self.itls,
        }


class FleetSim:
    def __init__(self, scenario: Scenario):
        self.scenario = scenario
        self.clock = SimClock()
        self.trace: List[SimRequest] = generate_trace(
            scenario.workload, scenario.seed)
        asc = scenario.autoscaler
        self.replicas: Dict[str, SimReplica] = {}
        params = None
        for i, name in enumerate(scenario.replica_names()):
            # autoscaler-managed fleets defer replicas beyond the initial
            # footprint: their first scale-up builds COLD (empty node AOT
            # cache) — the start cost the policy is charged for
            build_now = asc is None or i < asc.initial_replicas
            r = SimReplica(
                name, self.clock, scenario.spec, params=params,
                build_now=build_now,
                node_cache_warm=(asc is not None
                                 and asc.node_cache_prewarmed))
            r.set_fault_plan(FaultPlan([], seed=scenario.seed))
            params = r.params
            self.replicas[name] = r
        self.by_url = {r.url: r for r in self.replicas.values()}
        self.picker = EndpointPicker(
            [r.url for r in self.replicas.values()],
            clock=self.clock,
            # resident-prefix steering: scenario-tunable so symmetric-
            # traffic proofs (prefix_store_scenario) can pin it off
            **({} if scenario.resident_weight is None
               else {"resident_weight": scenario.resident_weight}),
            # gray-failure health layer (scheduler/health.py): scenario-
            # tunable config; None takes the picker's production defaults
            health=(FleetHealth(scenario.health, clock=self.clock)
                    if scenario.health is not None else None),
            breakers=BreakerRegistry(
                BreakerConfig(window=20, failure_threshold=0.5,
                              min_volume=4, open_for_s=5.0),
                clock=self.clock,
                # same transition metric production wires (tests assert a
                # simulated trip shows up on the real dashboard series)
                on_transition=record_breaker_transition,
            ),
        )
        # fleet-network fault plan (breaker trips, injected 503s/connect
        # errors between gateway and replica), matched on the DELIMITED
        # "<name>/proxy" target — a bare name would substring-match
        # replica-1 against replica-10+ in larger fleets
        self.net_plan = FaultPlan([], seed=scenario.seed + 1)
        # cross-replica page fabric (docs/kv_hierarchy.md "Cross-replica
        # page serving"): one verified PeerPageClient per persisting
        # replica, riding a FaultInjectingTransport whose handler answers
        # straight off the OTHER replicas' real engines/stores.  The
        # transport shares net_plan under the "/kv"-suffixed target
        # namespace, so peer churn ("replica-1/kv") can never collide
        # with client-path specs ("replica-1/proxy").
        self.peer_clients: Dict[str, PeerPageClient] = {}
        if scenario.spec.kv_persist:
            for i, r in enumerate(self.replicas.values()):
                transport = FaultInjectingTransport(
                    self.net_plan, handler=self._peer_page_handler,
                    clock=self.clock, target_suffix="/kv")
                client = PeerPageClient(
                    httpx.AsyncClient(transport=transport),
                    self_url=r.url,
                    clock=self.clock,
                    retry=RetryPolicy(
                        max_attempts=3, base_backoff_s=0.05,
                        max_backoff_s=0.4, retry_budget_s=2.0,
                        seed=scenario.seed * 131 + i),
                    breakers=BreakerRegistry(
                        BreakerConfig(window=8, failure_threshold=0.5,
                                      min_volume=2, open_for_s=5.0),
                        clock=self.clock),
                    fetch_deadline_s=2.0,
                )
                r.set_peer_client(client)
                self.peer_clients[r.url] = client
        self._validate_churn()
        self.records: List[ClientRecord] = []
        self._completed = 0
        self._tasks: List[asyncio.Task] = []
        self._churn_subtasks: List[asyncio.Task] = []
        # ---------------- autoscaler-in-the-loop (docs/autoscaling.md)
        self.autoscaler: Optional[AutoscalerLoop] = None
        self.hold_queue: Optional[HoldQueue] = None
        self.arrivals: Optional[ArrivalHistory] = None
        self._shed_rate = RateTracker()
        self._desired_on = scenario.n_replicas
        if asc is not None:
            if not 0 <= asc.initial_replicas <= scenario.n_replicas:
                raise ValueError(
                    f"initial_replicas {asc.initial_replicas} outside "
                    f"[0, {scenario.n_replicas}]")
            self._desired_on = asc.initial_replicas
            # wall anchor (ROADMAP 1c): lets a scenario fabricate a
            # time-of-day mapping for day-scale periodic detection
            self.arrivals = ArrivalHistory(wall_anchor_s=asc.wall_anchor_s)
            self.autoscaler = AutoscalerLoop(
                asc.build_policy(),
                self._fleet_signals,
                _SimActuator(self),
                clock=self.clock,
                interval_s=asc.interval_s,
                min_replicas=asc.min_replicas,
                max_replicas=asc.max_replicas or scenario.n_replicas,
                decision_log=100_000,  # the report wants the full history
            )
            # a parked request is the scale-from-zero trigger: the hold
            # wakes the loop at the instant it registers
            self.hold_queue = HoldQueue(
                clock=self.clock,
                max_holds=asc.hold_max,
                default_hold_s=asc.hold_timeout_s,
                on_hold=self.autoscaler.notify_demand,
            )

    # ---------------- fleet plumbing ----------------

    _CHURN_KINDS = frozenset({
        "preempt", "crash", "drain_restart", "breaker_trip",
        "shed_storm", "heal_shed", "skew", "heal_skew",
        "scale_down", "scale_up",
        "slow_decode", "wedged_fetch", "flapping",
        "peer_corrupt", "peer_partition", "peer_slow", "disk_wipe",
    })
    _FLEET_WIDE = frozenset({"shed_storm", "heal_shed"})

    def _validate_churn(self) -> None:
        """Fail a misconfigured scenario at construction, not silently at
        its at_s inside a background task (where the error would otherwise
        read as a churn-free green run)."""
        for ev in self.scenario.churn:
            if ev.kind not in self._CHURN_KINDS:
                raise ValueError(
                    f"unknown churn kind {ev.kind!r} (at_s={ev.at_s}); "
                    f"known: {sorted(self._CHURN_KINDS)}")
            if ev.kind not in self._FLEET_WIDE and (
                    ev.replica not in self.replicas):
                raise ValueError(
                    f"churn event {ev.kind!r} at_s={ev.at_s} names unknown "
                    f"replica {ev.replica!r}; have "
                    f"{sorted(self.replicas)}")

    async def _poll_loop(self) -> None:
        """The EPP's scrape loop: feeds each replica's real scheduler
        state (or a failure observation for a dead one) to the picker —
        and re-serves each replica's advertised digest-set wire to every
        OTHER replica's peer index (the EPP gossip leg of the fabric)."""
        while True:
            for r in self.replicas.values():
                if r.alive:
                    state = r.state_payload()
                    self.picker.observe_state(r.url, state)
                    self._gossip_peer_pages(r.url, state.get("peer_pages"))
                else:
                    self.picker.observe_failure(r.url)
            self._release_holds()
            await self.clock.sleep(self.scenario.poll_interval_s)

    def _gossip_peer_pages(self, url: str, wire) -> None:
        """Feed one replica's resident digest-set into every other
        replica's PeerPageIndex (generation-stamped: stale re-deliveries
        are ignored by the index itself).  A dead replica's last set is
        deliberately KEPT — fetching from a gone peer is the partition
        case the breaker + miss degradation already absorb."""
        if wire is None:
            return
        for owner_url, client in self.peer_clients.items():
            if owner_url != url:
                client.index.update(url, wire)

    def _peer_page_handler(self, request: httpx.Request):
        """The page-server half of the fabric, in-memory: GET
        {PAGE_ROUTE}/{digest} answered from the named replica's REAL
        engine + persistent store (protocol/rest/server.py's route minus
        the aiohttp plumbing)."""
        host = request.url.host or ""
        server = self.replicas.get(host)
        if server is None or not server.alive:
            # nothing listening: same wire shape as a dead/partitioned pod
            raise httpx.ConnectError("peer not listening", request=request)
        try:
            digest = bytes.fromhex(request.url.path.rsplit("/", 1)[-1])
        except ValueError:
            return 404, {"error": "not a page digest"}
        wire = server.engine.read_peer_page(digest)
        if wire is None:
            return 404, {"error": "page not resident"}
        server.peer_pages_served += 1
        return 200, wire

    def _release_holds(self) -> None:
        """Replay parked requests once any backend is accepting again (the
        activator's readiness-watch leg, on the sim's poll cadence)."""
        if self.hold_queue is None or self.hold_queue.held == 0:
            return
        if any(r.accepting for r in self.replicas.values()):
            self.hold_queue.release_all()

    def _fleet_signals(self) -> FleetSignals:
        """The EPP's FleetSignals export, built from the production picker
        state (scheduler/picker.snapshot()) exactly like epp.py does —
        stale by up to one poll interval, as in production."""
        asc = self.scenario.autoscaler
        now = self.clock.now()
        states = self.picker.snapshot()
        sheds_total = sum(int(s.get("sheds_total", 0) or 0) for s in states)
        return FleetSignals.from_replica_states(
            states, now,
            arrival_rate_per_s=self.arrivals.rate(
                now, asc.arrival_rate_window_s),
            arrival_slope_per_s2=self.arrivals.slope(
                now, asc.arrival_slope_window_s),
            shed_rate_per_s=self._shed_rate.update(sheds_total, now),
            held_requests=self.hold_queue.held,
        )

    async def _churn_loop(self) -> None:
        for ev in sorted(self.scenario.churn, key=lambda e: e.at_s):
            await self.clock.sleep_until(ev.at_s)
            self._apply_churn(ev)

    def _apply_churn(self, ev: ChurnEvent) -> None:
        r = self.replicas.get(ev.replica) if ev.replica else None
        if ev.kind == "preempt":
            r.fault_plan.specs.append(FaultSpec(
                "engine.preempt", "preempt", count=ev.count))
        elif ev.kind == "crash":
            self._churn_subtasks.append(asyncio.create_task(
                self._crash_restart(r, ev.restart_after_s)))
        elif ev.kind == "drain_restart":
            self._churn_subtasks.append(asyncio.create_task(
                self._drain_restart(r, ev.restart_after_s, ev.grace_s)))
        elif ev.kind == "scale_down":
            # autoscaler scale-in (to zero when it hits every replica):
            # graceful drain checkpoints in-flight work out to the
            # clients, then the pod is GONE until a scale_up — the
            # gateway (client retry loop) holds and replays
            self._churn_subtasks.append(asyncio.create_task(
                self._scale_down(r, ev.grace_s)))
        elif ev.kind == "scale_up":
            # wake: fresh pod on the same node — warm AOT cache, so the
            # stub charges aot_load_s instead of compile_s before ready
            self._churn_subtasks.append(asyncio.create_task(
                self._scale_up(r)))
        elif ev.kind == "breaker_trip":
            self.net_plan.specs.append(FaultSpec(
                f"{r.name}/proxy", "http_status", status=503,
                count=ev.count))
        elif ev.kind == "shed_storm":
            for rep in self.replicas.values():
                cfg = rep.shedder.config
                cfg.queue_watermark = max(
                    1, int(rep.spec.shed_watermark * ev.factor))
        elif ev.kind == "heal_shed":
            for rep in self.replicas.values():
                rep.shedder.config.queue_watermark = rep.spec.shed_watermark
        elif ev.kind == "skew":
            r.device.skew = ev.factor
        elif ev.kind == "slow_decode":
            # gray: the replica stays alive, polls green, and serves
            # `factor`x slower — only health-score outlier detection
            # (and the client's inter-token hedge) route around it
            r.device.skew = ev.factor
        elif ev.kind == "wedged_fetch":
            # gray: the fetch worker stops delivering for `factor`
            # virtual seconds; liveness stays green — the engine
            # watchdog must confirm the stall and self-drain
            r.device.wedge_fetch_until(self.clock.now() + ev.factor)
        elif ev.kind == "flapping":
            # gray: compute alternates normal / factor-slow in period_s
            # windows — the shape that defeats consecutive-failure counts
            r.device.flap(ev.period_s, ev.factor)
        elif ev.kind == "heal_skew":
            r.device.heal_gray()
        elif ev.kind in ("peer_corrupt", "peer_partition"):
            # page-fabric faults: the "/kv" namespace of the shared net
            # plan — fetches TO ev.replica's page server get a flipped
            # byte under a 200 (corrupt) or connection-refused (partition)
            self.net_plan.specs.append(FaultSpec(
                f"{r.name}/kv", ev.kind, count=ev.count, after=ev.after))
        elif ev.kind == "peer_slow":
            # straggler page server: fetches proceed, `factor` virtual
            # seconds late — the client's per-fetch deadline caps the
            # damage to one admission's page-in budget
            self.net_plan.specs.append(FaultSpec(
                f"{r.name}/kv", "peer_slow", latency_s=ev.factor,
                count=ev.count, after=ev.after))
        elif ev.kind == "disk_wipe":
            # node replacement: the persistent prefix files are gone (the
            # replica should be down when this fires); the next build
            # indexes an empty store and the wake must page hot prefixes
            # in over the peer fabric instead of local disk
            r.wipe_persist_dir()
        else:
            raise ValueError(f"unknown churn kind {ev.kind!r}")

    async def _crash_restart(self, r: SimReplica, after_s: float) -> None:
        await r.crash()
        await self.clock.sleep(after_s)
        await r.restart()
        # recycled-address contract: the fresh process must not inherit
        # the dead one's breaker state — or its quarantine
        self.picker.breakers.forget(r.url)
        self.picker.health.forget(r.url)

    async def _drain_restart(self, r: SimReplica, after_s: float,
                             grace_s) -> None:
        await r.drain(grace_s)
        await r.stop()
        await self.clock.sleep(after_s)
        await r.restart()
        self.picker.breakers.forget(r.url)
        self.picker.health.forget(r.url)

    async def _scale_down(self, r: SimReplica, grace_s) -> None:
        await r.drain(grace_s)
        await r.stop()

    async def _scale_up(self, r: SimReplica) -> None:
        await r.restart()
        self.picker.breakers.forget(r.url)

    async def _spawn_clients(self) -> None:
        for req in self.trace:
            await self.clock.sleep_until(req.arrival_s)
            self._tasks.append(asyncio.create_task(self._client(req)))

    # ---------------- the client ----------------

    async def _client(self, req: SimRequest) -> None:
        index = len(self.records)
        rec = ClientRecord(rid=req.rid, kind=req.kind, index=index)
        self.records.append(rec)
        if self.arrivals is not None:
            # the gateway's arrival stamp (predictive policies learn from
            # this) — recorded at the door, before any pick
            self.arrivals.record(self.clock.now())
        tl = RequestTimeline(req.rid, model_name="fleet")
        tl.mark_received(self.clock.now())
        started = self.clock.now()
        deadline = (
            Deadline.after(req.deadline_s, self.clock)
            if req.deadline_s is not None else None
        )
        policy = RetryPolicy(
            max_attempts=self.scenario.client_max_attempts,
            base_backoff_s=0.05, max_backoff_s=0.8,
            retry_budget_s=self.scenario.client_retry_budget_s,
            seed=self.scenario.seed * 1_000_003 + index,
        )
        ckpt = None
        shown: List[int] = []
        while True:
            rec.attempts += 1
            status, retry_after, ckpt, shown = await self._attempt(
                req, rec, tl, ckpt, shown, deadline)
            if status in ("completed", "deadline_exceeded", "rejected"):
                rec.outcome = status
                break
            delay = policy.next_delay(
                rec.attempts,
                retry_after=retry_after,
                elapsed=self.clock.now() - started,
                deadline=deadline,
            )
            if delay is None:
                rec.outcome = (
                    "deadline_exceeded"
                    if deadline is not None and deadline.expired
                    else "gave_up"
                )
                break
            RETRY_ATTEMPTS.labels(component="sim").inc()
            await self.clock.sleep(delay)
        self._account_tokens(req, rec, shown)
        tl.mark_finished(self.clock.now(), rec.outcome)
        rec.ttft_s = tl.ttft_s
        rec.e2e_s = tl.e2e_s
        rec.itls = list(tl.itls)
        self._completed += 1

    async def _attempt(self, req: SimRequest, rec: ClientRecord,
                       tl: RequestTimeline, ckpt, shown: List[int],
                       deadline) -> tuple:
        if deadline is not None and deadline.expired:
            return "deadline_exceeded", None, ckpt, shown
        # is_canary: this request is a quarantined replica's re-probe —
        # its completion must be reported as canary proof (a sick canary
        # fails via the hedge's note_stall or the error paths)
        pick, is_canary = self.picker.pick_ex(prompt_ids=req.prompt_ids)
        while pick is None and self.hold_queue is not None:
            # the hold-and-replay gateway leg: a request arriving into a
            # zero window (or any no-backend window) parks at the gateway
            # — registering the hold wakes the autoscaler — and replays
            # when a replica comes up.  NOT a retry: no attempt is burned,
            # no backoff is paid, no client persistence is assumed.
            rec.held += 1
            try:
                await self.hold_queue.hold(deadline)
            except HoldExpiredError:
                # production maps this to 504 (activator contract)
                return "deadline_exceeded", None, ckpt, shown
            except HoldOverflowError as exc:
                rec.no_backend += 1
                return "retry", exc.retry_after_s, ckpt, shown
            except RuntimeError:
                # fail_all at teardown (or a failed wake): the hold is
                # gone; fall back to the ordinary retry path
                rec.no_backend += 1
                return "retry", None, ckpt, shown
            pick, is_canary = self.picker.pick_ex(prompt_ids=req.prompt_ids)
        if pick is None:
            rec.no_backend += 1
            return "retry", None, ckpt, shown
        replica = self.by_url[pick.url]
        # injected network faults between gateway and replica (breaker
        # trips ride injected 503s; a crashed process is connect-refused);
        # delimited target: "replica-1/proxy" never matches replica-10+
        spec = self.net_plan.decide(f"{replica.name}/proxy")
        if spec is not None and spec.kind in ("connect_error",
                                              "replica_crash"):
            self.picker.observe_failure(pick.url)
            return "retry", None, ckpt, shown
        if spec is not None and spec.kind == "http_status":
            self.picker.observe_http_error(pick.url)
            return "retry", spec.retry_after_s, ckpt, shown
        if not replica.alive:
            self.picker.observe_failure(pick.url)
            return "retry", None, ckpt, shown
        if not self.picker.breakers.allow(pick.url):
            return "retry", None, ckpt, shown
        if replica.shedder.should_shed(replica.engine.queue_depth):
            rec.sheds += 1
            self.picker.observe_http_error(pick.url)
            return "retry", replica.shedder.retry_after_s, ckpt, shown
        rid_attempt = f"{req.rid}~a{rec.attempts}"
        # the user-visible stream for this attempt: a resume splices the
        # checkpoint's salvaged tokens (PR 5's _splice_resume contract), a
        # fresh attempt replaces the stream entirely
        shown = list(ckpt.generated) if ckpt is not None else []
        try:
            with deadline_scope(deadline):
                if ckpt is not None:
                    stream = replica.engine.resume_generation(
                        ckpt, request_id=rid_attempt)
                else:
                    stream = replica.engine.generate(
                        req.prompt_ids, req.sampling_params(),
                        request_id=rid_attempt, adapter=req.adapter)
            hedge = self.scenario.hedge_itl_s
            if hedge is None:
                # no hedging: the plain iteration — a per-token
                # ensure_future would add a Task allocation per token to
                # every pre-gray scenario for nothing
                async for out in stream:
                    if out.token_id >= 0:
                        shown.append(out.token_id)
                        tl.mark_token(self.clock.now())
                    if deadline is not None and deadline.expired:
                        replica.engine.cancel(rid_attempt)
                        return "deadline_exceeded", None, ckpt, shown
                    if out.finished:
                        break
                self.picker.observe_success(pick.url)
                if is_canary:
                    self.picker.observe_canary(pick.url, True)
                return "completed", None, ckpt, shown
            it = stream.__aiter__()
            got_token = False
            while True:
                nxt = asyncio.ensure_future(it.__anext__())
                if got_token:
                    # stall-triggered migration (docs/resilience.md): an
                    # inter-token gap past the hedge deadline means this
                    # stream is parked on a gray replica.  Checkpoint it
                    # CLIENT-side from the tokens already shown (token-
                    # exact: the stub chain is a pure function of
                    # (prompt_len, position)), cancel the sick seat, and
                    # re-submit to a healthy replica.
                    timer = asyncio.ensure_future(self.clock.sleep(hedge))
                    await asyncio.wait({nxt, timer},
                                       return_when=asyncio.FIRST_COMPLETED)
                    if not nxt.done():
                        # cancel BOTH: a stranded hedge timer would sit
                        # on the SimClock heap and drag finished_at_s
                        # forward at drain_timers
                        timer.cancel()
                        nxt.cancel()
                        migrated = await self._migrate_stalled(
                            replica, rid_attempt, nxt, it, pick.url)
                        if migrated:
                            new_ckpt = GenerationCheckpoint.capture(
                                request_id=req.rid,
                                prompt_ids=req.prompt_ids,
                                generated=shown,
                                params=req.sampling_params(),
                                adapter=req.adapter,
                                model_name=SIM_MODEL_NAME,
                                deadline=deadline,
                                reason="hedge")
                            rec.migrations += 1
                            record_generation_migration("hedge")
                            return "retry", 0.0, new_ckpt, shown
                    else:
                        timer.cancel()
                try:
                    out = await nxt
                except StopAsyncIteration:
                    break
                if out.token_id >= 0:
                    shown.append(out.token_id)
                    got_token = True
                    tl.mark_token(self.clock.now())
                if deadline is not None and deadline.expired:
                    replica.engine.cancel(rid_attempt)
                    return "deadline_exceeded", None, ckpt, shown
                if out.finished:
                    break
            self.picker.observe_success(pick.url)
            if is_canary:
                self.picker.observe_canary(pick.url, True)
            return "completed", None, ckpt, shown
        except GenerationPreempted as exc:
            rec.resumes += 1
            prev = len(ckpt.generated) if ckpt is not None else 0
            new_ckpt = exc.checkpoint
            rec.salvaged_tokens += max(len(new_ckpt.generated) - prev, 0)
            if new_ckpt.reason == "stall":
                # the replica's watchdog confirmed a stall and self-
                # drained: this resume IS a stall-triggered migration
                rec.migrations += 1
                record_generation_migration("stall")
            # 503 + checkpoint: the replica is going away; train the picker
            self.picker.observe_http_error(pick.url)
            return "retry", None, new_ckpt, shown
        except ReplicaDrainingError:
            self.picker.observe_http_error(pick.url)
            return "retry", None, ckpt, shown
        except DeadlineExceededError:
            return "deadline_exceeded", None, ckpt, shown
        except ValueError:
            # admission rejected the request outright (resume validation,
            # length bounds): a client bug, not a fleet failure — fatal
            return "rejected", None, ckpt, shown
        except RuntimeError:
            # engine crashed or stopped under us (ReplicaCrashError,
            # EngineWedgedError, "engine stopped"): the stream is gone;
            # retry resumes from the last checkpoint if one exists,
            # from the prompt otherwise
            rec.crash_restarts += 1
            self.picker.observe_failure(pick.url)
            return "retry", None, ckpt, shown

    async def _migrate_stalled(self, replica, rid_attempt: str,
                               nxt, it, url: str) -> bool:
        """Tear down a hedge-stalled stream: unwind the cancelled
        __anext__, close the generator (its finally releases the engine
        seat), cancel any residual engine state, and hand the health
        layer its stall evidence.  Always returns True — whatever the
        dying stream raised, the client-side checkpoint supersedes it
        (an engine-side checkpoint racing in here carries at most the
        same prefix the client already holds in `shown`)."""
        try:
            await nxt
        except (asyncio.CancelledError, StopAsyncIteration):
            pass
        except Exception as exc:  # noqa: BLE001 — a concurrent preempt /
            # crash surfacing in the cancelled step is superseded by the
            # migration; log for the determinism post-mortems
            logger.debug("stalled stream %s raised during migration: %s",
                         rid_attempt, exc)
        try:
            await it.aclose()
        except Exception as exc:  # noqa: BLE001 — same: the stream is dead
            logger.debug("aclose of stalled stream %s failed: %s",
                         rid_attempt, exc)
        replica.engine.cancel(rid_attempt)
        self.picker.health.note_stall(url)
        return True

    def _account_tokens(self, req: SimRequest, rec: ClientRecord,
                        shown: List[int]) -> None:
        """Token-exact accounting against the stub oracle: a completed
        request must have delivered EXACTLY its expected stream — anything
        shorter lost tokens, anything longer (or mismatched) duplicated or
        corrupted them."""
        rec.n_tokens = len(shown)
        if rec.outcome != "completed":
            return
        expected = expected_stream(len(req.prompt_ids), req.max_tokens)
        if shown == expected:
            rec.token_exact = True
            return
        rec.lost_tokens = max(len(expected) - len(shown), 0)
        rec.duplicated_tokens = max(len(shown) - len(expected), 0)
        if rec.lost_tokens == 0 and rec.duplicated_tokens == 0:
            # same length, wrong content: count each mismatch as one lost
            # (expected token never delivered) and one duplicated
            # (unexpected token delivered in its place)
            mismatches = sum(1 for a, b in zip(shown, expected) if a != b)
            rec.lost_tokens = mismatches
            rec.duplicated_tokens = mismatches

    # ---------------- the run ----------------

    async def run(self) -> dict:
        for i, r in enumerate(self.replicas.values()):
            if i < self._desired_on:
                await r.start()
        spawner = asyncio.create_task(self._spawn_clients())
        churn = asyncio.create_task(self._churn_loop())
        poll = asyncio.create_task(self._poll_loop())
        # the autoscaler loop is a WATCHED task: an exception inside it
        # (policy bug, actuation failure) fails the whole run — the same
        # contract churn tasks carry.  A silently-dead autoscaler would
        # read as a fleet frozen at its last size under a green report.
        scaler = (asyncio.create_task(self.autoscaler.run())
                  if self.autoscaler is not None else None)
        aux_tasks = [t for t in (spawner, churn, poll, scaler)
                     if t is not None]
        n = len(self.trace)

        def aux_failure():
            # a dead spawner/churn/autoscaler/restart task must FAIL the
            # run, not quietly produce a churn-free (or half-populated,
            # or frozen-fleet) green report
            for t in (*aux_tasks, *self._churn_subtasks):
                if t.done() and not t.cancelled() and t.exception():
                    return t.exception()
            return None

        try:
            await self.clock.drive(
                until=lambda: self._completed >= n or aux_failure(),
                describe_stuck=self._describe_stuck,
            )
            exc = aux_failure()
            if exc is not None:
                raise exc
            poll.cancel()
            churn.cancel()
            spawner.cancel()
            if scaler is not None:
                scaler.cancel()
            # flush in-flight engine work (abandoned decodes, pending churn
            # restarts) so teardown never waits on real time
            for t in self._churn_subtasks:
                if not t.done():
                    t.cancel()
            # watchdog tick loops re-arm a virtual timer every interval
            # forever — stop them or drain_timers below never empties
            for r in self.replicas.values():
                if r.engine is not None:
                    r.engine.stop_watchdog()
            await self.clock.drain_timers()
            finished_at = self.clock.now()
            for r in self.replicas.values():
                await r.stop()
        finally:
            # failure path (aux exception, SimDeadlockError): the engines'
            # run-loop tasks must not outlive the run — destroyed-pending
            # task spam would bury the diagnostic this path exists to raise
            for t in (*aux_tasks, *self._churn_subtasks):
                t.cancel()
            if self.hold_queue is not None:
                self.hold_queue.fail_all(
                    RuntimeError("simulation torn down"))
            for r in self.replicas.values():
                if r.engine is not None and r.engine.running:
                    await r.stop()
        for client in self.peer_clients.values():
            await client.client.aclose()
        for r in self.replicas.values():
            r.cleanup()  # the run owns the nodes' persist dirs
        faults = list(self.net_plan.log)
        for r in self.replicas.values():
            faults.extend(r.fault_plan.log)
        return build_report(
            self.scenario.name, self.scenario.seed,
            [rec.to_dict() for rec in self.records],
            [r.summary() for r in self.replicas.values()],
            faults, finished_at,
            autoscaler=self._autoscaler_summary(),
            health=self._health_summary(),
        )

    def _health_summary(self) -> Optional[dict]:
        """The report's gray-failure block: every health transition
        (quarantine / reintroduce / degrade / restore) with its virtual
        timestamp — the detection-budget evidence the gray scenario
        asserts on.  None when the run saw no transitions (keeps
        pre-gray scenario reports unchanged)."""
        transitions = self.picker.health.transitions
        if not transitions:
            return None
        counts: Dict[str, int] = {}
        for _, _, tr in transitions:
            counts[tr] = counts.get(tr, 0) + 1
        return {
            "transitions": [
                {"at_s": t, "replica": self.by_url[url].name,
                 "transition": tr}
                for t, url, tr in transitions
            ],
            "counts": dict(sorted(counts.items())),
        }

    def _autoscaler_summary(self) -> Optional[dict]:
        """The report's autoscaler block: every decision (reason-counted),
        hold-gateway outcomes, and the policy's warm-pool bill in
        replica-minutes — the currency policies are compared in."""
        if self.autoscaler is None:
            return None
        decisions = self.autoscaler.decisions
        return {
            "policy": self.scenario.autoscaler.policy,
            "ticks": self.autoscaler.ticks,
            "decisions": dict(sorted(
                self.autoscaler.decision_counts().items())),
            "scale_ups": sum(1 for d in decisions
                             if d.action == "scale_up"),
            "scale_downs": sum(1 for d in decisions
                               if d.action == "scale_down"),
            "final_desired": self._desired_on,
            "replica_up_minutes": round(sum(
                r.summary()["up_s"] for r in self.replicas.values()
            ) / 60.0, 9),
            "holds": dict(sorted(self.hold_queue.stats.items())),
        }

    def _describe_stuck(self) -> str:
        pending = [rec.rid for rec in self.records
                   if rec.outcome == "pending"]
        waiting = len(self.trace) - len(self.records)
        return (
            f"{self._completed}/{len(self.trace)} clients complete; "
            f"{waiting} not yet spawned; in-flight: {pending[:8]}"
        )


class _SimActuator(ReplicaActuator):
    """The AutoscalerLoop's hands inside the simulation: scale-up restarts
    parked replicas in index order (first-ever starts build cold, later
    wakes warm off the node AOT cache — StubCosts charges either way),
    scale-down gracefully drains from the top (checkpoints stream out to
    the held clients).  Awaited inline by the loop, so an actuation
    failure IS a loop failure IS a run failure."""

    def __init__(self, fleet: FleetSim):
        self.fleet = fleet

    async def current_replicas(self) -> int:
        return self.fleet._desired_on

    async def scale_to(self, n: int) -> None:
        fleet = self.fleet
        ordered = list(fleet.replicas.values())
        cur = fleet._desired_on
        if n > cur:
            for r in ordered[cur:n]:
                await r.restart()
                # recycled-address contract (picker.set_replicas): a fresh
                # process must not inherit breaker or health state, and
                # the picker learns the wake immediately, not a poll later
                fleet.picker.breakers.forget(r.url)
                fleet.picker.health.forget(r.url)
                fleet.picker.observe_state(r.url, r.state_payload())
        elif n < cur:
            for r in reversed(ordered[n:cur]):
                await r.drain(fleet.scenario.autoscaler.drain_grace_s)
                await r.stop()
        fleet._desired_on = n
        if n > cur:
            fleet._release_holds()


async def run_scenario(scenario: Scenario) -> dict:
    """Build a fleet for `scenario`, run it, return the goodput report."""
    return await FleetSim(scenario).run()
