"""Engine-internal child spans, emitted from a finished RequestTimeline.

The engine loop runs detached from any request's asyncio context, so the
usual start_as_current_span nesting cannot reach it.  Instead every
timeline carries the TraceContext bound when the request entered
(tracing.request_context_middleware), and when a generation reaches a
terminal state the engine emits retrospective queue/prefill/decode spans
tagged with that trace's ids — so the EPP-proxy span, the replica's
request span, and these engine internals line up as one trace in any
backend that groups by trace_id, and in the recording tracers the tests
use.

Span events carry the timeline's lifecycle events (preemptions,
checkpoints, resumes); breaker trips ride `tracing.add_span_event` at the
hop that observed them.
"""

from __future__ import annotations

from typing import Optional

from .timeline import RequestTimeline

_PHASES = (
    # (span name, start attr, end attr)
    ("engine.queue", "received", "admitted"),
    ("engine.prefill", "prefill_start", "prefill_end"),
    ("engine.decode", "first_token_at", "finished_at"),
)


def _end(span) -> None:
    if hasattr(span, "end"):
        span.end()


def _start_span(tracer, name: str, attributes: dict):
    """tracer.start_span across API generations; contextmanager-only fakes
    fall back to entering start_as_current_span and ending it inline."""
    if hasattr(tracer, "start_span"):
        return tracer.start_span(name, attributes=attributes), None
    cm = tracer.start_as_current_span(name, attributes=attributes)
    return cm.__enter__(), cm


def emit_timeline_spans(tracer, tl: Optional[RequestTimeline]) -> None:
    """Emit the engine-internal span tree for one finished timeline.  A
    None tracer or a timeline with no stamps is a no-op; failures here
    must never surface into the engine loop (the caller wraps)."""
    if tracer is None or tl is None:
        return
    base = {
        "kserve.request_id": tl.request_id,
        "kserve.model": tl.model_name,
    }
    if tl.trace is not None:
        base["trace_id"] = tl.trace.trace_id
        base["parent_span_id"] = tl.trace.span_id
    for name, start_attr, end_attr in _PHASES:
        t0 = getattr(tl, start_attr)
        t1 = getattr(tl, end_attr)
        if t0 is None or t1 is None:
            continue
        attrs = dict(base)
        attrs["start_s"] = t0
        attrs["duration_s"] = t1 - t0
        if name == "engine.decode":
            attrs["tokens"] = tl.n_generated
            if tl.finish_reason:
                attrs["finish_reason"] = tl.finish_reason
        span, cm = _start_span(tracer, name, attrs)
        try:
            if name == "engine.decode" and hasattr(span, "add_event"):
                for ev in tl.events:
                    detail = {k: v for k, v in ev.items() if k != "name"}
                    span.add_event(ev["name"], attributes=detail)
        finally:
            if cm is not None:
                cm.__exit__(None, None, None)
            else:
                _end(span)
