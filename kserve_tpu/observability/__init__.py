"""Request-lifecycle observability (docs/observability.md).

Three export surfaces over one clock-injectable `RequestTimeline` stamped
inside the engine:

- Prometheus histograms (metrics.py): TTFT, inter-token latency, queue
  wait, e2e, decode-step and prefill-chunk durations, XLA compile counts.
- OpenTelemetry spans (spans.py + tracing.py): W3C traceparent propagated
  EPP → replica → downstream hops, with engine queue/prefill/decode child
  spans and lifecycle span events.
- Introspection endpoints (introspection.py): GET /admin/telemetry
  (rolling percentiles + recent timelines) and POST /admin/profile
  (on-demand jax.profiler capture).
"""

from .introspection import (  # noqa: F401
    PROFILER_KEY,
    ProfilerBusyError,
    ProfilerSession,
    register_observability_routes,
)
from .spans import emit_timeline_spans  # noqa: F401
from .timeline import (  # noqa: F401
    RequestTimeline,
    TimelineRecorder,
    percentiles,
)
