"""Admin introspection endpoints: rolling telemetry + on-demand profiling.

- ``GET  /admin/telemetry``  — per-model rolling TTFT/ITL/step-time
  percentiles plus recent request timelines, straight from each engine's
  bounded TimelineRecorder (no Prometheus scrape required mid-incident).
- ``POST /admin/profile``    — capture a ``jax.profiler`` trace for N
  seconds into a configurable directory; 409 while a capture is already
  running (the profiler is a process-global singleton in JAX).

Both ride the always-open admin surface (resilience.is_inference_path is
False for /admin, so shedding/lifecycle gates never block an operator
mid-drain or mid-overload).
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Optional

from aiohttp import web

from ..logging import logger
from ..resilience import MONOTONIC, Clock

PROFILE_DIR_ENV = "KSERVE_TPU_PROFILE_DIR"
# typed app-config key (aiohttp 3.9 idiom): tests/operators reach the
# running ProfilerSession via app[PROFILER_KEY]
PROFILER_KEY: "web.AppKey[ProfilerSession]" = web.AppKey(
    "observability_profiler", object
)
DEFAULT_PROFILE_DIR = "/tmp/kserve-tpu-profiles"
MAX_PROFILE_SECONDS = 300.0


class ProfilerBusyError(RuntimeError):
    """A capture is already in flight (maps to HTTP 409)."""


class ProfilerSession:
    """One-at-a-time jax.profiler capture.  The clock is injectable so
    tests drive the capture window without real sleeps; start/stop always
    run in this process's event loop (jax.profiler is process-global)."""

    def __init__(self, clock: Optional[Clock] = None,
                 default_dir: Optional[str] = None):
        self._clock = clock or MONOTONIC
        self._default_dir = (
            default_dir
            or os.environ.get(PROFILE_DIR_ENV, DEFAULT_PROFILE_DIR)
        )
        self._task: Optional[asyncio.Task] = None
        self._current: Optional[dict] = None

    @property
    def active(self) -> bool:
        return self._task is not None and not self._task.done()

    def status(self) -> dict:
        return {"active": self.active, "capture": self._current}

    async def start(self, seconds: float, out_dir: Optional[str] = None) -> dict:
        if not (0 < seconds <= MAX_PROFILE_SECONDS):
            raise ValueError(
                f"profile seconds must be in (0, {MAX_PROFILE_SECONDS:g}]"
            )
        if self.active:
            raise ProfilerBusyError(
                f"profile capture already running: {self._current}"
            )
        target = os.path.join(
            out_dir or self._default_dir,
            time.strftime("trace-%Y%m%d-%H%M%S", time.gmtime()),
        )
        os.makedirs(target, exist_ok=True)
        import jax.profiler

        jax.profiler.start_trace(target)
        self._current = {"dir": target, "seconds": seconds}
        self._task = asyncio.get_running_loop().create_task(
            self._finish(seconds)
        )
        logger.info("profiler capture started: %s (%.3gs)", target, seconds)
        return dict(self._current)

    async def _finish(self, seconds: float) -> None:
        import jax.profiler

        try:
            await self._clock.sleep(seconds)
        finally:
            try:
                jax.profiler.stop_trace()
            except RuntimeError as exc:
                # double-stop / device-side teardown race: the capture is
                # over either way, only the artifact may be partial
                logger.warning("profiler stop_trace failed: %s", exc)
            logger.info("profiler capture finished")

    async def wait(self) -> None:
        """Test/shutdown helper: block until the running capture ends."""
        if self._task is not None:
            await self._task


def register_observability_routes(
    app: web.Application,
    model_registry,
    profiler: Optional[ProfilerSession] = None,
) -> None:
    profiler = profiler or ProfilerSession()
    app[PROFILER_KEY] = profiler

    async def telemetry_handler(request: web.Request) -> web.Response:
        models = {}
        for name, model in model_registry.get_models().items():
            engine = getattr(model, "engine", None)
            snap = getattr(engine, "telemetry_snapshot", None)
            if callable(snap):
                models[name] = snap()
        return web.json_response({
            "models": models,
            "profiler": profiler.status(),
        })

    async def profile_handler(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except ValueError:
            body = {}
        if not isinstance(body, dict):
            body = {}
        try:
            seconds = float(body.get("seconds", 2.0))
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "seconds must be a number"}, status=400
            )
        out_dir = body.get("dir")
        try:
            info = await profiler.start(seconds, out_dir=out_dir)
        except ProfilerBusyError as e:
            return web.json_response({"error": str(e)}, status=409)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        except (ImportError, RuntimeError, OSError) as e:
            # no profiler in this build / unwritable dir: the endpoint is
            # best-effort tooling, not a serving dependency
            return web.json_response({"error": str(e)}, status=501)
        return web.json_response(info, status=202)

    app.router.add_get("/admin/telemetry", telemetry_handler)
    app.router.add_post("/admin/profile", profile_handler)
