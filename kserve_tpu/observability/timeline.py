"""Request-lifecycle timelines: the engine's per-request telemetry spine.

Every generation is stamped with a `RequestTimeline` as it moves through
the engine (received → admitted → prefill start/end → first token →
per-token → finished/checkpointed).  All stamps come from an injectable
`resilience.Clock`, so the FakeClock chaos suite can assert exact TTFT /
inter-token / queue-wait values without a single real sleep.

The `TimelineRecorder` keeps a bounded ring of finished timelines plus
rolling sample windows (TTFT, ITL, queue wait, e2e, decode-step and
prefill-chunk durations) that back `GET /admin/telemetry` — engine step
introspection without a Prometheus scrape in the loop.

Derived metrics follow the serving-benchmark vocabulary of the vLLM/TGI
comparative study (PAPERS.md, arXiv:2511.17593): TTFT is first token
minus *received* (queue wait included — the client experiences it), ITL
is the gap between consecutive emitted tokens.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

# bounded per-timeline storage: events and ITL samples never grow past
# these caps even for max_model_len generations (overflow keeps aggregate
# count/sum so means stay exact)
MAX_EVENTS = 64
MAX_ITL_SAMPLES = 4096


class RequestTimeline:
    """Clock-stamped lifecycle of one generation.  Times are whatever the
    engine's injected clock reports (monotonic seconds in production,
    virtual seconds under FakeClock); only differences are meaningful."""

    __slots__ = (
        "request_id", "model_name", "trace", "received", "admitted",
        "prefill_start", "prefill_end", "first_token_at", "finished_at",
        "finish_reason", "n_prompt_tokens", "n_generated", "itls",
        "itl_overflow_n", "itl_overflow_sum", "events", "_last_token_at",
        "recorded",
    )

    def __init__(self, request_id: str, model_name: str = "",
                 trace: Any = None):
        self.request_id = request_id
        self.model_name = model_name
        # the tracing.TraceContext bound when the request entered (or None):
        # engine spans emitted from this timeline carry its trace_id so the
        # proxy → replica → engine spans form one linked trace
        self.trace = trace
        self.received: Optional[float] = None
        self.admitted: Optional[float] = None
        self.prefill_start: Optional[float] = None
        self.prefill_end: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.finish_reason: Optional[str] = None
        self.n_prompt_tokens = 0
        self.n_generated = 0
        self.itls: List[float] = []
        self.itl_overflow_n = 0
        self.itl_overflow_sum = 0.0
        self.events: List[dict] = []
        self._last_token_at: Optional[float] = None
        # set by the engine once this timeline has been fed to the
        # recorder/metrics — makes terminal recording idempotent across
        # overlapping teardown paths (finish vs cancel vs stop)
        self.recorded = False

    # ---- stamps (first-write-wins where re-admission can re-stamp) ----

    def mark_received(self, t: float) -> None:
        if self.received is None:
            self.received = t

    def mark_admitted(self, t: float) -> None:
        # queue wait is measured to the FIRST admission; a preemption
        # re-seat must not shrink it retroactively
        if self.admitted is None:
            self.admitted = t

    def mark_prefill_start(self, t: float) -> None:
        if self.prefill_start is None:
            self.prefill_start = t

    def mark_prefill_end(self, t: float) -> None:
        self.prefill_end = t

    def mark_token(self, t: float) -> None:
        """One emitted token: the first sets TTFT, later ones append ITL."""
        self.n_generated += 1
        if self.first_token_at is None:
            self.first_token_at = t
        elif self._last_token_at is not None:
            gap = t - self._last_token_at
            if len(self.itls) < MAX_ITL_SAMPLES:
                self.itls.append(gap)
            else:
                self.itl_overflow_n += 1
                self.itl_overflow_sum += gap
        self._last_token_at = t

    def mark_finished(self, t: float, reason: Optional[str]) -> None:
        if self.finished_at is None:
            self.finished_at = t
            self.finish_reason = reason

    def add_event(self, t: float, name: str, **detail) -> None:
        """Span-event seam: preemptions, checkpoints, resumes, errors."""
        if len(self.events) < MAX_EVENTS:
            self.events.append({"t": t, "name": name, **detail})

    # ---- derived latencies (None until both stamps exist) ----

    @staticmethod
    def _delta(a: Optional[float], b: Optional[float]) -> Optional[float]:
        if a is None or b is None:
            return None
        return b - a

    @property
    def queue_wait_s(self) -> Optional[float]:
        return self._delta(self.received, self.admitted)

    @property
    def ttft_s(self) -> Optional[float]:
        return self._delta(self.received, self.first_token_at)

    @property
    def prefill_s(self) -> Optional[float]:
        return self._delta(self.prefill_start, self.prefill_end)

    @property
    def decode_s(self) -> Optional[float]:
        return self._delta(self.first_token_at, self.finished_at)

    @property
    def e2e_s(self) -> Optional[float]:
        return self._delta(self.received, self.finished_at)

    @property
    def mean_itl_s(self) -> Optional[float]:
        n = len(self.itls) + self.itl_overflow_n
        if n == 0:
            return None
        return (sum(self.itls) + self.itl_overflow_sum) / n

    def to_dict(self, max_events: int = 16) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "request_id": self.request_id,
            "model_name": self.model_name,
            "received": self.received,
            "admitted": self.admitted,
            "prefill_start": self.prefill_start,
            "prefill_end": self.prefill_end,
            "first_token_at": self.first_token_at,
            "finished_at": self.finished_at,
            "finish_reason": self.finish_reason,
            "n_prompt_tokens": self.n_prompt_tokens,
            "n_generated": self.n_generated,
            "queue_wait_s": self.queue_wait_s,
            "ttft_s": self.ttft_s,
            "prefill_s": self.prefill_s,
            "e2e_s": self.e2e_s,
            "mean_itl_s": self.mean_itl_s,
            "events": self.events[:max_events],
        }
        if self.trace is not None:
            d["trace_id"] = getattr(self.trace, "trace_id", None)
        return d


def percentiles(samples) -> Dict[str, Any]:
    """{p50,p90,p99,mean,max,n} by nearest-rank over a bounded window —
    deterministic (no interpolation) so chaos tests can assert exactly."""
    xs = sorted(samples)
    n = len(xs)
    if n == 0:
        return {"n": 0}

    def rank(q: float) -> float:
        return xs[min(n - 1, int(q * n))]

    return {
        "n": n,
        "p50": rank(0.50),
        "p90": rank(0.90),
        "p99": rank(0.99),
        "mean": sum(xs) / n,
        "max": xs[-1],
    }


class TimelineRecorder:
    """Bounded in-memory telemetry behind `GET /admin/telemetry`: a ring of
    recent finished timelines plus rolling sample windows for the latency
    series.  Pure host-side bookkeeping — never touches the device."""

    def __init__(self, max_timelines: int = 128, max_samples: int = 2048):
        self.timelines: deque = deque(maxlen=max_timelines)
        self._ttft: deque = deque(maxlen=max_samples)
        self._itl: deque = deque(maxlen=max_samples)
        self._queue_wait: deque = deque(maxlen=max_samples)
        self._e2e: deque = deque(maxlen=max_samples)
        self._step: deque = deque(maxlen=max_samples)
        self._prefill_chunk: deque = deque(maxlen=max_samples)
        self.finished_count = 0
        self.preempted_count = 0
        self.aborted_count = 0
        self.step_count = 0

    def observe(self, tl: RequestTimeline) -> None:
        """Record a timeline that reached a terminal state.  Preempted /
        cancelled / errored timelines land in the ring (operators debugging
        a drain want them) but not in the latency windows — a half
        generation's e2e is noise."""
        self.timelines.append(tl)
        if tl.finish_reason not in ("stop", "length"):
            if tl.finish_reason == "preempted":
                self.preempted_count += 1
            else:
                self.aborted_count += 1
            return
        self.finished_count += 1
        if tl.ttft_s is not None:
            self._ttft.append(tl.ttft_s)
        if tl.queue_wait_s is not None:
            self._queue_wait.append(tl.queue_wait_s)
        if tl.e2e_s is not None:
            self._e2e.append(tl.e2e_s)
        self._itl.extend(tl.itls)

    def signal_windows(self) -> Dict[str, Any]:
        """The autoscaling-relevant latency percentiles (the compact
        subset of snapshot() the EPP /state payload carries per replica —
        kserve_tpu/autoscale/signals.py ingests this shape)."""
        ttft = percentiles(self._ttft)
        itl = percentiles(self._itl)
        return {
            "ttft_p50_s": ttft.get("p50"),
            "ttft_p99_s": ttft.get("p99"),
            "itl_p99_s": itl.get("p99"),
            "finished": self.finished_count,
        }

    def record_step(self, seconds: float) -> None:
        """One decode step: a multi-token dispatch+fetch chunk."""
        self.step_count += 1
        self._step.append(seconds)

    def record_prefill_chunk(self, seconds: float) -> None:
        self._prefill_chunk.append(seconds)

    def snapshot(self, max_recent: int = 32) -> Dict[str, Any]:
        # [-0:] would slice the WHOLE ring, the opposite of "none"
        recent = list(self.timelines)[-max_recent:] if max_recent > 0 else []
        return {
            "counts": {
                "finished": self.finished_count,
                "preempted": self.preempted_count,
                "aborted": self.aborted_count,
                "decode_steps": self.step_count,
            },
            "ttft_s": percentiles(self._ttft),
            "itl_s": percentiles(self._itl),
            "queue_wait_s": percentiles(self._queue_wait),
            "e2e_s": percentiles(self._e2e),
            "decode_step_s": percentiles(self._step),
            "prefill_chunk_s": percentiles(self._prefill_chunk),
            "recent": [tl.to_dict() for tl in reversed(recent)],
        }
