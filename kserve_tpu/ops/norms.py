"""Normalization ops (RMSNorm) — f32 accumulation, bf16 in/out."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def rms_norm_plus_one(x: jnp.ndarray, weight: jnp.ndarray,
                      eps: float = 1e-6) -> jnp.ndarray:
    """Gemma-style RMSNorm: multiplies by (1 + weight), with the product
    taken in f32 BEFORE the cast (HF PR #29402 semantics)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    return (normed * (1.0 + weight.astype(jnp.float32))).astype(dtype)


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    normed = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    return (normed * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
