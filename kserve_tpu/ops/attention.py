"""Attention ops: causal prefill, paged-KV decode, and the unified ragged
paged-attention contract for mixed prefill+decode batches.

Decode attention over the paged cache has two implementations:
- `paged_attention_xla`: pure-XLA gather + masked softmax (portable, used on
  CPU test meshes and as the safety net).
- `paged_attention_pallas` (ops/pallas_paged_attention.py): fused kernel that
  streams pages HBM->VMEM without materializing the gathered KV (the Ragged
  Paged Attention approach; see PAPERS.md).

The RAGGED contract (docs/kernels.md) generalizes both: every sequence in
the batch contributes an arbitrary-length query slice — a full prompt, a
prompt chunk, or a single decode token — packed into one [T, nq, d] token
buffer with per-sequence (q_start, q_len, kv_start) metadata.  The caller
writes the slice's K/V into the paged cache FIRST (kvcache.write_ragged_kv),
then attention reads everything from pages with a causal mask anchored at
each sequence's kv offset, so prompt chunks and decode steps fold into the
same online-softmax program:
- `ragged_paged_attention_xla`: the gather-based reference (CPU-runnable
  numerics ground truth; also the production path off-TPU).
- `ragged_paged_attention_pallas` (ops/pallas_paged_attention.py): the
  fused kernel, verified against the reference in interpret mode.

Role parity: replaces vLLM's CUDA PagedAttention, which the reference uses
through the vLLM engine (SURVEY.md §2.3 "Sequence/context parallel" row).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def _gqa_scores(q: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """q:[B,Tq,nq,d] k:[B,Tk,nkv,d] -> scores [B,nq,Tq,Tk] with GQA groups."""
    B, Tq, nq, d = q.shape
    nkv = k.shape[2]
    group = nq // nkv
    qg = q.reshape(B, Tq, nkv, group, d)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg.astype(jnp.float32), k.astype(jnp.float32))
    return scores.reshape(B, nkv * group, Tq, k.shape[1])


def _gqa_out(weights: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """weights:[B,nq,Tq,Tk] v:[B,Tk,nkv,d] -> [B,Tq,nq,d]."""
    B, nq, Tq, Tk = weights.shape
    nkv = v.shape[2]
    group = nq // nkv
    wg = weights.reshape(B, nkv, group, Tq, Tk)
    out = jnp.einsum("bkgts,bskd->btkgd", wg, v.astype(jnp.float32))
    return out.reshape(B, Tq, nq, v.shape[3])


def causal_prefill_attention(
    q: jnp.ndarray,  # [B, T, nq, d]
    k: jnp.ndarray,  # [B, T, nkv, d]
    v: jnp.ndarray,  # [B, T, nkv, d]
    valid_len: jnp.ndarray,  # [B] int32
    logit_softcap: float = 0.0,
    scale: Optional[float] = None,  # default 1/sqrt(d); Gemma overrides
    window=None,  # traced int32 scalar; >0 = sliding-window width
) -> jnp.ndarray:
    """Causal self-attention over the prompt (no cache read)."""
    B, T, nq, d = q.shape
    if scale is None:
        scale = 1.0 / float(d) ** 0.5
    scores = _gqa_scores(q, k) * scale  # [B,nq,T,T]
    if logit_softcap > 0.0:
        scores = jnp.tanh(scores / logit_softcap) * logit_softcap
    t = jnp.arange(T)
    causal = t[None, :] <= t[:, None]  # [Tq, Tk]
    valid = t[None, :] < valid_len[:, None]  # [B, Tk]
    mask = causal[None, None, :, :] & valid[:, None, None, :]
    if window is not None:
        dist = t[:, None] - t[None, :]  # q - k
        wmask = (dist < window) | (window <= 0)
        mask = mask & wmask[None, None, :, :]
    scores = jnp.where(mask, scores, -1e30)
    weights = jax.nn.softmax(scores, axis=-1)
    out = _gqa_out(weights, v)
    return out.astype(q.dtype)


def _gather_history(kv_pages, page_table):
    """Gather history pages from a plain or quantized cache ->
    (k [B,H,nkv,d], v [B,H,nkv,d]) dequantized."""
    if isinstance(kv_pages, tuple):
        pages, scales = kv_pages
        B, W = page_table.shape
        nkv, ps, d = pages.shape[2], pages.shape[3], pages.shape[4]
        g = pages[page_table]  # [B, W, 2, nkv, ps, d] int8
        s = scales[page_table]  # [B, W, 2, nkv, ps]
        from ..engine.kvcache import dequantize_rows

        # dequantize to bf16: the attention math upcasts to f32 internally,
        # and a f32 intermediate would double the bandwidth the int8 cache
        # exists to save
        deq = dequantize_rows(
            g.transpose(0, 1, 2, 4, 3, 5), s.transpose(0, 1, 2, 4, 3),
            jnp.bfloat16,
        )  # [B, W, 2, ps, nkv, d]
        k = deq[:, :, 0].reshape(B, W * ps, nkv, d)
        v = deq[:, :, 1].reshape(B, W * ps, nkv, d)
        return k, v
    B, W = page_table.shape
    nkv, ps, d = kv_pages.shape[2], kv_pages.shape[3], kv_pages.shape[4]
    gathered = kv_pages[page_table]  # [B, W, 2, nkv, ps, d]
    k = gathered[:, :, 0].transpose(0, 1, 3, 2, 4).reshape(B, W * ps, nkv, d)
    v = gathered[:, :, 1].transpose(0, 1, 3, 2, 4).reshape(B, W * ps, nkv, d)
    return k, v


def chunked_prefill_attention(
    q: jnp.ndarray,  # [B, C, nq, d] — current chunk queries
    k_chunk: jnp.ndarray,  # [B, C, nkv, d] — current chunk keys
    v_chunk: jnp.ndarray,  # [B, C, nkv, d]
    kv_pages,  # [num_pages, 2, nkv, ps, d] (or (int8, scales)) — cache w/ history
    page_table: jnp.ndarray,  # [B, W] pages holding positions 0..history-1
    history_len: jnp.ndarray,  # [B] tokens already in the cache
    valid_len: jnp.ndarray,  # [B] valid tokens within THIS chunk
    logit_softcap: float = 0.0,
    scale: Optional[float] = None,
    window=None,  # traced int32 scalar; >0 = sliding-window width
) -> jnp.ndarray:
    """Causal attention for a prefill CHUNK: queries attend to the cached
    history (gathered from pages) plus the causal prefix of the chunk
    itself.  This is what makes chunked prefill and prefix-cache reuse
    possible — the first chunk (history_len=0) degenerates to plain causal
    prefill attention."""
    B, C, nq, d = q.shape
    k_hist, v_hist = _gather_history(kv_pages, page_table)
    H = k_hist.shape[1]
    k_all = jnp.concatenate([k_hist, k_chunk.astype(k_hist.dtype)], axis=1)
    v_all = jnp.concatenate([v_hist, v_chunk.astype(v_hist.dtype)], axis=1)
    if scale is None:
        scale = 1.0 / float(d) ** 0.5
    scores = _gqa_scores(q, k_all) * scale  # [B, nq, C, H+C]
    if logit_softcap > 0.0:
        scores = jnp.tanh(scores / logit_softcap) * logit_softcap
    hist_pos = jnp.arange(H, dtype=jnp.int32)
    hist_mask = hist_pos[None, :] < history_len[:, None]  # [B, H]
    c = jnp.arange(C, dtype=jnp.int32)
    causal = c[None, :] <= c[:, None]  # [Cq, Ck]
    chunk_mask = causal[None, :, :] & (c[None, None, :] < valid_len[:, None, None])
    mask = jnp.concatenate(
        [
            jnp.broadcast_to(hist_mask[:, None, :], (B, C, H)),
            chunk_mask,
        ],
        axis=-1,
    )  # [B, C, H+C]
    if window is not None:
        # absolute positions: history keys 0..H-1; chunk token c sits at
        # chunk_start + c
        q_pos = history_len[:, None] + c[None, :]  # [B, C]
        k_pos = jnp.concatenate([
            jnp.broadcast_to(hist_pos[None, :], (B, H)),
            history_len[:, None] + c[None, :],
        ], axis=1)  # [B, H+C]
        dist = q_pos[:, :, None] - k_pos[:, None, :]
        mask = mask & ((dist < window) | (window <= 0))
    scores = jnp.where(mask[:, None, :, :], scores, -1e30)
    weights = jax.nn.softmax(scores, axis=-1)
    out = _gqa_out(weights, v_all)  # [B, C, nq, d]
    return out.astype(q.dtype)


def paged_attention_xla(
    q: jnp.ndarray,  # [B, nq, d] — one decode token per sequence
    kv_pages,  # [num_pages, 2, nkv, ps, d] or (int8 pages, scales)
    page_table: jnp.ndarray,  # [B, max_pages]
    seq_lens: jnp.ndarray,  # [B] int32 (length INCLUDING current token)
    logit_softcap: float = 0.0,
    scale: Optional[float] = None,
    window=None,  # traced int32 scalar; >0 = sliding-window width
) -> jnp.ndarray:
    """Decode attention: gather this batch's pages and do masked softmax.
    Materializes [B, L, nkv, d]; the Pallas kernel avoids that copy."""
    B, nq, d = q.shape
    k, v = _gather_history(kv_pages, page_table)
    L = k.shape[1]
    if scale is None:
        scale = 1.0 / float(d) ** 0.5
    scores = _gqa_scores(q[:, None], k) * scale  # [B,nq,1,L]
    if logit_softcap > 0.0:
        scores = jnp.tanh(scores / logit_softcap) * logit_softcap
    pos = jnp.arange(L, dtype=jnp.int32)
    mask = pos[None, :] < seq_lens[:, None]  # [B, L]
    if window is not None:
        # the query sits at pos seq_len-1: keep keys within the window
        dist = (seq_lens[:, None] - 1) - pos[None, :]
        mask = mask & ((dist < window) | (window <= 0))
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    weights = jax.nn.softmax(scores, axis=-1)
    out = _gqa_out(weights, v)  # [B,1,nq,d]
    return out[:, 0].astype(q.dtype)


# Auto-dispatch threshold, in page-table width (pages).  Measured e2e on one
# v5e chip (B=48, bench_1b, page_size=16, 2026-07-29):
#   width 16 (256-tok ctx):  gather 1671 tok/s  vs kernel 1146  -> gather
#   width 40 (640-tok ctx):  gather  847 tok/s  vs kernel  809  -> gather
#   width 72 (1152-tok ctx): gather  603 tok/s  vs kernel  636  -> kernel
# The gather path writes a [B, width*ps, nkv, d] copy of the live KV before
# attention; the kernel streams pages once.  The copy's extra traffic grows
# with width, the kernel's serial per-sequence grid cost does not — they
# cross between 40 and 72 pages.
PALLAS_MIN_PAGES = 64


def _should_use_pallas(d: int, quantized: bool, table_width: int, batch: int,
                       backend: str, page_size) -> bool:
    """The use_pallas=None auto-dispatch predicate (factored out so tests
    assert the production decision, not a re-inlined copy)."""
    from .pallas_paged_attention import _pick_sb

    supported_head = (
        d % 128 == 0
        # d=64 runs the packed two-tokens-per-row kernel, which needs an
        # even page_size; auto must fall back to the gather, not raise
        or (d == 64 and page_size is not None and page_size % 2 == 0)
    )
    return (
        supported_head
        and not quantized  # kernel reads bf16 pages only (today)
        and table_width >= PALLAS_MIN_PAGES
        # a batch with no divisor <= MAX_SB would run the serialized
        # sb=1 kernel shape, which loses to the gather
        and _pick_sb(batch) > 1
        # Mosaic only lowers on TPU; CPU smoke runs of a real model at
        # long context must take the gather, not fail to compile
        and backend == "tpu"
    )


def make_sharded_paged_attention(
    mesh,
    logit_softcap: float = 0.0,
    use_pallas: Optional[bool] = None,
    quantized: bool = False,
    interpret: bool = False,
    scale: Optional[float] = None,
    windowed: bool = False,
):
    """Decode attention under `shard_map` over the model (head) axis.

    The Pallas kernel has no GSPMD partitioning rule, so under tp>1 XLA
    would replicate the model-axis-sharded KV cache at the custom-call
    boundary.  shard_map sidesteps GSPMD entirely: each device runs the
    kernel (or the gather, per the same auto-dispatch) on its LOCAL heads —
    q heads and KV heads shard together on the model axis, so GQA group
    structure is preserved per shard and the op is embarrassingly parallel
    (no collectives).  This is what un-boxes the kernel for the multi-chip
    path (round-2 VERDICT weak #3).

    Returns fn(q [B,nq,d], kv_pages, page_table [B,W], seq_lens [B],
    window [] int32) -> [B,nq,d].  `windowed` is STATIC: when False the
    traced window arg is ignored (0 at every call site) and the Pallas
    auto-dispatch stays available; when True the scalar rides through to
    the gather path (per-layer sliding windows are data, and a traced
    window always forces the gather — threading it unconditionally would
    silently disable the kernel for every non-windowed tp>1 model).
    `quantized` selects the (int8 pages, scales) cache layout.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import MODEL_AXIS, shard_map

    if interpret and (windowed or scale is not None):
        # the interpret path exists to test the KERNEL's math on CPU, and
        # the kernel takes neither a window nor a scale override — dropping
        # them here would make a parity test compare the wrong math
        raise ValueError(
            "interpret mode tests the Pallas kernel, which supports "
            "neither `windowed` nor a scale override")

    q_spec = P(None, MODEL_AXIS, None)
    kv_spec = P(None, None, MODEL_AXIS, None, None)
    if quantized:
        kv_spec = (kv_spec, P(None, None, MODEL_AXIS, None))

    def inner(q, kv_pages, page_table, seq_lens, window):
        if interpret:
            from .pallas_paged_attention import paged_attention_pallas

            return paged_attention_pallas(
                q, kv_pages, page_table, seq_lens,
                logit_softcap=logit_softcap, interpret=True)
        return paged_attention(
            q, kv_pages, page_table, seq_lens,
            logit_softcap=logit_softcap, use_pallas=use_pallas,
            scale=scale, window=window if windowed else None)

    return shard_map(
        inner,
        mesh=mesh,
        in_specs=(q_spec, kv_spec, P(None, None), P(None), P()),
        out_specs=q_spec,
        check_vma=False,
    )


# ---------------- ragged paged attention (mixed prefill+decode) ----------------


def ragged_token_metadata(q_start, q_len, T: int):
    """Per-token (seq index, local offset, validity) for a packed ragged
    buffer of T tokens, derived ON DEVICE from the per-sequence metadata —
    packing metadata must never round-trip through the host inside traced
    code (jaxlint: ragged-metadata-host-sync).  Tokens outside every
    sequence's slice get seq index -1."""
    idx = jnp.arange(T, dtype=jnp.int32)
    member = (idx[None, :] >= q_start[:, None]) & (
        idx[None, :] < (q_start + q_len)[:, None]
    )  # [B, T]
    valid = member.any(axis=0)
    token_seq = jnp.where(
        valid, jnp.argmax(member, axis=0).astype(jnp.int32), -1)
    token_loc = idx - q_start[jnp.maximum(token_seq, 0)]
    return token_seq, token_loc, valid


def ragged_paged_attention_xla(
    q: jnp.ndarray,  # [T, nq, d] — packed ragged query buffer
    kv_pages,  # [num_pages, 2, nkv, ps, d] or (int8 pages, scales)
    page_table: jnp.ndarray,  # [B, W]
    q_start: jnp.ndarray,  # [B] first packed index of each sequence's slice
    q_len: jnp.ndarray,  # [B] slice length (0 = inactive lane)
    kv_start: jnp.ndarray,  # [B] tokens already cached BEFORE this slice
    logit_softcap: float = 0.0,
    scale: Optional[float] = None,
    window=None,  # traced int32 scalar; >0 = sliding-window width
) -> jnp.ndarray:
    """XLA gather reference for the ragged contract (docs/kernels.md).

    The caller has already written the slice's K/V into the pages
    (kvcache.write_ragged_kv), so attention reads ONLY the paged cache:
    query token j of sequence i sits at absolute position kv_start[i]+j and
    attends causally to positions 0..kv_start[i]+j.  Padded table entries
    point at the null page, whose positions lie beyond every query's causal
    horizon — the causal mask is the null-page mask.  This is the numerics
    ground truth the Pallas ragged kernel is tested against, and the
    production path off-TPU."""
    T, nq, d = q.shape
    if scale is None:
        scale = 1.0 / float(d) ** 0.5
    k_all, v_all = _gather_history(kv_pages, page_table)  # [B, L, nkv, d]
    L = k_all.shape[1]
    nkv = k_all.shape[2]
    group = nq // nkv
    token_seq, token_loc, valid = ragged_token_metadata(q_start, q_len, T)
    seq_ix = jnp.maximum(token_seq, 0)
    q_pos = kv_start[seq_ix] + token_loc  # [T] absolute query positions
    k_t = k_all[seq_ix]  # [T, L, nkv, d]
    v_t = v_all[seq_ix]
    qg = q.reshape(T, nkv, group, d).astype(jnp.float32)
    scores = jnp.einsum(
        "tkgd,tlkd->tkgl", qg, k_t.astype(jnp.float32)) * scale
    if logit_softcap > 0.0:
        scores = jnp.tanh(scores / logit_softcap) * logit_softcap
    kpos = jnp.arange(L, dtype=jnp.int32)
    mask = (kpos[None, :] <= q_pos[:, None]) & valid[:, None]  # [T, L]
    if window is not None:
        dist = q_pos[:, None] - kpos[None, :]
        mask = mask & ((dist < window) | (window <= 0))
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("tkgl,tlkd->tkgd", weights, v_t.astype(jnp.float32))
    out = jnp.where(valid[:, None, None], out.reshape(T, nq, d), 0.0)
    return out.astype(q.dtype)


def _should_use_ragged_pallas(d: int, backend: str) -> bool:
    """Auto-dispatch predicate for the ragged kernel: lane-aligned heads on
    a TPU backend.  Unlike the decode kernel there is no gather-vs-kernel
    width crossover — the ragged gather reference materializes [T, L, ...]
    per token and is strictly a correctness/CPU path."""
    return d % 128 == 0 and backend == "tpu"


def dense_stride_for(width: int, align: int) -> int:
    """Packed-slice stride for lanes carrying `width` query tokens each
    under a kernel block alignment of `align` (RAGGED_BQ on the kernel
    path, 1 on the XLA reference — docs/kernels.md dense packing).

    - align <= 1 (XLA reference): pack densely, stride == width.
    - width a multiple of align, or larger than it: round up to the next
      align multiple — every block still belongs to ONE lane, so the solo
      kernel's invariant holds unchanged.
    - width < align: the smallest power of two >= width (align is a power
      of two, so the result divides it) — lanes SHARE blocks at this
      stride and the dense-block kernel variant serves them.  This is
      what stops a single-token decode lane burning a whole align-token
      block (K+1-token speculative slices included)."""
    if width <= 0:
        raise ValueError(f"slice width must be positive, got {width}")
    if align <= 1 or width % align == 0:
        return width
    if width > align:
        return -(-width // align) * align
    sp = 1
    while sp < width:
        sp *= 2
    return sp


def ragged_paged_attention(
    q: jnp.ndarray,  # [T, nq, d]
    kv_pages,
    page_table: jnp.ndarray,  # [B, W]
    q_start: jnp.ndarray,  # [B]
    q_len: jnp.ndarray,  # [B]
    kv_start: jnp.ndarray,  # [B]
    logit_softcap: float = 0.0,
    use_pallas: Optional[bool] = None,
    scale: Optional[float] = None,
    window=None,  # traced int32 scalar (None = full attention)
    dense_stride: Optional[int] = None,  # static lane stride for dense
    # decode/spec-verify packing (< RAGGED_BQ shares blocks between lanes;
    # ignored by the XLA reference, which is per-token already)
) -> jnp.ndarray:
    """Dispatch the ragged contract between the fused Pallas kernel and the
    XLA gather reference.  The ragged kernel (unlike the decode kernel)
    supports int8 KV pages, sliding windows and scale overrides natively,
    so the dispatch is purely head-alignment + backend; use_pallas=True
    forces the kernel (raising on unsupported head_dim), False forces the
    reference."""
    d = q.shape[-1]
    if use_pallas is None:
        use_pallas = _should_use_ragged_pallas(d, jax.default_backend())
    if use_pallas:
        from .pallas_paged_attention import ragged_paged_attention_pallas

        return ragged_paged_attention_pallas(
            q, kv_pages, page_table, q_start, q_len, kv_start,
            window=window, logit_softcap=logit_softcap, scale=scale,
            dense_stride=dense_stride,
        )
    return ragged_paged_attention_xla(
        q, kv_pages, page_table, q_start, q_len, kv_start,
        logit_softcap=logit_softcap, scale=scale, window=window,
    )


def make_sharded_ragged_attention(
    mesh,
    logit_softcap: float = 0.0,
    use_pallas: Optional[bool] = None,
    quantized: bool = False,
    interpret: bool = False,
    scale: Optional[float] = None,
    dense_stride: Optional[int] = None,  # static: the spec-verify dense
    # packing stride (compiled.py builds a second sharded fn with it set)
):
    """Ragged paged attention under `shard_map` over the model (head) axis
    — same seam as make_sharded_paged_attention: q heads and KV heads shard
    together so GQA group structure is preserved per shard and the op needs
    no collectives.  Ragged packing metadata is replicated (tiny int32
    arrays).  The window scalar is always threaded: the ragged kernel masks
    the window natively, so no static `windowed` escape hatch is needed.

    Returns fn(q [T,nq,d], kv_pages, page_table [B,W], q_start [B],
    q_len [B], kv_start [B], window [] int32) -> [T,nq,d]."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import MODEL_AXIS, shard_map

    q_spec = P(None, MODEL_AXIS, None)
    kv_spec = P(None, None, MODEL_AXIS, None, None)
    if quantized:
        kv_spec = (kv_spec, P(None, None, MODEL_AXIS, None))

    def inner(q, kv_pages, page_table, q_start, q_len, kv_start, window):
        if interpret:
            from .pallas_paged_attention import ragged_paged_attention_pallas

            return ragged_paged_attention_pallas(
                q, kv_pages, page_table, q_start, q_len, kv_start,
                window=window, logit_softcap=logit_softcap, scale=scale,
                interpret=True, dense_stride=dense_stride)
        return ragged_paged_attention(
            q, kv_pages, page_table, q_start, q_len, kv_start,
            logit_softcap=logit_softcap, use_pallas=use_pallas,
            scale=scale, window=window, dense_stride=dense_stride)

    return shard_map(
        inner,
        mesh=mesh,
        in_specs=(q_spec, kv_spec, P(None, None), P(None), P(None),
                  P(None), P()),
        out_specs=q_spec,
        check_vma=False,
    )


def paged_attention(
    q: jnp.ndarray,
    kv_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    seq_lens: jnp.ndarray,
    logit_softcap: float = 0.0,
    use_pallas: Optional[bool] = None,
    scale: Optional[float] = None,
    window=None,  # sliding window (forces the gather path)
) -> jnp.ndarray:
    """Dispatch between the fused Pallas kernel and the XLA gather path.

    use_pallas=None (default) auto-selects: the kernel for long-context
    batches (page-table width >= PALLAS_MIN_PAGES and a supported head_dim),
    the gather otherwise — each path where it measures faster (table above).
    True forces the kernel (raising on unsupported head_dim rather than
    silently benchmarking the gather); False forces the gather."""
    d = q.shape[-1]
    quantized = isinstance(kv_pages, tuple)
    if window is not None:
        # the kernel has no sliding-window mask yet; windowed layers take
        # the gather (scale/softcap still apply).  An explicit opt-in
        # stays loud — silently measuring the gather would corrupt a
        # benchmark that forced the kernel
        if use_pallas:
            raise ValueError(
                "pallas paged attention has no sliding-window mask; "
                "windowed layers cannot run with use_pallas=True")
        use_pallas = False
    if scale is not None and use_pallas is None:
        # same for a non-default scale (query_pre_attn_scalar without a
        # sliding window): auto-dispatch falls back rather than raising
        use_pallas = False
    if use_pallas is None:
        page_size = None if quantized else int(kv_pages.shape[3])
        use_pallas = _should_use_pallas(
            d, quantized, int(page_table.shape[1]), int(q.shape[0]),
            jax.default_backend(), page_size,
        )
    if use_pallas:
        if quantized:
            raise ValueError(
                "pallas paged attention does not support the int8 KV cache"
            )
        # loud, not silent: an explicit opt-in with an unsupported head_dim
        # must not quietly benchmark the XLA path
        from .pallas_paged_attention import paged_attention_pallas

        if scale is not None:
            raise ValueError(
                "pallas paged attention does not take a scale override")
        return paged_attention_pallas(
            q, kv_pages, page_table, seq_lens, logit_softcap=logit_softcap
        )
    return paged_attention_xla(
        q, kv_pages, page_table, seq_lens, logit_softcap,
        scale=scale, window=window,
    )
