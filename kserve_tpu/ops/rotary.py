"""Rotary position embeddings (half-rotation layout, Llama/NeoX style).

Computed on the fly from positions (no host-side cache tables) so the same
function serves prefill ([B,T]) and decode ([B,1]) under one jit.

Supports the HF `rope_scaling` variants needed for real checkpoints:
- "llama3" (Llama-3.1/3.2): low/high-frequency wavelength scaling applied
  at ALL positions (config.json rope_type "llama3")
- "linear": uniform inv_freq / factor
Unsupported types raise instead of being silently dropped.
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int,
    theta: float = 10000.0,
    rope_scaling: Optional[dict] = None,
) -> jnp.ndarray:
    """[head_dim/2] inverse frequencies, with optional HF rope_scaling."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta**exponent)
    if not rope_scaling:
        return inv_freq
    rope_type = rope_scaling.get("rope_type") or rope_scaling.get("type") or "default"
    if rope_type == "default":
        return inv_freq
    if rope_type == "linear":
        return inv_freq / float(rope_scaling["factor"])
    if rope_type == "llama3":
        # Per-frequency interpolation: wavelengths shorter than
        # orig_ctx/high_freq_factor are kept, longer than
        # orig_ctx/low_freq_factor are divided by `factor`, and the band in
        # between is linearly blended.  The clip form below is exactly
        # equivalent to the three-way where() in HF modeling_rope_utils.
        factor = float(rope_scaling["factor"])
        low = float(rope_scaling["low_freq_factor"])
        high = float(rope_scaling["high_freq_factor"])
        orig_ctx = float(rope_scaling["original_max_position_embeddings"])
        wavelen = 2.0 * math.pi / inv_freq
        smooth = jnp.clip((orig_ctx / wavelen - low) / (high - low), 0.0, 1.0)
        return (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    raise ValueError(
        f"unsupported rope_scaling type {rope_type!r}; supported: "
        "default, linear, llama3"
    )


def apply_rope(
    x: jnp.ndarray,  # [B, T, H, D]
    positions: jnp.ndarray,  # [B, T] int32
    theta: float = 10000.0,
    rope_scaling: Optional[dict] = None,
) -> jnp.ndarray:
    """Rotate q/k by position-dependent phases.  Half-rotation layout:
    pairs are (x[..., :D/2], x[..., D/2:]) as in Llama."""
    head_dim = x.shape[-1]
    inv_freq = rope_frequencies(head_dim, theta, rope_scaling)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [B,T,D/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B,T,1,D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
