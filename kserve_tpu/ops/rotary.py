"""Rotary position embeddings (half-rotation layout, Llama/NeoX style).

Computed on the fly from positions (no host-side cache tables) so the same
function serves prefill ([B,T]) and decode ([B,1]) under one jit.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float = 10000.0) -> jnp.ndarray:
    """[head_dim/2] inverse frequencies."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponent)


def apply_rope(
    x: jnp.ndarray,  # [B, T, H, D]
    positions: jnp.ndarray,  # [B, T] int32
    theta: float = 10000.0,
    scaling: float = 1.0,
) -> jnp.ndarray:
    """Rotate q/k by position-dependent phases.  Half-rotation layout:
    pairs are (x[..., :D/2], x[..., D/2:]) as in Llama."""
    head_dim = x.shape[-1]
    inv_freq = rope_frequencies(head_dim, theta)
    if scaling != 1.0:
        inv_freq = inv_freq / scaling
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [B,T,D/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B,T,1,D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
