"""Fused paged-attention decode kernel (Pallas/TPU).

Kernel shape (v3, sequence-block parallel): each grid step owns SB
sequences.  At inner iteration i it streams page i of ALL SB sequences from
HBM into an NBUF-deep VMEM ring (SB concurrent DMAs per iteration — the
page-major cache layout [num_pages, 2, nkv, ps, d] in kvcache.py makes each
page one contiguous 64KB-class descriptor covering K and V for every local
head) and folds them into a batched online-softmax accumulator
[SB, nkv, group, ·].  The compute is the same batched shape XLA uses for
the gather path — but the gathered KV only ever exists in VMEM, so HBM
traffic is ONE read of the table width instead of gather's read + write +
re-read.

Why not one-sequence-per-grid-step (v1/v2): the grid is sequential on a
TPU core, so per-sequence page loops serialize B small DMA bursts and the
per-page compute ([group, ps] matmuls) is far below MXU granularity —
measured 1146 vs 1671 tok/s e2e against the gather at 256-token context.
Batching SB sequences multiplies both the DMA parallelism and the matmul
batch.

This is the Ragged Paged Attention design point (see PAPERS.md) specialized
to decode (query length 1 per sequence).

Numerics match ops/attention.paged_attention_xla (tests compare both paths
in interpret mode; bench exercises the compiled kernel on hardware).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NBUF = 4  # VMEM ring depth (iterations in flight); NBUF-1 ahead
MAX_SB = 8  # sequences per grid step (VMEM budget: NBUF*SB pages resident)


def _pick_sb(B: int) -> int:
    """Largest divisor of B up to MAX_SB (any divisor, not just powers of
    two — an odd batch must not silently degrade to the serialized sb=1
    shape)."""
    for sb in range(min(MAX_SB, B), 0, -1):
        if B % sb == 0:
            return sb
    return 1


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [B, W] int32 (SMEM)
    seq_lens_ref,  # [B] int32 (SMEM)
    # inputs
    q_ref,  # [SB, nq, d] VMEM block for this sequence block
    kv_hbm_ref,  # [num_pages, 2, nkv, ps, d] in HBM
    # output
    out_ref,  # [SB, nq, d] VMEM
    # scratch
    kv_bufs,  # [NBUF, SB, 2, nkv, ps, d] VMEM ring
    sems,  # DMA semaphores [NBUF, SB]
    *,
    sb: int,
    page_size: int,
    num_kv_heads: int,
    head_dim: int,
    scale: float,
    logit_softcap: float,
):
    g = pl.program_id(0)
    nq = q_ref.shape[1]
    group = nq // num_kv_heads

    # pages needed by the longest sequence in this block bounds the loop
    max_len = seq_lens_ref[g * sb]
    for s in range(1, sb):
        max_len = jnp.maximum(max_len, seq_lens_ref[g * sb + s])
    num_pages = (max_len + page_size - 1) // page_size

    def start_iter(i, slot):
        # SB concurrent page DMAs; shorter sequences' padded table entries
        # point at the null page (page 0) — a valid, masked-out fetch
        for s in range(sb):
            page = page_table_ref[g * sb + s, i]
            pltpu.make_async_copy(
                kv_hbm_ref.at[page], kv_bufs.at[slot, s], sems.at[slot, s]
            ).start()

    for j in range(NBUF - 1):
        @pl.when(j < num_pages)
        def _(j=j):
            start_iter(j, j)

    # q per kv-head group: [SB, nkv, group, d] f32
    q = q_ref[...].astype(jnp.float32).reshape(sb, num_kv_heads, group, head_dim)
    # per-row valid lengths [SB, 1, 1, 1]
    lens = jnp.stack(
        [seq_lens_ref[g * sb + s] for s in range(sb)]
    ).reshape(sb, 1, 1, 1)

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, NBUF)
        for s in range(sb):
            pltpu.make_async_copy(
                kv_hbm_ref.at[0], kv_bufs.at[slot, s], sems.at[slot, s]
            ).wait()

        # refill the slot consumed LAST iteration ((i-1) mod NBUF — already
        # read, safe to overwrite) with iteration i+NBUF-1's pages
        @pl.when(i + NBUF - 1 < num_pages)
        def _():
            start_iter(i + NBUF - 1, jax.lax.rem(i + NBUF - 1, NBUF))

        k = kv_bufs[slot, :, 0].astype(jnp.float32)  # [SB, nkv, ps, d]
        v = kv_bufs[slot, :, 1].astype(jnp.float32)
        s_ = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((3,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32,
        ) * scale  # [SB, nkv, group, ps]
        if logit_softcap > 0.0:
            s_ = jnp.tanh(s_ / logit_softcap) * logit_softcap
        token_pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, 1, page_size), 3
        )
        s_ = jnp.where(token_pos < lens, s_, -1e30)
        m_new = jnp.maximum(m, s_.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s_ - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32,
        )  # [SB, nkv, group, d]
        acc_new = acc * alpha + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((sb, num_kv_heads, group, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((sb, num_kv_heads, group, 1), jnp.float32)
    acc0 = jnp.zeros((sb, num_kv_heads, group, head_dim), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_pages, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    out_ref[...] = out.reshape(sb, nq, head_dim).astype(out_ref.dtype)


def paged_attention_pallas(
    q: jnp.ndarray,  # [B, nq, d]
    kv_pages: jnp.ndarray,  # [num_pages, 2, nkv, ps, d]
    page_table: jnp.ndarray,  # [B, max_pages] int32
    seq_lens: jnp.ndarray,  # [B] int32
    logit_softcap: float = 0.0,
    interpret: bool = False,
) -> jnp.ndarray:
    B, nq, d = q.shape
    num_pages_total, _, nkv, ps, _ = kv_pages.shape
    if d % 128 != 0 and not interpret:
        # Lane tiling pads head_dim to 128 and Mosaic rejects both DMA
        # slices of the padded trailing dim and the shape-cast that would
        # unpack a token-packed row.  Callers fall back to the XLA path.
        raise ValueError(
            f"pallas paged attention requires head_dim % 128 == 0, got {d}"
        )
    sb = _pick_sb(B)
    scale = float(1.0 / (d ** 0.5))
    kernel = functools.partial(
        _decode_kernel,
        sb=sb,
        page_size=ps,
        num_kv_heads=nkv,
        head_dim=d,
        scale=scale,
        logit_softcap=logit_softcap,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B // sb,),
        in_specs=[
            pl.BlockSpec((sb, nq, d), lambda g, *_: (g, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_specs=pl.BlockSpec((sb, nq, d), lambda g, *_: (g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM(tuple((NBUF, sb) + kv_pages.shape[1:]), kv_pages.dtype),
            pltpu.SemaphoreType.DMA((NBUF, sb)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, nq, d), q.dtype),
        interpret=interpret,
    )(page_table, seq_lens, q, kv_pages)
