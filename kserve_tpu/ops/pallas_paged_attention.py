"""Fused paged-attention decode kernel (Pallas/TPU).

Kernel shape (v3, sequence-block parallel): each grid step owns SB
sequences.  At inner iteration i it streams page i of ALL SB sequences from
HBM into an NBUF-deep VMEM ring (SB concurrent DMAs per iteration — the
page-major cache layout [num_pages, 2, nkv, ps, d] in kvcache.py makes each
page one contiguous 64KB-class descriptor covering K and V for every local
head) and folds them into a batched online-softmax accumulator
[SB, nkv, group, ·].  The compute is the same batched shape XLA uses for
the gather path — but the gathered KV only ever exists in VMEM, so HBM
traffic is ONE read of the table width instead of gather's read + write +
re-read.

Why not one-sequence-per-grid-step (v1/v2): the grid is sequential on a
TPU core, so per-sequence page loops serialize B small DMA bursts and the
per-page compute ([group, ps] matmuls) is far below MXU granularity —
measured 1146 vs 1671 tok/s e2e against the gather at 256-token context.
Batching SB sequences multiplies both the DMA parallelism and the matmul
batch.

This is the Ragged Paged Attention design point (see PAPERS.md) specialized
to decode (query length 1 per sequence).  The FULL ragged generalization —
arbitrary per-sequence query slices (prompt chunks and decode tokens in one
program) — is `ragged_paged_attention_pallas` below; its packing contract,
masking rules, VMEM ring budget, int8/sliding-window composition and the
engine's legacy-fallback flag are documented in docs/kernels.md.

Numerics match ops/attention.paged_attention_xla and
ops/attention.ragged_paged_attention_xla respectively (tests compare the
paths in interpret mode; bench exercises the compiled kernels on hardware).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NBUF = 4  # VMEM ring depth (iterations in flight); NBUF-1 ahead
MAX_SB = 8  # sequences per grid step (VMEM budget: NBUF*SB pages resident)

# jax>=0.5 renamed pltpu.TPUMemorySpace -> MemorySpace (and the HBM member
# replaced ANY as the name for "stay in device memory, no VMEM block").
# The 0.4.x fallback keeps interpret-mode tests runnable on CI images that
# pin the older jax.
if hasattr(pltpu, "MemorySpace"):
    _HBM = pltpu.MemorySpace.HBM
else:  # jax 0.4.x
    _HBM = pltpu.TPUMemorySpace.ANY


def _pick_sb(B: int) -> int:
    """Largest divisor of B up to MAX_SB (any divisor, not just powers of
    two — an odd batch must not silently degrade to the serialized sb=1
    shape)."""
    for sb in range(min(MAX_SB, B), 0, -1):
        if B % sb == 0:
            return sb
    return 1


# ---- DMA-ring scaffolding shared by both kernel variants ----


def _block_pages(seq_lens_ref, g, sb, page_size):
    """Pages needed by the longest sequence in block g (bounds the loop)."""
    max_len = seq_lens_ref[g * sb]
    for s in range(1, sb):
        max_len = jnp.maximum(max_len, seq_lens_ref[g * sb + s])
    return (max_len + page_size - 1) // page_size


def _make_start_iter(page_table_ref, kv_hbm_ref, kv_bufs, sems, g, sb):
    """start_iter(i, slot): kick off this block's SB concurrent page DMAs
    for iteration i.  Shorter sequences' padded table entries point at the
    null page (page 0) — a valid, masked-out fetch."""

    def start_iter(i, slot):
        for s in range(sb):
            page = page_table_ref[g * sb + s, i]
            pltpu.make_async_copy(
                kv_hbm_ref.at[page], kv_bufs.at[slot, s], sems.at[slot, s]
            ).start()

    return start_iter


def _ring_prologue(start_iter, num_pages):
    """Prime the first NBUF-1 ring slots."""
    for j in range(NBUF - 1):
        @pl.when(j < num_pages)
        def _(j=j):
            start_iter(j, j)


def _ring_wait_and_refill(start_iter, kv_hbm_ref, kv_bufs, sems, sb, i,
                          num_pages):
    """Wait for iteration i's slot, then refill the slot consumed LAST
    iteration ((i-1) mod NBUF — already read, safe to overwrite) with
    iteration i+NBUF-1's pages.  Returns the slot index."""
    slot = jax.lax.rem(i, NBUF)
    for s in range(sb):
        pltpu.make_async_copy(
            kv_hbm_ref.at[0], kv_bufs.at[slot, s], sems.at[slot, s]
        ).wait()

    @pl.when(i + NBUF - 1 < num_pages)
    def _():
        start_iter(i + NBUF - 1, jax.lax.rem(i + NBUF - 1, NBUF))

    return slot


def _block_lens(seq_lens_ref, g, sb):
    """Per-row valid lengths [SB, 1, 1, 1] for masking."""
    return jnp.stack(
        [seq_lens_ref[g * sb + s] for s in range(sb)]
    ).reshape(sb, 1, 1, 1)


def _pallas_call(kernel, B, sb, nq, lane, kv_arr):
    """Shared PrefetchScalarGridSpec + pallas_call builder: q/out blocks
    are [SB, nq, lane], the cache stays in HBM, scratch is the NBUF-deep
    VMEM ring + DMA semaphores."""
    return functools.partial(
        pl.pallas_call,
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B // sb,),
            in_specs=[
                pl.BlockSpec((sb, nq, lane), lambda g, *_: (g, 0, 0)),
                pl.BlockSpec(memory_space=_HBM),
            ],
            out_specs=pl.BlockSpec((sb, nq, lane), lambda g, *_: (g, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((NBUF, sb) + kv_arr.shape[1:], kv_arr.dtype),
                pltpu.SemaphoreType.DMA((NBUF, sb)),
            ],
        ),
    )


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [B, W] int32 (SMEM)
    seq_lens_ref,  # [B] int32 (SMEM)
    # inputs
    q_ref,  # [SB, nq, d] VMEM block for this sequence block
    kv_hbm_ref,  # [num_pages, 2, nkv, ps, d] in HBM
    # output
    out_ref,  # [SB, nq, d] VMEM
    # scratch
    kv_bufs,  # [NBUF, SB, 2, nkv, ps, d] VMEM ring
    sems,  # DMA semaphores [NBUF, SB]
    *,
    sb: int,
    page_size: int,
    num_kv_heads: int,
    head_dim: int,
    scale: float,
    logit_softcap: float,
):
    g = pl.program_id(0)
    nq = q_ref.shape[1]
    group = nq // num_kv_heads

    num_pages = _block_pages(seq_lens_ref, g, sb, page_size)
    start_iter = _make_start_iter(
        page_table_ref, kv_hbm_ref, kv_bufs, sems, g, sb)
    _ring_prologue(start_iter, num_pages)

    # q per kv-head group: [SB, nkv, group, d] f32
    q = q_ref[...].astype(jnp.float32).reshape(sb, num_kv_heads, group, head_dim)
    lens = _block_lens(seq_lens_ref, g, sb)

    def body(i, carry):
        m, l, acc = carry
        slot = _ring_wait_and_refill(
            start_iter, kv_hbm_ref, kv_bufs, sems, sb, i, num_pages)

        k = kv_bufs[slot, :, 0].astype(jnp.float32)  # [SB, nkv, ps, d]
        v = kv_bufs[slot, :, 1].astype(jnp.float32)
        s_ = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((3,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32,
        ) * scale  # [SB, nkv, group, ps]
        if logit_softcap > 0.0:
            s_ = jnp.tanh(s_ / logit_softcap) * logit_softcap
        token_pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, 1, page_size), 3
        )
        s_ = jnp.where(token_pos < lens, s_, -1e30)
        m_new = jnp.maximum(m, s_.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s_ - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32,
        )  # [SB, nkv, group, d]
        acc_new = acc * alpha + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((sb, num_kv_heads, group, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((sb, num_kv_heads, group, 1), jnp.float32)
    acc0 = jnp.zeros((sb, num_kv_heads, group, head_dim), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_pages, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    out_ref[...] = out.reshape(sb, nq, head_dim).astype(out_ref.dtype)


def _packed_decode_kernel(
    # scalar prefetch
    page_table_ref,  # [B, W] int32 (SMEM)
    seq_lens_ref,  # [B] int32 (SMEM)
    # inputs
    q_ref,  # [SB, nq, 128] VMEM — q duplicated into both lane halves
    kv_hbm_ref,  # [num_pages, 2, nkv, ps/2, 128] in HBM (packed view)
    # output
    out_ref,  # [SB, nq, 128] VMEM — even-token pv in lanes 0-63, odd in 64-127
    # scratch
    kv_bufs,  # [NBUF, SB, 2, nkv, ps/2, 128] VMEM ring
    sems,  # DMA semaphores [NBUF, SB]
    *,
    sb: int,
    page_size: int,  # TOKENS per page (rows per page = page_size // 2)
    num_kv_heads: int,
    scale: float,
    logit_softcap: float,
):
    """head_dim=64 variant: two tokens share one 128-lane row.

    The natural [ps, 64] layout would pad the lane dim to 128 (half of
    VMEM wasted) and Mosaic rejects both trailing-dim DMA slices and the
    in-kernel shape-cast that would unpack a packed row.  Instead the
    CALLER bit-casts the cache to [.., ps/2, 128] (contiguous memory, free
    view) and everything inside stays 128-lane aligned:
    - q arrives duplicated: q2 = [q | q], so one dot against a half-masked
      K row contracts exactly one token's 64 dims
    - scores for even/odd tokens are two dots against lane-masked K; each
      feeds the shared online-softmax accumulator
    - pv accumulates PACKED: lanes 0-63 carry the even tokens' 64-dim
      contribution, lanes 64-127 the odd tokens'; the caller folds the two
      halves with one XLA add — no lane slicing anywhere in the kernel.
    """
    g = pl.program_id(0)
    nq = q_ref.shape[1]
    group = nq // num_kv_heads
    rows = page_size // 2  # packed rows per page

    num_pages = _block_pages(seq_lens_ref, g, sb, page_size)
    start_iter = _make_start_iter(
        page_table_ref, kv_hbm_ref, kv_bufs, sems, g, sb)
    _ring_prologue(start_iter, num_pages)

    q2 = q_ref[...].astype(jnp.float32).reshape(
        sb, num_kv_heads, group, 128
    )
    lens = _block_lens(seq_lens_ref, g, sb)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, 128), 3)
    mask_lo = (lane < 64).astype(jnp.float32)
    mask_hi = (lane >= 64).astype(jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        slot = _ring_wait_and_refill(
            start_iter, kv_hbm_ref, kv_bufs, sems, sb, i, num_pages)

        k = kv_bufs[slot, :, 0].astype(jnp.float32)  # [SB, nkv, ps/2, 128]
        v = kv_bufs[slot, :, 1].astype(jnp.float32)
        row = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, rows), 3)

        def scores(kmask, parity):
            s_ = jax.lax.dot_general(
                q2, k * kmask,
                dimension_numbers=(((3,), (3,)), ((0, 1), (0, 1))),
                preferred_element_type=jnp.float32,
            ) * scale  # [SB, nkv, group, ps/2]
            if logit_softcap > 0.0:
                s_ = jnp.tanh(s_ / logit_softcap) * logit_softcap
            pos = i * page_size + 2 * row + parity
            return jnp.where(pos < lens, s_, -1e30)

        s_even = scores(mask_lo, 0)
        s_odd = scores(mask_hi, 1)
        m_new = jnp.maximum(
            m,
            jnp.maximum(
                s_even.max(axis=-1, keepdims=True),
                s_odd.max(axis=-1, keepdims=True),
            ),
        )
        alpha = jnp.exp(m - m_new)
        p_even = jnp.exp(s_even - m_new)
        p_odd = jnp.exp(s_odd - m_new)
        l_new = (
            l * alpha
            + p_even.sum(axis=-1, keepdims=True)
            + p_odd.sum(axis=-1, keepdims=True)
        )
        dims = (((3,), (2,)), ((0, 1), (0, 1)))
        pv = jax.lax.dot_general(
            p_even, v * mask_lo, dimension_numbers=dims,
            preferred_element_type=jnp.float32,
        ) + jax.lax.dot_general(
            p_odd, v * mask_hi, dimension_numbers=dims,
            preferred_element_type=jnp.float32,
        )  # [SB, nkv, group, 128] — halves carry their parity's pv
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((sb, num_kv_heads, group, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((sb, num_kv_heads, group, 1), jnp.float32)
    acc0 = jnp.zeros((sb, num_kv_heads, group, 128), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_pages, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    out_ref[...] = out.reshape(sb, nq, 128).astype(out_ref.dtype)


def _paged_attention_pallas_packed(
    q, kv_pages, page_table, seq_lens, logit_softcap, interpret
):
    """head_dim=64 entry: pack the cache view, duplicate q, fold halves."""
    B, nq, d = q.shape
    num_pages_total, _, nkv, ps, _ = kv_pages.shape
    if ps % 2 != 0:
        raise ValueError(f"packed kernel requires even page_size, got {ps}")
    sb = _pick_sb(B)
    scale = float(1.0 / (d ** 0.5))
    # contiguous-memory view: [.., ps, 64] -> [.., ps/2, 128] (two tokens
    # per lane row); XLA lowers this to a bitcast, not a copy
    kv_packed = kv_pages.reshape(num_pages_total, 2, nkv, ps // 2, 128)
    q2 = jnp.concatenate([q, q], axis=-1)  # [B, nq, 128]
    kernel = functools.partial(
        _packed_decode_kernel,
        sb=sb,
        page_size=ps,
        num_kv_heads=nkv,
        scale=scale,
        logit_softcap=logit_softcap,
    )
    packed_out = _pallas_call(kernel, B, sb, nq, 128, kv_packed)(
        out_shape=jax.ShapeDtypeStruct((B, nq, 128), jnp.float32),
        interpret=interpret,
    )(page_table, seq_lens, q2, kv_packed)
    # fold the parity halves (plain XLA; f32 before the final cast)
    out = packed_out.reshape(B, nq, 2, 64).sum(axis=2)
    return out.astype(q.dtype)


def paged_attention_pallas(
    q: jnp.ndarray,  # [B, nq, d]
    kv_pages: jnp.ndarray,  # [num_pages, 2, nkv, ps, d]
    page_table: jnp.ndarray,  # [B, max_pages] int32
    seq_lens: jnp.ndarray,  # [B] int32
    logit_softcap: float = 0.0,
    interpret: bool = False,
) -> jnp.ndarray:
    B, nq, d = q.shape
    num_pages_total, _, nkv, ps, _ = kv_pages.shape
    if d == 64:
        # real Llama-3.2-1B / Qwen-class checkpoints (VERDICT r4 #4): two
        # tokens packed per 128-lane row, see _packed_decode_kernel
        return _paged_attention_pallas_packed(
            q, kv_pages, page_table, seq_lens, logit_softcap, interpret
        )
    if d % 128 != 0 and not interpret:
        # Lane tiling pads head_dim to 128 and Mosaic rejects both DMA
        # slices of the padded trailing dim and the shape-cast that would
        # unpack a token-packed row (d=64 has the dedicated packed kernel
        # above; other sub-128 head dims fall back to the XLA path).
        raise ValueError(
            f"pallas paged attention requires head_dim % 128 == 0 or 64, got {d}"
        )
    sb = _pick_sb(B)
    scale = float(1.0 / (d ** 0.5))
    kernel = functools.partial(
        _decode_kernel,
        sb=sb,
        page_size=ps,
        num_kv_heads=nkv,
        head_dim=d,
        scale=scale,
        logit_softcap=logit_softcap,
    )
    return _pallas_call(kernel, B, sb, nq, d, kv_pages)(
        out_shape=jax.ShapeDtypeStruct((B, nq, d), q.dtype),
        interpret=interpret,
    )(page_table, seq_lens, q, kv_pages)


# ---------------- ragged paged attention (mixed prefill+decode) ----------------
#
# The generalization of the decode kernel above to arbitrary per-sequence
# query lengths (docs/kernels.md): sequences pack their query slices — a
# full prompt, a prompt chunk, or a single decode token — into one [T, nq,
# d] buffer at RAGGED_BQ-aligned offsets.  The grid walks BQ-token blocks;
# each block belongs to exactly ONE sequence (the alignment invariant) and
# streams that sequence's KV pages through the same VMEM DMA ring as the
# decode kernel, folding them into an online-softmax accumulator under a
# causal mask anchored at the sequence's kv offset.  Decode (q_len=1) and
# prefill chunks (q_len=C) are the same program; sliding windows, int8 KV
# pages and scale overrides are masked/dequantized/applied in-kernel.

RAGGED_BQ = 8  # query tokens per grid block (f32 sublane granularity)


def _ragged_block_metadata(q_start, q_len, G: int, bq: int):
    """[G] (sequence index, local query offset) per BQ block, derived on
    device from the per-sequence metadata (no host reads on packing
    metadata — the jaxlint ragged-metadata-host-sync contract).  Blocks
    outside every slice get sequence -1 (the kernel skips them)."""
    blk0 = jnp.arange(G, dtype=jnp.int32) * bq
    member = (blk0[None, :] >= q_start[:, None]) & (
        blk0[None, :] < (q_start + q_len)[:, None]
    )  # [B, G]
    hit = member.any(axis=0)
    block_seq = jnp.where(
        hit, jnp.argmax(member, axis=0).astype(jnp.int32), -1)
    block_qoff = jnp.where(
        hit, blk0 - q_start[jnp.maximum(block_seq, 0)], 0)
    return block_seq, block_qoff


def _ragged_kernel(
    # scalar prefetch (SMEM)
    block_seq_ref,  # [G] int32 — sequence owning each BQ block (-1 = pad)
    block_qoff_ref,  # [G] int32 — block's first query offset in its slice
    page_table_ref,  # [B, W] int32
    kv_start_ref,  # [B] int32 — history length per sequence
    q_len_ref,  # [B] int32
    window_ref,  # [1] int32 — sliding window (0 = full attention)
    # inputs
    q_ref,  # [BQ, nq, d] VMEM block
    kv_hbm_ref,  # [num_pages, 2, nkv, ps, d] in HBM (int8 when quantized)
    *rest,  # (scales_hbm?) out_ref, kv_bufs, kv_sems, (s_bufs, s_sems?)
    bq: int,
    page_size: int,
    num_kv_heads: int,
    head_dim: int,
    scale: float,
    logit_softcap: float,
    quantized: bool,
):
    if quantized:
        scales_hbm_ref, out_ref, kv_bufs, kv_sems, s_bufs, s_sems = rest
    else:
        out_ref, kv_bufs, kv_sems = rest
        scales_hbm_ref = s_bufs = s_sems = None

    g = pl.program_id(0)
    s_raw = block_seq_ref[g]
    s = jnp.maximum(s_raw, 0)
    qoff = block_qoff_ref[g]
    kv0 = kv_start_ref[s]
    qn = q_len_ref[s]
    w = window_ref[0]
    # keys this block needs: positions 0 .. kv0 + min(qoff+BQ, qn) - 1
    kv_hi = kv0 + jnp.minimum(qoff + bq, qn)
    num_pages = jnp.where(
        s_raw < 0, 0, (kv_hi + page_size - 1) // page_size)

    def start_iter(i, slot):
        page = page_table_ref[s, i]
        pltpu.make_async_copy(
            kv_hbm_ref.at[page], kv_bufs.at[slot], kv_sems.at[slot]
        ).start()
        if quantized:
            pltpu.make_async_copy(
                scales_hbm_ref.at[page], s_bufs.at[slot], s_sems.at[slot]
            ).start()

    for j in range(NBUF - 1):
        @pl.when(j < num_pages)
        def _(j=j):
            start_iter(j, j)

    nq = q_ref.shape[1]
    group = nq // num_kv_heads
    rows = bq * group
    # [nkv, BQ*group, d]: row r*group+j is query token r, q-head group j
    q = (
        q_ref[...].astype(jnp.float32)
        .reshape(bq, num_kv_heads, group, head_dim)
        .transpose(1, 0, 2, 3)
        .reshape(num_kv_heads, rows, head_dim)
    )
    rowq = jax.lax.broadcasted_iota(jnp.int32, (1, rows, 1), 1) // group
    qpos = kv0 + qoff + rowq  # absolute position per query row
    qvalid = (qoff + rowq) < qn

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, NBUF)
        pltpu.make_async_copy(
            kv_hbm_ref.at[0], kv_bufs.at[slot], kv_sems.at[slot]
        ).wait()
        if quantized:
            pltpu.make_async_copy(
                scales_hbm_ref.at[0], s_bufs.at[slot], s_sems.at[slot]
            ).wait()

        @pl.when(i + NBUF - 1 < num_pages)
        def _():
            start_iter(i + NBUF - 1, jax.lax.rem(i + NBUF - 1, NBUF))

        k = kv_bufs[slot, 0].astype(jnp.float32)  # [nkv, ps, d]
        v = kv_bufs[slot, 1].astype(jnp.float32)
        if quantized:
            k = k * s_bufs[slot, 0].astype(jnp.float32)[..., None]
            v = v * s_bufs[slot, 1].astype(jnp.float32)[..., None]
        s_ = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale  # [nkv, BQ*group, ps]
        if logit_softcap > 0.0:
            s_ = jnp.tanh(s_ / logit_softcap) * logit_softcap
        kpos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2)
        mask = (kpos <= qpos) & qvalid
        mask = mask & ((qpos - kpos < w) | (w <= 0))
        s_ = jnp.where(mask, s_, -1e30)
        m_new = jnp.maximum(m, s_.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s_ - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [nkv, BQ*group, d]
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((num_kv_heads, rows, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((num_kv_heads, rows, 1), jnp.float32)
    acc0 = jnp.zeros((num_kv_heads, rows, head_dim), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_pages, body, (m0, l0, acc0))
    # rows past their slice's q_len never see a valid key: their running
    # max stays -1e30, so exp(s - m) saturates to 1 and acc collects a
    # garbage mean of V — mask them to exact zero instead
    out = jnp.where(qvalid, acc / jnp.maximum(l, 1e-30), 0.0)
    out_ref[...] = (
        out.reshape(num_kv_heads, bq, group, head_dim)
        .transpose(1, 0, 2, 3)
        .reshape(bq, nq, head_dim)
        .astype(out_ref.dtype)
    )


def _dense_ragged_kernel(
    # scalar prefetch (SMEM)
    page_table_ref,  # [B, W] int32
    kv_start_ref,  # [B] int32 — history length per lane
    q_len_ref,  # [B] int32 — valid slice tokens (<= sp; 0 = inactive)
    window_ref,  # [1] int32 — sliding window (0 = full attention)
    # inputs
    q_ref,  # [BQ, nq, d] VMEM block
    kv_hbm_ref,  # [num_pages, 2, nkv, ps, d] in HBM (int8 when quantized)
    *rest,  # (scales_hbm?) out_ref, kv_bufs, kv_sems, (s_bufs, s_sems?)
    sp: int,
    page_size: int,
    num_kv_heads: int,
    head_dim: int,
    scale: float,
    logit_softcap: float,
    quantized: bool,
):
    """Dense-block variant of `_ragged_kernel` (docs/kernels.md): every BQ
    block holds L = BQ // sp lanes at a STATIC stride of `sp` query rows
    each — the speculative-decode packing, where lane i's verify slice
    (its last token + K drafts, padded to sp) sits at offset i*sp.  The
    one-sequence-per-block invariant is relaxed to
    one-sequence-per-STRIDE-SLOT: row j belongs to relative lane j // sp,
    a static index, so the compute stays the decode kernel's batched
    [L, nkv, rows, ·] shape while each iteration streams page i of all L
    member lanes concurrently (L DMAs, like the decode kernel's SB)."""
    if quantized:
        scales_hbm_ref, out_ref, kv_bufs, kv_sems, s_bufs, s_sems = rest
    else:
        out_ref, kv_bufs, kv_sems = rest
        scales_hbm_ref = s_bufs = s_sems = None

    g = pl.program_id(0)
    lanes = q_ref.shape[0] // sp  # L member lanes per block (static)
    nq = q_ref.shape[1]
    group = nq // num_kv_heads
    rows = sp * group

    kv0 = jnp.stack(
        [kv_start_ref[g * lanes + l] for l in range(lanes)]
    ).reshape(lanes, 1, 1, 1)
    qn = jnp.stack(
        [q_len_ref[g * lanes + l] for l in range(lanes)]
    ).reshape(lanes, 1, 1, 1)
    # keys each lane needs; a lane with no valid query rows (inactive, or
    # capacity-starved mid-dispatch with a large kv_start) must not drive
    # the page loop — all its rows are masked, so streaming its history
    # would be pure wasted DMA
    kv_hi = jnp.where(qn > 0, kv0 + qn, 0)
    max_hi = kv_hi.max()
    num_pages = (max_hi + page_size - 1) // page_size

    def start_iter(i, slot):
        for l in range(lanes):
            # inactive lanes' padded table entries are the null page — a
            # valid, masked-out fetch (same contract as the decode kernel)
            page = page_table_ref[g * lanes + l, i]
            pltpu.make_async_copy(
                kv_hbm_ref.at[page], kv_bufs.at[slot, l], kv_sems.at[slot, l]
            ).start()
            if quantized:
                pltpu.make_async_copy(
                    scales_hbm_ref.at[page], s_bufs.at[slot, l],
                    s_sems.at[slot, l]
                ).start()

    for j in range(NBUF - 1):
        @pl.when(j < num_pages)
        def _(j=j):
            start_iter(j, j)

    # [L, nkv, sp*group, d]: row r*group+j is the lane's query token r,
    # q-head group j — the decode kernel's batched shape with sp query
    # rows per lane instead of one
    q = (
        q_ref[...].astype(jnp.float32)
        .reshape(lanes, sp, num_kv_heads, group, head_dim)
        .transpose(0, 2, 1, 3, 4)
        .reshape(lanes, num_kv_heads, rows, head_dim)
    )
    rowq = jax.lax.broadcasted_iota(jnp.int32, (1, 1, rows, 1), 2) // group
    qpos = kv0 + rowq  # absolute position per query row
    qvalid = rowq < qn
    w = window_ref[0]

    def body(i, carry):
        m, l_, acc = carry
        slot = jax.lax.rem(i, NBUF)
        for l in range(lanes):
            pltpu.make_async_copy(
                kv_hbm_ref.at[0], kv_bufs.at[slot, l], kv_sems.at[slot, l]
            ).wait()
            if quantized:
                pltpu.make_async_copy(
                    scales_hbm_ref.at[0], s_bufs.at[slot, l],
                    s_sems.at[slot, l]
                ).wait()

        @pl.when(i + NBUF - 1 < num_pages)
        def _():
            start_iter(i + NBUF - 1, jax.lax.rem(i + NBUF - 1, NBUF))

        k = kv_bufs[slot, :, 0].astype(jnp.float32)  # [L, nkv, ps, d]
        v = kv_bufs[slot, :, 1].astype(jnp.float32)
        if quantized:
            k = k * s_bufs[slot, :, 0].astype(jnp.float32)[..., None]
            v = v * s_bufs[slot, :, 1].astype(jnp.float32)[..., None]
        s_ = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((3,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32,
        ) * scale  # [L, nkv, rows, ps]
        if logit_softcap > 0.0:
            s_ = jnp.tanh(s_ / logit_softcap) * logit_softcap
        kpos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, 1, page_size), 3)
        mask = (kpos <= qpos) & qvalid
        mask = mask & ((qpos - kpos < w) | (w <= 0))
        s_ = jnp.where(mask, s_, -1e30)
        m_new = jnp.maximum(m, s_.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s_ - m_new)
        l_new = l_ * alpha + p.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32,
        )  # [L, nkv, rows, d]
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((lanes, num_kv_heads, rows, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((lanes, num_kv_heads, rows, 1), jnp.float32)
    acc0 = jnp.zeros((lanes, num_kv_heads, rows, head_dim), jnp.float32)
    m, l_, acc = jax.lax.fori_loop(0, num_pages, body, (m0, l0, acc0))
    # rows past q_len (slice padding / inactive lanes) never see a valid
    # key: mask them to exact zero (same contract as the solo-block kernel)
    out = jnp.where(qvalid, acc / jnp.maximum(l_, 1e-30), 0.0)
    out_ref[...] = (
        out.reshape(lanes, num_kv_heads, sp, group, head_dim)
        .transpose(0, 2, 1, 3, 4)
        .reshape(lanes * sp, nq, head_dim)
        .astype(out_ref.dtype)
    )


def _dense_ragged_call(q, pages, scales, page_table, q_len, kv_start, win,
                       sp, logit_softcap, scale, interpret):
    """pallas_call plumbing for the dense-stride kernel (shared scratch
    ring shape with the solo kernel, widened to L pages per iteration)."""
    T, nq, d = q.shape
    quantized = scales is not None
    nkv, ps = pages.shape[2], pages.shape[3]
    lanes = RAGGED_BQ // sp
    kernel = functools.partial(
        _dense_ragged_kernel,
        sp=sp,
        page_size=ps,
        num_kv_heads=nkv,
        head_dim=d,
        scale=float(scale),
        logit_softcap=logit_softcap,
        quantized=quantized,
    )
    in_specs = [
        pl.BlockSpec((RAGGED_BQ, nq, d), lambda g, *_: (g, 0, 0)),
        pl.BlockSpec(memory_space=_HBM),
    ]
    scratch = [
        pltpu.VMEM((NBUF, lanes) + pages.shape[1:], pages.dtype),
        pltpu.SemaphoreType.DMA((NBUF, lanes)),
    ]
    operands = [q, pages]
    if quantized:
        in_specs.append(pl.BlockSpec(memory_space=_HBM))
        scratch += [
            pltpu.VMEM((NBUF, lanes) + scales.shape[1:], scales.dtype),
            pltpu.SemaphoreType.DMA((NBUF, lanes)),
        ]
        operands.append(scales)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(T // RAGGED_BQ,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (RAGGED_BQ, nq, d), lambda g, *_: (g, 0, 0)),
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct((T, nq, d), q.dtype),
        interpret=interpret,
    )(page_table, kv_start, q_len, win, *operands)


def ragged_paged_attention_pallas(
    q: jnp.ndarray,  # [T, nq, d] — packed at RAGGED_BQ-aligned offsets
    kv_pages,  # [num_pages, 2, nkv, ps, d] or (int8 pages, scales)
    page_table: jnp.ndarray,  # [B, W] int32
    q_start: jnp.ndarray,  # [B] int32 (each a multiple of RAGGED_BQ)
    q_len: jnp.ndarray,  # [B] int32 (0 = inactive lane)
    kv_start: jnp.ndarray,  # [B] int32
    window=None,  # traced int32 scalar or None (full attention)
    logit_softcap: float = 0.0,
    scale: Optional[float] = None,
    interpret: bool = False,
    dense_stride: Optional[int] = None,  # static lane stride < RAGGED_BQ:
    # lane i's slice sits at offset i*dense_stride and blocks hold
    # BQ // dense_stride lanes (the speculative-verify packing)
) -> jnp.ndarray:
    T, nq, d = q.shape
    if T % RAGGED_BQ != 0:
        raise ValueError(
            f"ragged buffer length {T} not a multiple of RAGGED_BQ="
            f"{RAGGED_BQ}; pad the packed buffer")
    if d % 128 != 0 and not interpret:
        raise ValueError(
            f"ragged pallas kernel requires head_dim % 128 == 0, got {d}")
    quantized = isinstance(kv_pages, tuple)
    if quantized:
        pages, scales = kv_pages
        nkv, ps = pages.shape[2], pages.shape[3]
    else:
        pages, scales = kv_pages, None
        nkv, ps = kv_pages.shape[2], kv_pages.shape[3]
    if scale is None:
        scale = 1.0 / float(d) ** 0.5
    if dense_stride is not None and dense_stride < RAGGED_BQ:
        # dense-block packing (speculative verify): lanes share blocks at
        # a static stride, so the one-sequence-per-block invariant becomes
        # one-sequence-per-stride-slot (_dense_ragged_kernel)
        if RAGGED_BQ % dense_stride != 0:
            raise ValueError(
                f"dense_stride {dense_stride} must divide RAGGED_BQ="
                f"{RAGGED_BQ}")
        B = page_table.shape[0]
        if B * dense_stride != T:
            raise ValueError(
                f"dense packing expects T == B*stride "
                f"({B}*{dense_stride}), got T={T}")
        win = jnp.reshape(jnp.asarray(
            window if window is not None else 0, jnp.int32), (1,))
        return _dense_ragged_call(
            q, pages, scales, page_table, q_len, kv_start, win,
            dense_stride, logit_softcap, scale, interpret)
    G = T // RAGGED_BQ
    block_seq, block_qoff = _ragged_block_metadata(q_start, q_len, G, RAGGED_BQ)
    win = jnp.reshape(jnp.asarray(
        window if window is not None else 0, jnp.int32), (1,))
    kernel = functools.partial(
        _ragged_kernel,
        bq=RAGGED_BQ,
        page_size=ps,
        num_kv_heads=nkv,
        head_dim=d,
        scale=float(scale),
        logit_softcap=logit_softcap,
        quantized=quantized,
    )
    in_specs = [
        pl.BlockSpec((RAGGED_BQ, nq, d), lambda g, *_: (g, 0, 0)),
        pl.BlockSpec(memory_space=_HBM),
    ]
    scratch = [
        pltpu.VMEM((NBUF,) + pages.shape[1:], pages.dtype),
        pltpu.SemaphoreType.DMA((NBUF,)),
    ]
    operands = [q, pages]
    if quantized:
        in_specs.append(pl.BlockSpec(memory_space=_HBM))
        scratch += [
            pltpu.VMEM((NBUF,) + scales.shape[1:], scales.dtype),
            pltpu.SemaphoreType.DMA((NBUF,)),
        ]
        operands.append(scales)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=6,
            grid=(G,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (RAGGED_BQ, nq, d), lambda g, *_: (g, 0, 0)),
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct((T, nq, d), q.dtype),
        interpret=interpret,
    )(block_seq, block_qoff, page_table, kv_start, q_len, win,
      *operands)
