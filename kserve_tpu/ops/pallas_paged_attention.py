"""Fused paged-attention decode kernel (Pallas/TPU).

One grid step per sequence: the kernel walks the sequence's page list
(scalar-prefetched page table), streams each page's K/V from HBM into a
double-buffered VMEM scratch with async DMA, and folds it into an online-
softmax accumulator — no [B, L, nkv, d] gather ever materializes, so HBM
traffic is exactly one read of the live KV plus the output write.

This is the Ragged Paged Attention design point (see PAPERS.md) specialized
to decode (query length 1 per sequence).  The page-major cache layout
([2, num_pages, nkv, ps, d]) makes each DMA cover all local KV heads.

Numerics match ops/attention.paged_attention_xla (tests compare both paths
in interpret mode; bench exercises the compiled kernel on hardware).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [B, max_pages] int32 (SMEM)
    seq_lens_ref,  # [B] int32 (SMEM)
    # inputs
    q_ref,  # [1, nq, d] VMEM block for this sequence
    kv_hbm_ref,  # [2, num_pages, nkv, ps, d] in HBM (ANY)
    # output
    out_ref,  # [1, nq, d] VMEM
    # scratch
    kv_bufs,  # [2(buffer), 2(k/v), nkv, ps, d] VMEM
    sems,  # DMA semaphores [2]
    *,
    page_size: int,
    num_kv_heads: int,
    head_dim: int,
    scale: float,
    logit_softcap: float,
):
    b = pl.program_id(0)
    seq_len = seq_lens_ref[b]
    num_pages = (seq_len + page_size - 1) // page_size
    nq = q_ref.shape[1]
    group = nq // num_kv_heads

    def start_copy(i, slot):
        # two leading-dim DMAs (K then V): strided [:, page] slices are not
        # DMA-able, [kv, page] prefixes are
        page = page_table_ref[b, i]
        pltpu.make_async_copy(
            kv_hbm_ref.at[0, page], kv_bufs.at[slot, 0], sems.at[slot, 0]
        ).start()
        pltpu.make_async_copy(
            kv_hbm_ref.at[1, page], kv_bufs.at[slot, 1], sems.at[slot, 1]
        ).start()

    @pl.when(num_pages > 0)
    def _():
        start_copy(0, 0)

    # q laid out per kv-head group: [nkv, group, d] in f32
    q = q_ref[0].astype(jnp.float32).reshape(num_kv_heads, group, head_dim)

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)
        pltpu.make_async_copy(
            kv_hbm_ref.at[0, 0], kv_bufs.at[slot, 0], sems.at[slot, 0]
        ).wait()
        pltpu.make_async_copy(
            kv_hbm_ref.at[1, 0], kv_bufs.at[slot, 1], sems.at[slot, 1]
        ).wait()

        @pl.when(i + 1 < num_pages)
        def _():
            start_copy(i + 1, 1 - slot)

        k = kv_bufs[slot, 0].astype(jnp.float32)  # [nkv, ps, d]
        v = kv_bufs[slot, 1].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale  # [nkv, group, ps]
        if logit_softcap > 0.0:
            s = jnp.tanh(s / logit_softcap) * logit_softcap
        token_pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2
        )
        s = jnp.where(token_pos < seq_len, s, -1e30)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))  # [nkv, group, 1]
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [nkv, group, d]
        acc_new = acc * alpha + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((num_kv_heads, group, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((num_kv_heads, group, 1), jnp.float32)
    acc0 = jnp.zeros((num_kv_heads, group, head_dim), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_pages, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    out_ref[0] = out.reshape(nq, head_dim).astype(out_ref.dtype)


def paged_attention_pallas(
    q: jnp.ndarray,  # [B, nq, d]
    kv_pages: jnp.ndarray,  # [2, num_pages, nkv, ps, d]
    page_table: jnp.ndarray,  # [B, max_pages] int32
    seq_lens: jnp.ndarray,  # [B] int32
    logit_softcap: float = 0.0,
    interpret: bool = False,
) -> jnp.ndarray:
    B, nq, d = q.shape
    _, num_pages_total, nkv, ps, _ = kv_pages.shape
    if d % 128 != 0 and not interpret:
        # Lane tiling pads head_dim to 128 and Mosaic rejects both DMA
        # slices of the padded trailing dim and the shape-cast that would
        # unpack a token-packed row.  TODO(round2): packed-q compute for
        # d=64 models; callers fall back to the XLA path meanwhile.
        raise ValueError(
            f"pallas paged attention requires head_dim % 128 == 0, got {d}"
        )
    scale = float(1.0 / (d ** 0.5))
    kernel = functools.partial(
        _decode_kernel,
        page_size=ps,
        num_kv_heads=nkv,
        head_dim=d,
        scale=scale,
        logit_softcap=logit_softcap,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, nq, d), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_specs=pl.BlockSpec((1, nq, d), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM(tuple((2, 2) + kv_pages.shape[2:]), kv_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, nq, d), q.dtype),
        interpret=interpret,
    )(page_table, seq_lens, q, kv_pages)
