"""Process entrypoint: wires models + DataPlane + REST/gRPC servers.

`ModelServer.start(models)` blocks serving; `start_async()` is the embeddable
form used by tests and by engine runtimes that own the event loop.

Parity: reference python/kserve/kserve/model_server.py (start :332, engine
startup :441-455, signal handling, arg parser :48-208); rebuilt on
aiohttp/grpc.aio with the same lifecycle semantics.
"""

from __future__ import annotations

import argparse
import asyncio
import concurrent.futures
import inspect
import signal
from typing import Dict, List, Optional, Union

from . import logging as ks_logging
from .errors import NoModelReady
from .lifecycle import GenerationCheckpoint, ReplicaLifecycle
from .logging import logger
from .model import BaseModel, Model
from .model_repository import ModelRepository
from .protocol.dataplane import DataPlane
from .protocol.grpc.server import GRPCServer
from .protocol.model_repository_extension import ModelRepositoryExtension
from .protocol.openai.dataplane import OpenAIDataPlane
from .protocol.rest.server import RESTServer

DEFAULT_HTTP_PORT = 8080
DEFAULT_GRPC_PORT = 8081


def build_arg_parser(parents: Optional[list] = None) -> argparse.ArgumentParser:
    """Shared CLI surface; runtimes extend via parent-parser composition the
    same way the reference runtimes do."""
    parser = argparse.ArgumentParser(
        add_help=(parents is None), parents=parents or [], conflict_handler="resolve"
    )
    parser.add_argument("--http_port", default=DEFAULT_HTTP_PORT, type=int)
    parser.add_argument("--grpc_port", default=DEFAULT_GRPC_PORT, type=int)
    parser.add_argument("--workers", default=1, type=int)
    parser.add_argument("--max_threads", default=4, type=int)
    parser.add_argument("--max_asyncio_workers", default=None, type=int)
    parser.add_argument("--enable_grpc", default=True, type=lambda x: str(x).lower() == "true")
    parser.add_argument("--enable_docs_url", default=False, type=lambda x: str(x).lower() == "true")
    parser.add_argument(
        "--enable_latency_logging", default=True, type=lambda x: str(x).lower() == "true"
    )
    parser.add_argument("--log_config_file", default=None, type=str)
    parser.add_argument("--access_log_format", default=None, type=str)
    parser.add_argument("--model_name", default="model", type=str)
    parser.add_argument("--model_dir", default="/mnt/models", type=str)
    # secure serving (parity: the reference manager/agent TLS flags,
    # pkg/tls/tls.go; certs typically ride the self-signed Secret the
    # LLMISVC reconciler provisions)
    parser.add_argument("--ssl_certfile", default=None, type=str)
    parser.add_argument("--ssl_keyfile", default=None, type=str)
    parser.add_argument("--tls_min_version", default="1.2", type=str)
    parser.add_argument("--tls_cipher_suites", default=None, type=str)
    return parser


args, _ = build_arg_parser().parse_known_args()


class ModelServer:
    def __init__(
        self,
        http_port: int = args.http_port,
        grpc_port: int = args.grpc_port,
        workers: int = args.workers,
        max_threads: int = args.max_threads,
        max_asyncio_workers: Optional[int] = args.max_asyncio_workers,
        registered_models: Optional[ModelRepository] = None,
        enable_grpc: bool = args.enable_grpc,
        enable_docs_url: bool = args.enable_docs_url,
        enable_latency_logging: bool = args.enable_latency_logging,
        access_log_format: Optional[str] = args.access_log_format,
        grace_period: int = 30,
        ssl_certfile: Optional[str] = args.ssl_certfile,
        ssl_keyfile: Optional[str] = args.ssl_keyfile,
        tls_min_version: str = args.tls_min_version,
        tls_cipher_suites: Optional[str] = args.tls_cipher_suites,
    ):
        self.http_port = http_port
        self._ssl_context = None
        if ssl_certfile and ssl_keyfile:
            from .controlplane.tls import server_ssl_context

            self._ssl_context = server_ssl_context(
                ssl_certfile, ssl_keyfile,
                min_version=tls_min_version,
                cipher_suites=tls_cipher_suites,
            )
        self.grpc_port = grpc_port
        self.workers = workers
        self.max_threads = max_threads
        self.max_asyncio_workers = max_asyncio_workers
        self.enable_grpc = enable_grpc
        self.enable_docs_url = enable_docs_url
        self.enable_latency_logging = enable_latency_logging
        self.access_log_format = access_log_format
        self.grace_period = grace_period
        self.registered_models = registered_models or ModelRepository()
        self.dataplane = OpenAIDataPlane(self.registered_models)
        self.model_repository_extension = ModelRepositoryExtension(self.registered_models)
        self._rest_server: Optional[RESTServer] = None
        self._grpc_server: Optional[GRPCServer] = None
        self._engine_tasks: List[asyncio.Task] = []
        self._grpc_task: Optional[asyncio.Task] = None
        # replica lifecycle (kserve_tpu/lifecycle — docs/lifecycle.md):
        # STARTING -> READY after start_async; SIGTERM / POST /admin/drain
        # -> DRAINING (readiness red, admission 503, in-flight gets the
        # drain budget); second signal escalates to TERMINATING
        self.lifecycle = ReplicaLifecycle()
        if not ks_logging.is_configured():
            ks_logging.configure_logging(args.log_config_file)

    # ---------- registration ----------

    def register_model(self, model: BaseModel, name: Optional[str] = None) -> None:
        if not (name or getattr(model, "name", None)):
            raise Exception("Failed to register model, model.name must be provided.")
        self.registered_models.update(model)
        logger.info("Registering model: %s", name or model.name)

    def _register_and_check_ready(self, models: Union[List[BaseModel], Dict[str, object]]):
        if isinstance(models, dict):
            for name, handle in models.items():
                self.registered_models.update_handle(name, handle)
                logger.info("Registering model handle: %s", name)
        else:
            at_least_one_ready = False
            for model in models:
                if not isinstance(model, BaseModel):
                    raise RuntimeError("Model type should be 'BaseModel'")
                self.register_model(model)
                if model.ready:
                    at_least_one_ready = True
            engine_models = [m for m in models if _has_engine(m)]
            if not at_least_one_ready and models and not engine_models:
                raise NoModelReady(models)
            return engine_models
        return []

    # ---------- lifecycle ----------

    async def start_async(self, models: List[BaseModel]) -> None:
        """Start servers inside an existing event loop (non-blocking serve)."""
        engine_models = self._register_and_check_ready(models)
        self._setup_asyncio_executor()
        for model in engine_models:
            task = asyncio.create_task(_start_engine(model))
            task.add_done_callback(
                lambda _t, m=model: self._wire_stall_hook(m))
            self._engine_tasks.append(task)
        self._rest_server = RESTServer(
            self.dataplane,
            self.model_repository_extension,
            http_port=self.http_port,
            access_log_format=self.access_log_format,
            enable_docs_url=self.enable_docs_url,
            enable_latency_logging=self.enable_latency_logging,
            reuse_port=getattr(self, "_reuse_port", False),
            ssl_context=self._ssl_context,
            lifecycle=self.lifecycle,
            on_drain=self.drain_async,
        )
        await self._rest_server.start()
        if self.enable_grpc:
            self._grpc_server = GRPCServer(
                self.grpc_port, self.dataplane, self.model_repository_extension
            )
            self._grpc_task = asyncio.create_task(self._grpc_server.start(self.max_threads))
        self.lifecycle.mark_ready()

    def _wire_stall_hook(self, model) -> None:
        """Gray-failure watchdog wiring (docs/resilience.md): a confirmed
        engine stall must flip THIS replica's readiness red — the engine
        self-drains its streams internally, but only the server lifecycle
        makes the readiness probe (and with it the endpoint controller)
        see it.  Liveness stays green: checkpoints must outlive the
        stall, a kubelet kill would lose them."""
        engine = getattr(model, "engine", None)
        if engine is None or not hasattr(engine, "on_stall_confirmed"):
            return

        def on_stall(reason: str) -> None:
            logger.error(
                "engine stall confirmed (%s): flipping replica readiness "
                "(DRAINING)", reason)
            self.lifecycle.begin_drain()

        engine.on_stall_confirmed = on_stall

    async def drain_async(self) -> List[GenerationCheckpoint]:
        """Graceful drain: flip DRAINING (readiness red, liveness green,
        new inference 503s), give every engine's in-flight generations the
        drain budget, and checkpoint what the budget cannot finish.
        Idempotent — the signal handler and POST /admin/drain share one
        budget; escalation expires it in place."""
        deadline = self.lifecycle.begin_drain()
        logger.info(
            "draining replica: budget %.1fs (signal again to escalate)",
            max(deadline.remaining(), 0.0),
        )
        drains = []
        for model in self.registered_models.get_models().values():
            # model-level drain is the extension point (a wrapper can
            # aggregate several engines); plain engine models fall back to
            # engine.drain directly
            drain = getattr(model, "drain", None)
            if drain is not None:
                drains.append(drain(deadline))
                continue
            engine = getattr(model, "engine", None)
            if engine is not None and hasattr(engine, "drain"):
                drains.append(engine.drain(deadline))
        # CONCURRENT, not sequential: every engine must flip into drain
        # mode immediately — an engine drained later would keep seating new
        # work and 'length'-finishing KV-starved lanes while earlier models
        # consume the shared budget (DataParallelEngine.drain gathers its
        # replicas for the same reason)
        checkpoints: List[GenerationCheckpoint] = []
        for result in await asyncio.gather(*drains):
            checkpoints.extend(result)
        self.lifecycle.finish_drain()
        if checkpoints:
            logger.info(
                "drain complete: %d generation(s) checkpointed for resume "
                "elsewhere", len(checkpoints),
            )
        return checkpoints

    def _make_signal_handler(self, stop_event: asyncio.Event):
        """First SIGINT/SIGTERM starts the graceful drain; a SECOND signal
        escalates — it expires the drain budget in place (every engine
        drain loop observes that on its next poll) so shutdown proceeds
        immediately with the leftovers checkpointed, and cancels any stop
        task a model already has pending (a wedged engine.stop() must not
        outlive the escalation)."""

        def on_signal():
            if not stop_event.is_set():
                stop_event.set()
            else:
                logger.warning(
                    "second shutdown signal: escalating to immediate "
                    "shutdown (drain budget expired in place)"
                )
                self.lifecycle.escalate()
                for model in self.registered_models.get_models().values():
                    stop = getattr(model, "stop", None)
                    if stop is not None and (
                        "escalate" in inspect.signature(stop).parameters
                    ):
                        stop(escalate=True)

        return on_signal

    async def stop_async(self) -> None:
        for model_name in list(self.registered_models.get_models().keys()):
            try:
                self.registered_models.unload(model_name)
            except KeyError:
                pass
        for task in self._engine_tasks:
            task.cancel()
        if self._grpc_server is not None:
            await self._grpc_server.stop()
        if self._grpc_task is not None:
            self._grpc_task.cancel()
        if self._rest_server is not None:
            await self._rest_server.stop()

    def start(self, models: List[BaseModel]) -> None:
        """Blocking entrypoint.  workers > 1 serves the REST port from N
        processes sharing it via SO_REUSEPORT (parity: reference
        protocol/rest/multiprocess/server.py) — predictive serving only;
        a generative engine owns the accelerator and must stay single."""
        if self.workers > 1:
            self._start_multiprocess(models)
            return
        self._serve_blocking(models, reuse_port=False)

    def _serve_blocking(self, models: List[BaseModel], reuse_port: bool) -> None:
        self._reuse_port = reuse_port

        async def serve():
            await self.start_async(models)
            stop_event = asyncio.Event()
            loop = asyncio.get_event_loop()
            handler = self._make_signal_handler(stop_event)
            for sig in (signal.SIGINT, signal.SIGTERM):
                try:
                    loop.add_signal_handler(sig, handler)
                except NotImplementedError:  # pragma: no cover (non-unix)
                    pass
            await stop_event.wait()
            await self.drain_async()
            logger.info("Stopping servers (grace period %ss)", self.grace_period)
            await self.stop_async()

        asyncio.run(serve())

    def _child_main(self, models: List[BaseModel]) -> None:
        # one gRPC listener is enough; REST shares the port via SO_REUSEPORT
        self.enable_grpc = False
        self._serve_blocking(models, reuse_port=True)

    def _start_multiprocess(self, models: List[BaseModel]) -> None:
        if any(_has_engine(m) for m in models):
            raise ValueError(
                "--workers > 1 is for predictive serving; a generative "
                "engine owns the accelerator and cannot be forked"
            )
        import multiprocessing

        ctx = multiprocessing.get_context("fork")
        children = [
            ctx.Process(target=self._child_main, args=(models,), daemon=True)
            for _ in range(self.workers - 1)
        ]
        for child in children:
            child.start()
        logger.info(
            "REST multiprocess: %d workers sharing port %d (SO_REUSEPORT)",
            self.workers, self.http_port,
        )
        # a crashed worker must not silently degrade capacity: a monitor
        # thread respawns dead children (parity: reference multiprocess
        # server's process supervision)
        import threading

        stopping = threading.Event()

        def monitor():
            while not stopping.wait(5):
                for i, child in enumerate(children):
                    if not child.is_alive():
                        logger.error(
                            "REST worker pid=%s died (exitcode=%s); respawning",
                            child.pid, child.exitcode,
                        )
                        children[i] = ctx.Process(
                            target=self._child_main, args=(models,), daemon=True
                        )
                        children[i].start()

        threading.Thread(target=monitor, daemon=True).start()
        try:
            self._serve_blocking(models, reuse_port=True)
        finally:
            stopping.set()
            for child in children:
                child.terminate()
            for child in children:
                child.join(timeout=self.grace_period)

    def _setup_asyncio_executor(self):
        workers = self.max_asyncio_workers
        if workers is None:
            import multiprocessing

            # Mirrors the reference default: bounded small multiple of cores.
            workers = min(32, multiprocessing.cpu_count() + 4)
        loop = asyncio.get_event_loop()
        loop.set_default_executor(concurrent.futures.ThreadPoolExecutor(max_workers=workers))


def _has_engine(model: BaseModel) -> bool:
    return type(model).start_engine is not BaseModel.start_engine or (
        hasattr(model, "start_engine") and getattr(model, "_is_engine_model", False)
    )


async def _start_engine(model: BaseModel) -> None:
    try:
        result = model.start_engine()
        if asyncio.iscoroutine(result):
            await result
    except Exception:
        # a dead engine must be loud and fail readiness, not vanish into an
        # unawaited task
        logger.exception("engine startup failed for model %s", model.name)
        model.ready = False
        raise
