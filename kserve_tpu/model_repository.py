"""Name -> model registry with hot load/unload.

Parity: reference python/kserve/kserve/model_repository.py.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Union

from .model import BaseModel

MODEL_MOUNT_DIRS = "/mnt/models"


class ModelRepository:
    """Registry the data plane dispatches against.  Multi-model runtimes
    override `load()`/`unload()` to fetch/evict artifacts on demand."""

    def __init__(self, models_dir: str = MODEL_MOUNT_DIRS):
        self.models: Dict[str, BaseModel] = {}
        self.models_dir = models_dir

    def set_models_dir(self, models_dir: str):
        self.models_dir = models_dir

    def get_model(self, name: str) -> Optional[BaseModel]:
        model = self._get_model_direct(name)
        if model is not None:
            return model
        # alias resolution: a model may serve under extra names (vLLM-style
        # LoRA adapters select by the OpenAI `model` field)
        for candidate in self.models.values():
            if name in getattr(candidate, "aliases", ()):
                return candidate
        return None

    def _get_model_direct(self, name: str) -> Optional[BaseModel]:
        return self.models.get(name)

    def get_models(self) -> Dict[str, BaseModel]:
        return self.models

    async def is_model_ready(self, name: str) -> bool:
        model = self.get_model(name)
        if model is None:
            return False
        if not isinstance(model, BaseModel):  # e.g. Ray-style handle
            return True
        return model.ready

    def update(self, model: BaseModel):
        self.models[model.name] = model

    def update_handle(self, name: str, handle):
        self.models[name] = handle

    def load(self, name: str) -> bool:
        """Load a model by name from `models_dir/name`; runtimes that support
        multi-model serving override this."""
        return self.load_model(name)

    def load_model(self, name: str) -> bool:
        model = self.get_model(name)
        if model is None:
            return False
        if isinstance(model, BaseModel) and not model.ready:
            model.load()
        return model.ready

    def unload(self, name: str):
        if name in self.models:
            model = self.models[name]
            if isinstance(model, BaseModel):
                model.stop()
            del self.models[name]
        else:
            raise KeyError(f"model with name {name} does not exist")

    def model_dir_exists(self, name: str) -> bool:
        return os.path.isdir(os.path.join(self.models_dir, name))
