"""Payload <-> numpy helpers shared by the predictive runtimes.

Parity: reference python/kserve/kserve/utils/utils.py
(get_predict_input/get_predict_response).
"""

from __future__ import annotations

from typing import Dict, List, Union

import numpy as np

from ..errors import InvalidInput
from ..infer_type import InferInput, InferOutput, InferRequest, InferResponse


def single_input_matrix(instances, model_name: str) -> np.ndarray:
    """Predictive runtimes take exactly one input tensor; 400 otherwise."""
    if isinstance(instances, list):
        raise InvalidInput(
            f"model {model_name} expects a single input tensor, got "
            f"{len(instances)} named inputs"
        )
    try:
        return np.asarray(instances)
    except (ValueError, TypeError) as e:
        raise InvalidInput(f"malformed instances for model {model_name}: {e}")


def validate_feature_count(instances: np.ndarray, n_features: int, model_name: str) -> None:
    """400 (not an XLA shape error) when the input width doesn't match."""
    if n_features and instances.ndim >= 2 and instances.shape[-1] != n_features:
        raise InvalidInput(
            f"model {model_name} expects {n_features} features, got {instances.shape[-1]}"
        )


def get_predict_input(payload: Union[Dict, InferRequest]) -> Union[np.ndarray, List[np.ndarray]]:
    """Extract the model input matrix from a V1 dict or V2 InferRequest."""
    if isinstance(payload, InferRequest):
        if len(payload.inputs) == 1:
            return payload.inputs[0].as_numpy()
        return [inp.as_numpy() for inp in payload.inputs]
    if isinstance(payload, dict):
        instances = payload.get("instances", payload.get("inputs"))
        if instances is None:
            raise InvalidInput('Expected "instances" in request body')
        if (
            isinstance(instances, list)
            and len(instances) > 0
            and isinstance(instances[0], dict)
        ):
            # column-style records -> 2-D array in key order of first record
            keys = list(instances[0].keys())
            try:
                return np.asarray([[row[k] for k in keys] for row in instances])
            except (KeyError, TypeError) as e:
                raise InvalidInput(f"inconsistent record keys in instances: {e}")
        return np.asarray(instances)
    raise InvalidInput(f"unsupported payload type {type(payload).__name__}")


def get_predict_response(
    payload: Union[Dict, InferRequest],
    result: Union[np.ndarray, List],
    model_name: str,
) -> Union[Dict, InferResponse]:
    """Wrap a numpy result in the same protocol family the request used."""
    result = np.asarray(result)
    if isinstance(payload, InferRequest):
        output = InferOutput(
            name="output-0",
            shape=list(result.shape),
            datatype=_np_to_datatype(result),
        )
        binary = any(inp.raw_data is not None for inp in payload.inputs)
        output.set_data_from_numpy(result, binary_data=binary or payload.from_grpc)
        return InferResponse(
            response_id=payload.id,
            model_name=model_name,
            infer_outputs=[output],
        )
    return {"predictions": result.tolist()}


def _np_to_datatype(arr: np.ndarray) -> str:
    from .numpy_codec import from_np_dtype

    dt = from_np_dtype(arr.dtype)
    if dt is None:
        raise InvalidInput(f"unsupported result dtype {arr.dtype}")
    return dt
