"""JAX backend selection helper.

This image ships an `axon` PJRT plugin that force-selects itself via
JAX_PLATFORMS at import time, so the usual env vars are unreliable.  Calling
jax.config.update("jax_platforms", ...) before backend init is the only
selector that always wins; runtimes call `apply_platform_override()` first
thing so `JAX_PLATFORM_NAME=cpu` behaves as users expect.
"""

from __future__ import annotations

import os

from ..logging import logger


def apply_platform_override() -> None:
    want = os.environ.get("JAX_PLATFORM_NAME", "").strip().lower()
    # multi-host: join the slice BEFORE backend init (jax.distributed must
    # precede the first device query).  Deliberately OUTSIDE the tolerant
    # try below: a pod that cannot join its slice must crash-loop, not
    # quietly serve single-host.
    import jax

    if want:
        try:
            jax.config.update("jax_platforms", want)
            logger.info("JAX platform forced to %s via JAX_PLATFORM_NAME", want)
        except Exception as e:
            logger.warning("could not force JAX platform: %s", e)
    from .distributed import maybe_initialize_distributed

    maybe_initialize_distributed()
    try:
        # Initialize the backend NOW: the ambient JAX_PLATFORMS=axon names a
        # plugin that intermittently fails to register when jax first
        # initializes late inside a server process.  Initializing early —
        # with an auto-select retry — makes runtime startup deterministic.
        try:
            jax.devices()
        except RuntimeError as e:
            if not want:
                logger.warning("backend init failed (%s); retrying auto-select", e)
                jax.config.update("jax_platforms", "")
                jax.devices()
            else:
                raise
        logger.info("JAX backend: %s (%d devices)", jax.default_backend(), len(jax.devices()))
    except Exception as e:  # pragma: no cover — backend already initialized
        logger.warning("could not configure JAX platform: %s", e)
