"""JAX backend selection helper.

This image ships an `axon` PJRT plugin that force-selects itself via
JAX_PLATFORMS at import time, so the usual env vars are unreliable.  Calling
jax.config.update("jax_platforms", ...) before backend init is the only
selector that always wins; runtimes call `apply_platform_override()` first
thing so `JAX_PLATFORM_NAME=cpu` behaves as users expect.
"""

from __future__ import annotations

import os

from ..logging import logger


def apply_platform_override() -> None:
    want = os.environ.get("JAX_PLATFORM_NAME", "").strip().lower()
    if not want:
        return
    try:
        import jax

        jax.config.update("jax_platforms", want)
        logger.info("JAX platform forced to %s via JAX_PLATFORM_NAME", want)
    except Exception as e:  # pragma: no cover — backend already initialized
        logger.warning("could not force JAX platform %s: %s", want, e)
