"""Multi-host bootstrap: join the jax.distributed coordination service from
the environment the LLMISVC controller injects.

The controller's multi-host workload (controlplane/llmisvc.py) is a
StatefulSet whose pods share a headless peer Service; it injects
COORDINATOR_ADDRESS (peer-0 DNS:port) and NUM_PROCESSES (slice host count).
The process rank comes from PROCESS_ID when set, else the StatefulSet pod
ordinal parsed from the hostname ("name-3" -> 3).

Parity: the reference bootstraps multi-node vLLM through Ray/LWS
(pkg/controller/.../components/predictor.go:656-681,
config/runtimes/kserve-huggingfaceserver-multinode.yaml:36-40); here the
coordination layer IS jax.distributed — XLA collectives then ride ICI
within a slice and DCN across slices with no extra runtime.

MUST run after the platform override but before the first jax backend use.
"""

from __future__ import annotations

import os
import socket
from typing import Optional

from ..logging import logger


def infer_process_id() -> Optional[int]:
    """Rank from $PROCESS_ID, $JOB_COMPLETION_INDEX (Jobs), or the
    StatefulSet ordinal suffix of the hostname."""
    for var in ("PROCESS_ID", "JOB_COMPLETION_INDEX"):
        val = os.getenv(var)
        if val is not None and val.strip():
            return int(val)
    hostname = os.getenv("HOSTNAME") or socket.gethostname()
    _, _, suffix = hostname.rpartition("-")
    if suffix.isdigit():
        return int(suffix)
    return None


def maybe_initialize_distributed(env: Optional[dict] = None) -> bool:
    """Call jax.distributed.initialize from the injected env; no-op (False)
    when COORDINATOR_ADDRESS/NUM_PROCESSES are absent.  Raises on malformed
    env or an unreachable coordinator — a multi-host pod that cannot join
    its slice must crash-loop, not serve a split brain."""
    env = env if env is not None else dict(os.environ)
    address = (env.get("COORDINATOR_ADDRESS") or "").strip()
    num = (env.get("NUM_PROCESSES") or "").strip()
    if not address or not num:
        return False
    num_processes = int(num)
    if num_processes < 2:
        logger.info("NUM_PROCESSES=%s: single-host, skipping jax.distributed", num)
        return False
    explicit = (env.get("PROCESS_ID") or "").strip()
    process_id = int(explicit) if explicit else infer_process_id()
    if process_id is None:
        raise RuntimeError(
            "multi-host env present (COORDINATOR_ADDRESS/NUM_PROCESSES) but "
            "no process rank: set PROCESS_ID or run under a StatefulSet "
            "(ordinal hostname)"
        )
    import jax

    logger.info(
        "joining jax.distributed: coordinator=%s rank=%d/%s",
        address, process_id, num_processes,
    )
    jax.distributed.initialize(
        coordinator_address=address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed up: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
    return True
