"""Open Inference Protocol tensor <-> numpy codec.

Covers the datatype table of the V2 (OIP) protocol plus TPU-relevant BF16, and
the BYTES binary wire format (4-byte little-endian length-prefixed elements).

Parity: reference python/kserve/kserve/utils/numpy_codec.py and the datatype
handling spread through python/kserve/kserve/infer_type.py; rebuilt clean.
"""

from __future__ import annotations

import struct
from typing import List, Optional

import numpy as np

try:  # bfloat16 rides along with jax/ml_dtypes; optional for pure-CPU installs
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None

# OIP datatype name -> numpy dtype
_DTYPE_TABLE = {
    "BOOL": np.dtype(np.bool_),
    "UINT8": np.dtype(np.uint8),
    "UINT16": np.dtype(np.uint16),
    "UINT32": np.dtype(np.uint32),
    "UINT64": np.dtype(np.uint64),
    "INT8": np.dtype(np.int8),
    "INT16": np.dtype(np.int16),
    "INT32": np.dtype(np.int32),
    "INT64": np.dtype(np.int64),
    "FP16": np.dtype(np.float16),
    "FP32": np.dtype(np.float32),
    "FP64": np.dtype(np.float64),
}
if _BF16 is not None:
    _DTYPE_TABLE["BF16"] = _BF16

_REVERSE_TABLE = {v: k for k, v in _DTYPE_TABLE.items()}


def to_np_dtype(datatype: str) -> Optional[np.dtype]:
    """OIP datatype string -> numpy dtype (BYTES -> object dtype)."""
    if datatype == "BYTES":
        return np.dtype(object)
    return _DTYPE_TABLE.get(datatype)


def from_np_dtype(dtype: np.dtype) -> Optional[str]:
    """numpy dtype -> OIP datatype string."""
    dtype = np.dtype(dtype)
    if dtype.kind in ("S", "U", "O"):
        return "BYTES"
    return _REVERSE_TABLE.get(dtype)


def serialize_byte_tensor(tensor: np.ndarray) -> bytes:
    """Flatten a BYTES tensor (object/str/bytes ndarray) to the OIP binary
    format: each element is a uint32 little-endian length followed by raw bytes.
    Elements are serialized in C order."""
    if tensor.size == 0:
        return b""
    flat = np.ascontiguousarray(tensor).flatten()
    out = bytearray()
    for el in flat:
        if isinstance(el, bytes):
            raw = el
        elif isinstance(el, str):
            raw = el.encode("utf-8")
        elif isinstance(el, (np.bytes_,)):
            raw = bytes(el)
        elif isinstance(el, (np.str_,)):
            raw = str(el).encode("utf-8")
        else:
            raw = str(el).encode("utf-8")
        out += struct.pack("<I", len(raw))
        out += raw
    return bytes(out)


def deserialize_bytes_tensor(encoded: bytes) -> np.ndarray:
    """Inverse of serialize_byte_tensor -> 1-D object ndarray of bytes."""
    items: List[bytes] = []
    offset = 0
    n = len(encoded)
    while offset < n:
        if offset + 4 > n:
            raise ValueError("malformed BYTES tensor: truncated length prefix")
        (length,) = struct.unpack_from("<I", encoded, offset)
        offset += 4
        if offset + length > n:
            raise ValueError("malformed BYTES tensor: truncated element")
        items.append(encoded[offset : offset + length])
        offset += length
    return np.array(items, dtype=object)
