"""KServeClient: the operator-facing Python SDK.

Parity: reference python/kserve/kserve/api/kserve_client.py (create :114,
get :259, patch :357, delete :481, is_isvc_ready :523, wait_isvc_ready
:543).  The reference SDK binds to the Kubernetes API server through the
generated kubernetes client; here the transport is pluggable: the default
binds to an in-process ControllerManager (the fake apiserver used across
the control-plane tests).  A custom transport must provide apply(obj),
apply_yaml(path), get(kind, name, namespace), list(kind, namespace) and
delete(kind, name, namespace) — e.g. a thin shim over a real apiserver.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Union


class KServeClient:
    def __init__(self, transport=None):
        if transport is None:
            from ..controlplane.cluster import ControllerManager

            transport = ControllerManager()
        self.transport = transport

    # ---------------- CRUD ----------------

    def create(self, resource: dict) -> dict:
        return self.transport.apply(resource)

    def apply_yaml(self, path: str) -> List[dict]:
        return self.transport.apply_yaml(path)

    def get(self, kind: str, name: str, namespace: str = "default") -> Optional[dict]:
        return self.transport.get(kind, name, namespace)

    def list(self, kind: str, namespace: Optional[str] = None) -> List[dict]:
        return self.transport.list(kind, namespace)

    def patch(self, kind: str, name: str, patch: dict,
              namespace: str = "default") -> dict:
        """Strategic-merge patch + re-reconcile."""
        from ..controlplane.objects import strategic_merge

        existing = self.get(kind, name, namespace)
        if existing is None:
            raise KeyError(f"{kind}/{namespace}/{name} not found")
        merged = strategic_merge(existing, patch)
        return self.transport.apply(merged)

    def replace(self, resource: dict) -> dict:
        return self.transport.apply(resource)

    def delete(self, kind: str, name: str, namespace: str = "default") -> bool:
        return self.transport.delete(kind, name, namespace)

    # ---------------- InferenceService conveniences ----------------

    def is_isvc_ready(self, name: str, namespace: str = "default") -> bool:
        isvc = self.get("InferenceService", name, namespace)
        if isvc is None:
            return False
        for cond in isvc.get("status", {}).get("conditions", []):
            if cond.get("type") == "Ready":
                return cond.get("status") in (True, "True")
        return False

    def wait_isvc_ready(self, name: str, namespace: str = "default",
                        timeout_seconds: int = 600,
                        polling_interval: float = 1.0) -> dict:
        deadline = time.monotonic() + timeout_seconds
        while time.monotonic() < deadline:
            if self.is_isvc_ready(name, namespace):
                return self.get("InferenceService", name, namespace)
            if hasattr(self.transport, "reconcile_all"):
                self.transport.reconcile_all()
            if self.is_isvc_ready(name, namespace):
                return self.get("InferenceService", name, namespace)
            # sync SDK surface: callers are operator CLIs/tests off the
            # event loop, so a real sleep is the contract here
            time.sleep(polling_interval)  # jaxlint: disable=blocking-async
        raise TimeoutError(
            f"InferenceService {namespace}/{name} not Ready after "
            f"{timeout_seconds}s"
        )

    def isvc_url(self, name: str, namespace: str = "default") -> Optional[str]:
        isvc = self.get("InferenceService", name, namespace)
        return (isvc or {}).get("status", {}).get("url")
