"""HTTP transport: the SDK/controller binding to a real Kubernetes apiserver.

Speaks the Kubernetes REST wire protocol with stdlib urllib only (no
kubernetes client dependency): create via POST, update via PUT (falling
back from a 409 create), status via the `status` subresource
merge-patch, list/get at the canonical paths, and `?watch=true` chunked
JSON streams with resourceVersion resume.

Exposes BOTH surfaces used across the repo:
- the `FakeCluster` store surface (`apply/get/list/delete/update_status/
  all_objects`) so `ControllerManager` can run its reconcilers against a
  real apiserver unchanged, and
- the `KServeClient` transport surface (`apply_yaml`, no `reconcile_all`)
  so the operator SDK drives the same cluster the manager watches.

Parity: python/kserve/kserve/api/kserve_client.py:114 (SDK over the real
API) + the client-go reader/writer pair behind the reference manager.
"""

from __future__ import annotations

import json
import ssl
import time
import urllib.error
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple

from ..controlplane.gvk import (
    BUILTIN_RESOURCES,
    Resource,
    api_version_of,
    collection_path,
    object_path,
    resource_from_crd,
)
from ..logging import logger
from ..metrics import RETRY_ATTEMPTS
from ..resilience import RetryPolicy, parse_retry_after


class APIError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"apiserver returned {status}: {message}")
        self.status = status
        self.message = message


class HTTPCluster:
    """Store-surface client for one apiserver (`base_url`, optional bearer
    token / CA bundle — in-cluster config is read from the standard
    serviceaccount mount when ``in_cluster=True``)."""

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, base_url: str, token: Optional[str] = None,
                 ca_file: Optional[str] = None, in_cluster: bool = False,
                 timeout: float = 30.0):
        if in_cluster:
            import os

            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            base_url = f"https://{host}:{port}"
            try:
                with open(f"{self.SA_DIR}/token") as f:
                    token = f.read().strip()
            except OSError:
                pass
            ca = f"{self.SA_DIR}/ca.crt"
            import os.path

            if os.path.exists(ca):
                ca_file = ca
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        # apiserver flow control (429) is retried under the shared policy:
        # the request was rejected before execution, so any verb is safe
        self.retry_policy = RetryPolicy(
            max_attempts=3, base_backoff_s=0.2, max_backoff_s=2.0
        )
        self._ssl_ctx = None
        if self.base_url.startswith("https"):
            self._ssl_ctx = ssl.create_default_context(cafile=ca_file)
            if ca_file is None:
                self._ssl_ctx.check_hostname = False
                self._ssl_ctx.verify_mode = ssl.CERT_NONE
        self._resources: Dict[str, Resource] = dict(BUILTIN_RESOURCES)

    # ---------------- plumbing ----------------

    def _request(self, method: str, path: str, body=None,
                 content_type: str = "application/json",
                 timeout: Optional[float] = None, stream: bool = False):
        url = self.base_url + path
        data = None
        headers = {"Accept": "application/json"}
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = content_type
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(url, data=data, headers=headers,
                                     method=method)
        attempt = 0
        started = time.monotonic()
        while True:
            attempt += 1
            try:
                resp = urllib.request.urlopen(
                    req, timeout=timeout or self.timeout, context=self._ssl_ctx)
                break
            except urllib.error.HTTPError as exc:
                detail = exc.read().decode(errors="replace")
                retry_after = parse_retry_after(exc.headers.get("Retry-After"))
                try:
                    detail = json.loads(detail).get("message", detail)
                except ValueError:
                    pass  # non-JSON error body: keep the raw text
                if exc.code == 429 and not stream:
                    delay = self.retry_policy.next_delay(
                        attempt, retry_after=retry_after,
                        elapsed=time.monotonic() - started)
                    if delay is not None:
                        RETRY_ATTEMPTS.labels(component="cluster").inc()
                        # sync bootstrap/controller client — no event loop
                        time.sleep(delay)  # jaxlint: disable=blocking-async
                        continue
                raise APIError(exc.code, detail) from None
        if stream:
            return resp
        with resp:
            payload = resp.read()
        if not payload:
            return {}
        try:
            return json.loads(payload)
        except ValueError:  # non-JSON endpoints (/readyz)
            return {"raw": payload.decode(errors="replace")}

    def _resource(self, kind: str) -> Resource:
        res = self._resources.get(kind)
        if res is None:
            self.refresh_discovery()
            res = self._resources.get(kind)
        if res is None:
            raise KeyError(f"no served resource for kind {kind!r}")
        return res

    def has_kind(self, kind: str) -> bool:
        return kind in self._resources

    def refresh_discovery(self) -> None:
        """Learn CRD-backed kinds from the server (the RESTMapper refresh)."""
        try:
            crds = self.list("CustomResourceDefinition")
        except APIError:
            return
        for crd in crds:
            res = resource_from_crd(crd)
            if res is not None:
                self._resources[res.kind] = res

    # ---------------- FakeCluster store surface ----------------

    def _coords(self, obj: dict):
        res = self._resource(obj.get("kind", ""))
        meta = obj.get("metadata", {})
        ns = meta.get("namespace", "default") if res.namespaced else None
        return res, ns, meta.get("name", "")

    def create(self, obj: dict) -> dict:
        """Strict POST — 409 AlreadyExists raises (leader election and
        anything else racing on create-wins semantics needs this; apply()
        would silently fall through to a replace)."""
        res, ns, _ = self._coords(obj)
        obj = dict(obj)
        obj.setdefault("apiVersion", api_version_of(res))
        return self._request("POST", collection_path(res, ns), obj)

    def replace(self, obj: dict) -> dict:
        """Strict PUT — carries metadata.resourceVersion so a concurrent
        writer surfaces as a 409 Conflict (optimistic concurrency)."""
        res, ns, name = self._coords(obj)
        obj = dict(obj)
        obj.setdefault("apiVersion", api_version_of(res))
        return self._request("PUT", object_path(res, ns, name), obj)

    def apply(self, obj: dict) -> dict:
        try:
            return self.create(obj)
        except APIError as exc:
            if exc.status != 409:
                raise
        # exists → replace (the server preserves the status subresource);
        # drop any stale resourceVersion — apply semantics are last-write-wins
        obj = dict(obj)
        if obj.get("metadata", {}).get("resourceVersion"):
            obj["metadata"] = {k: v for k, v in obj["metadata"].items()
                               if k != "resourceVersion"}
        return self.replace(obj)

    def get(self, kind: str, name: str,
            namespace: str = "default") -> Optional[dict]:
        res = self._resource(kind)
        ns = namespace if res.namespaced else None
        try:
            return self._request("GET", object_path(res, ns, name))
        except APIError as exc:
            if exc.status == 404:
                return None
            raise

    def list_collection(self, kind: str, namespace: Optional[str] = None,
                        label_selector: Optional[str] = None) -> dict:
        """Full <Kind>List response — items plus the collection
        resourceVersion watch loops resume from."""
        res = self._resource(kind)
        ns = namespace if res.namespaced else None
        path = collection_path(res, ns)
        if label_selector:
            from urllib.parse import quote

            path += f"?labelSelector={quote(label_selector)}"
        return self._request("GET", path)

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[str] = None) -> List[dict]:
        return self.list_collection(kind, namespace,
                                    label_selector).get("items", [])

    def delete(self, kind: str, name: str,
               namespace: str = "default") -> bool:
        res = self._resource(kind)
        ns = namespace if res.namespaced else None
        try:
            self._request("DELETE", object_path(res, ns, name))
            return True
        except APIError as exc:
            if exc.status == 404:
                return False
            raise

    def update_status(self, kind: str, name: str, namespace: str,
                      status: dict) -> None:
        res = self._resource(kind)
        ns = namespace if res.namespaced else None
        try:
            self._request(
                "PATCH", object_path(res, ns, name) + "/status",
                {"status": status},
                content_type="application/merge-patch+json")
        except APIError as exc:
            if exc.status == 404:
                logger.debug("status patch target %s/%s gone", kind, name)
            else:
                raise

    def all_objects(self) -> List[dict]:
        """Every object of every known resource type (the reconcilers'
        prune pass needs an ownership sweep; a real controller would use
        per-type informer caches)."""
        out: List[dict] = []
        for kind in list(self._resources):
            try:
                out.extend(self.list(kind))
            except APIError:
                continue
        return out

    # ---------------- watch ----------------

    def watch(self, kind: str, namespace: Optional[str] = None,
              resource_version: Optional[str] = None,
              timeout_seconds: float = 300,
              ) -> Iterator[Tuple[str, dict]]:
        """Yield (event_type, object) from one watch request; returns when
        the server closes the stream (callers loop + resume from the last
        seen resourceVersion)."""
        res = self._resource(kind)
        ns = namespace if res.namespaced else None
        path = (f"{collection_path(res, ns)}?watch=true"
                f"&timeoutSeconds={int(timeout_seconds)}")
        if resource_version:
            path += f"&resourceVersion={resource_version}"
        resp = self._request("GET", path, stream=True,
                             timeout=timeout_seconds + 15)
        with resp:
            for raw in resp:
                line = raw.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except ValueError:
                    continue
                yield event.get("type", ""), event.get("object", {})

    # ---------------- KServeClient transport surface ----------------

    def apply_yaml(self, path: str) -> List[dict]:
        from ..controlplane.objects import iter_yaml_documents

        applied = [self.apply(doc) for doc in iter_yaml_documents(path)]
        self.refresh_discovery()
        return applied

    def wait_ready(self, timeout: float = 15.0) -> None:
        # readiness probing rides the shared backoff policy (capped by the
        # caller's timeout) instead of a fixed-interval poll
        policy = RetryPolicy(
            max_attempts=10_000, base_backoff_s=0.2, max_backoff_s=1.0,
            retry_budget_s=timeout,
        )
        started = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            try:
                self._request("GET", "/readyz")
                return
            except (APIError, OSError):
                delay = policy.next_delay(
                    attempt, elapsed=time.monotonic() - started)
                if delay is None:
                    break
                # sync bootstrap client: runs before any event loop exists
                # (manager/agent main() readiness gate)
                time.sleep(delay)  # jaxlint: disable=blocking-async
        raise TimeoutError(f"apiserver at {self.base_url} not ready")
