from .client import KServeClient

__all__ = ["KServeClient"]
