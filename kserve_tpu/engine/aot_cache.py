"""Persistent AOT executable cache: zero-compile replica start.

A replica restart today re-traces and re-compiles every engine program
(`mixed`, the legacy prefill/decode set, the inject scatters) even though
the programs are 100% identical across replicas of the same deployment —
restart cost is dominated by redundant work (ROADMAP item 3; SLINFER
arXiv:2507.00507 and DeepServe arXiv:2501.14417 both put cold start on
the critical path of scale-to-zero).  This module makes compiled
executables a *persistent artifact*:

- ``AOTProgram`` replaces ``jax.jit(fn)`` at the engine dispatch seam.
  Each distinct input signature (pytree structure + leaf shape/dtype) is
  lowered ONCE with ``jax.jit(fn).lower(*args).compile()`` and the
  resulting executable is serialized to a disk cache via
  ``jax.experimental.serialize_executable`` (the XLA executable
  serialization path ``jax.export`` also rides).  Subsequent dispatches
  call the loaded executable directly — no tracing, no lowering, no XLA.
- On replica start, ``preload()`` deserializes every cached entry for
  this configuration digest, so a warm start performs **zero** XLA
  compiles (pinned by ``engine_xla_compiles_total`` in
  tests/test_retrace_budget.py) and its first request pays neither
  trace nor compile nor deserialize latency.
- The cache key is a content digest of everything that changes the
  compiled artifact: the model config, the engine-config fields the
  compiled programs read (``AOT_KEY_ENGINE_FIELDS`` — the jaxlint rule
  ``aot-cache-key-drift`` pins this list against the fields
  ``build_compiled`` actually reads), the mesh topology and device
  assignment, and the jax/jaxlib versions.  Any drift lands in a fresh
  digest directory; stale executables are structurally unreachable.
- Corrupt or version-skewed entries NEVER crash a start: they log a
  structured warning, count an ``invalid`` cache event, and fall back to
  trace-and-compile (which then overwrites the bad entry).

Deploy story (docs/coldstart.md): point ``EngineConfig.aot_cache_dir``
(env ``KSERVE_TPU_AOT_CACHE``) at a node-local hostPath or a warmed PVC;
the first replica on a node pays the compile and every later start —
scale-up burst, crash restart, scale-from-zero wake — is weight-I/O
bound instead of compile-bound.
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
import time
from dataclasses import dataclass, field
from hashlib import sha256
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..logging import logger
from ..metrics import AOT_CACHE_EVENTS, XLA_COMPILES

# bump when the on-disk entry layout changes; old entries become
# structurally invalid (logged + recompiled) instead of misread
AOT_CACHE_FORMAT = 1

#: EngineConfig fields that participate in the cache-key digest.  This is
#: the canonical list the jaxlint rule ``aot-cache-key-drift`` checks
#: ``engine/compiled.py`` against: every engine-config field read during
#: compiled-program construction MUST appear here, or two configs that
#: differ in that field would silently share executables (the
#: stale-executable hazard).  Fields that only steer host-side scheduling
#: (queue policy, offload tiers, deadlines) are deliberately excluded so
#: tuning them does not cold-start the fleet.
AOT_KEY_ENGINE_FIELDS = (
    "max_batch_size",
    "page_size",
    "num_pages",
    "max_pages_per_seq",
    "max_prefill_len",
    "prefill_buckets",
    "tp",
    "dp",
    "sp",
    "pp",
    "pp_microbatches",
    "dtype",
    "kv_quant",
    "weight_quant",
    "use_pallas",
    "steps_per_sync",
    "prefill_batch",
    "max_logprobs",
    "use_ragged",
)


def aot_cache_dir_from_env() -> Optional[str]:
    """The deploy knob: ``KSERVE_TPU_AOT_CACHE`` names the cache dir the
    llmisvc reconciler mounts (hostPath/warmed PVC).  Empty/unset = the
    cache is disabled and every start compiles (today's behavior)."""
    value = os.environ.get("KSERVE_TPU_AOT_CACHE", "").strip()
    return value or None


def _jsonable(value: Any) -> Any:
    """Digest-stable view of a config value (tuples/dtypes -> plain)."""
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in sorted(value.items())}
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return str(value)


def aot_cache_key(model_config, engine_config, mesh) -> str:
    """Content digest of everything that determines the compiled
    artifact.  Model config is digested WHOLE (any architectural field
    changes the HLO); engine config is digested through the explicit
    ``AOT_KEY_ENGINE_FIELDS`` list; the mesh contributes axis names,
    shape, and the concrete device assignment (serialized executables
    bake device ids, so dp groups on disjoint device sets must not share
    entries); jax/jaxlib versions guard serialization-format skew."""
    import dataclasses as _dc

    import jaxlib

    devices = list(mesh.devices.flat) if mesh is not None else jax.devices()
    payload = {
        "format": AOT_CACHE_FORMAT,
        "model": _jsonable(_dc.asdict(model_config)),
        "engine": {
            name: _jsonable(getattr(engine_config, name, None))
            for name in AOT_KEY_ENGINE_FIELDS
        },
        "mesh": {
            "axis_names": list(getattr(mesh, "axis_names", ()) or ()),
            "shape": _jsonable(dict(getattr(mesh, "shape", {}) or {})),
            "devices": [
                (d.id, d.platform, getattr(d, "device_kind", ""))
                for d in devices
            ],
        },
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
    }
    blob = json.dumps(payload, sort_keys=True).encode()
    return sha256(blob).hexdigest()[:32]


def _leaf_sig(x: Any) -> Tuple:
    """Signature atom for one pytree leaf: shape + dtype + weak-type +
    sharding spelling.  Two calls with equal signatures are guaranteed to
    hit the same jit-cache entry, so they may share one executable."""
    aval = getattr(x, "aval", None)
    if aval is not None:
        sharding = getattr(x, "sharding", None)
        spec = getattr(sharding, "spec", None)
        return (
            tuple(aval.shape),
            str(aval.dtype),
            bool(getattr(aval, "weak_type", False)),
            str(spec) if spec is not None else "",
        )
    arr = np.asarray(x)
    return (tuple(arr.shape), str(arr.dtype), isinstance(x, (int, float)), "")


def signature_of(args: Tuple) -> Tuple:
    """Hashable signature of a positional arg tuple (pytree structure +
    per-leaf signatures) — the in-memory executable cache key.
    PyTreeDefs are hashable, so they key directly."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (treedef, tuple(_leaf_sig(leaf) for leaf in leaves))


def signature_digest(sig: Tuple) -> str:
    return sha256(repr(sig).encode()).hexdigest()[:24]


def _discard_tmp(tmp_name: Optional[str]) -> None:
    """Remove a temp file that never made it to its rename (None = it
    did); best-effort, the cache dir may be going away underneath us."""
    if tmp_name is None:
        return
    try:
        os.unlink(tmp_name)
    except OSError:
        pass


def _reset_jax_compilation_cache() -> None:
    """Drop jax's in-memory compilation-cache state so the enable-flag is
    re-consulted on the next compile (is_cache_used latches its verdict
    once per process; without the reset a disable toggle is ignored after
    any cached compile has happened).  Private-API guarded: on a jax that
    moved it, the AOT cache degrades to verified stores (see store())."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception as exc:  # noqa: BLE001 — best-effort; store() verifies
        logger.debug("jax compilation-cache reset unavailable: %s", exc)


@dataclass
class AOTCacheStats:
    """Per-engine accounting behind ``engine_startup_seconds`` and the
    coldstart bench: wall seconds per startup phase plus event counts."""

    trace_s: float = 0.0
    compile_s: float = 0.0
    aot_load_s: float = 0.0
    compiles: int = 0
    loads: int = 0
    stores: int = 0
    invalid: int = 0

    def as_dict(self) -> Dict[str, float]:
        return {
            "trace_s": round(self.trace_s, 6),
            "compile_s": round(self.compile_s, 6),
            "aot_load_s": round(self.aot_load_s, 6),
            "compiles": self.compiles,
            "loads": self.loads,
            "stores": self.stores,
            "invalid": self.invalid,
        }


class AOTExecutableCache:
    """Disk cache of serialized engine executables for ONE configuration
    digest.  Thread-compatible with the engine's single-dispatcher model:
    all loads/stores happen on the engine loop thread."""

    def __init__(self, cache_dir: str, model_config, engine_config, mesh,
                 label: str = "engine"):
        self.digest = aot_cache_key(model_config, engine_config, mesh)
        self.root = os.path.join(cache_dir, self.digest)
        self.label = label
        self.stats = AOTCacheStats()
        os.makedirs(self.root, exist_ok=True)
        self._write_meta(model_config, engine_config)

    def _write_meta(self, model_config, engine_config) -> None:
        """Human-auditable digest description (never read back for
        validation — the digest dir name IS the validation)."""
        meta_path = os.path.join(self.root, "meta.json")
        if os.path.exists(meta_path):
            return
        import dataclasses as _dc

        tmp_name = None
        try:
            with tempfile.NamedTemporaryFile(
                "w", dir=self.root, suffix=".tmp", delete=False
            ) as f:
                tmp_name = f.name
                json.dump({
                    "format": AOT_CACHE_FORMAT,
                    "jax": jax.__version__,
                    "backend": jax.default_backend(),
                    "model": _jsonable(_dc.asdict(model_config)),
                    "engine": {
                        k: _jsonable(getattr(engine_config, k, None))
                        for k in AOT_KEY_ENGINE_FIELDS
                    },
                }, f, sort_keys=True, indent=1)
            os.replace(tmp_name, meta_path)
            tmp_name = None
        except OSError:
            logger.warning("aot-cache meta write failed under %s", self.root)
        finally:
            _discard_tmp(tmp_name)

    # ---------------- entry IO ----------------

    def _entry_path(self, program: str, sig_hash: str) -> str:
        return os.path.join(self.root, f"{program}.{sig_hash}.aotexe")

    def entries(self, program: str) -> List[str]:
        """Signature hashes cached on disk for `program`."""
        prefix = f"{program}."
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(
            n[len(prefix):-len(".aotexe")]
            for n in names
            if n.startswith(prefix) and n.endswith(".aotexe")
        )

    def oracle_reports(self) -> Dict[str, dict]:
        """The per-compile oracle metric snapshots persisted alongside
        the executables ({"<program>.<sig_hash>": report}; see
        AOTProgram._observe).  Written only on genuine cold compiles, so
        this is the cost record of what THIS digest's fleet actually
        built — unreadable/corrupt snapshots are skipped."""
        out: Dict[str, dict] = {}
        suffix = ".oracle.json"
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for n in sorted(names):
            if not n.endswith(suffix):
                continue
            try:
                with open(os.path.join(self.root, n),
                          encoding="utf-8") as f:
                    out[n[:-len(suffix)]] = json.load(f)
            except (OSError, ValueError):
                continue
        return out

    def load(self, program: str, sig_hash: str):
        """Deserialize one executable; None on any miss/corruption/skew
        (the caller falls back to trace-and-compile — a bad cache entry
        must cost a compile, never a crash)."""
        path = self._entry_path(program, sig_hash)
        if not os.path.exists(path):
            AOT_CACHE_EVENTS.labels(program=program, event="miss").inc()
            return None
        t0 = time.perf_counter()
        try:
            from jax.experimental import serialize_executable as _se

            with open(path, "rb") as f:
                entry = pickle.load(f)
            if (entry.get("format") != AOT_CACHE_FORMAT
                    or entry.get("jax") != jax.__version__):
                raise ValueError(
                    f"format/version skew: entry {entry.get('format')}/"
                    f"{entry.get('jax')} vs {AOT_CACHE_FORMAT}/{jax.__version__}"
                )
            compiled = _se.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"])
        except Exception as exc:  # noqa: BLE001 — any deserialization
            # failure (truncated write, pickle drift, backend skew) must
            # degrade to a compile, not a crashed replica start
            self.stats.invalid += 1
            AOT_CACHE_EVENTS.labels(program=program, event="invalid").inc()
            logger.warning(
                "aot-cache-entry-invalid program=%s path=%s error=%s: "
                "falling back to trace-and-compile", program, path,
                f"{type(exc).__name__}: {exc}",
            )
            return None
        dt = time.perf_counter() - t0
        self.stats.aot_load_s += dt
        self.stats.loads += 1
        AOT_CACHE_EVENTS.labels(program=program, event="hit").inc()
        return compiled

    def store(self, program: str, sig_hash: str, compiled) -> None:
        """Serialize one executable (atomic tmp+rename so a concurrent
        reader never sees a torn entry).  Best-effort: a full disk must
        not take down serving."""
        tmp_name = None
        try:
            from jax.experimental import serialize_executable as _se

            payload, in_tree, out_tree = _se.serialize(compiled)
            # round-trip verification BEFORE persisting: CPU executable
            # serialization is lossy for executables that were themselves
            # deserialized (jax-cache hits), and a silently-poisoned entry
            # would force a compile on every future restart while looking
            # cached.  A payload that cannot load back is never written.
            _se.deserialize_and_load(payload, in_tree, out_tree)
            entry = {
                "format": AOT_CACHE_FORMAT,
                "jax": jax.__version__,
                "program": program,
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            }
            with tempfile.NamedTemporaryFile(
                "wb", dir=self.root, suffix=".tmp", delete=False
            ) as f:
                tmp_name = f.name
                pickle.dump(entry, f)
            os.replace(tmp_name, self._entry_path(program, sig_hash))
            tmp_name = None
            self.stats.stores += 1
            AOT_CACHE_EVENTS.labels(program=program, event="store").inc()
        except Exception as exc:  # noqa: BLE001 — persistence is an
            # optimization; serving continues with the in-memory executable
            logger.warning(
                "aot-cache-store-failed program=%s error=%s",
                program, f"{type(exc).__name__}: {exc}")
        finally:
            # a write that died before the rename (disk full mid-pickle —
            # the exact survivable failure) must not leave a giant orphan
            # .tmp accumulating on the shared node volume
            _discard_tmp(tmp_name)


#: callables invoked as ``observer(program, sig_hash, lowered, compiled)``
#: after every genuine AOTProgram compile — the HLO perf oracle's
#: extraction seam (analysis/hlo_oracle).  Warm starts never compile, so
#: a warm fleet pays zero extraction cost by construction.
_COMPILE_OBSERVERS: List[Callable] = []


def register_compile_observer(fn: Callable) -> Callable:
    _COMPILE_OBSERVERS.append(fn)
    return fn


def unregister_compile_observer(fn: Callable) -> None:
    try:
        _COMPILE_OBSERVERS.remove(fn)
    except ValueError:
        pass


class AOTProgram:
    """Callable standing where ``jax.jit(fn)`` stood in CompiledPrograms:
    per-signature ahead-of-time compiled executables, persisted across
    process restarts.

    Dispatch path per call: build the (cheap, hashable) arg signature ->
    in-memory executable table -> disk cache -> trace+lower+compile.
    Only the last leg counts into ``engine_xla_compiles_total`` — which
    is exactly what makes "warm start performs zero XLA compiles" an
    assertable property rather than a log line."""

    __slots__ = ("_name", "_jit", "_cache", "_mem", "_sig_hash",
                 "_arg_memo")

    def __init__(self, name: str, fn: Callable, cache: AOTExecutableCache,
                 donate_argnums: Tuple[int, ...] = ()):
        self._name = name
        self._jit = jax.jit(fn, donate_argnums=donate_argnums)
        self._cache = cache
        self._mem: Dict[str, Any] = {}  # sig hash -> loaded executable
        self._sig_hash: Dict[Tuple, str] = {}  # signature -> hash memo
        # per-arg-position signature memo keyed by OBJECT IDENTITY (strong
        # ref held, so the id cannot be recycled): the params pytree —
        # hundreds of leaves on a real model — is the same object on every
        # dispatch, and re-flattening it per step would put Python pytree
        # work on the decode hot path
        self._arg_memo: Dict[int, Tuple[Any, Tuple]] = {}

    @property
    def name(self) -> str:
        return self._name

    def preload(self) -> int:
        """Deserialize every on-disk entry for this program into memory
        (replica start: first request pays zero trace/compile/load).
        Returns the number of executables loaded."""
        n = 0
        for sig_hash in self._cache.entries(self._name):
            if sig_hash in self._mem:
                continue
            compiled = self._cache.load(self._name, sig_hash)
            if compiled is not None:
                self._mem[sig_hash] = compiled
                n += 1
        return n

    def _compile(self, args: Tuple, sig_hash: str = ""):
        stats = self._cache.stats
        t0 = time.perf_counter()
        lowered = self._jit.lower(*args)
        t1 = time.perf_counter()
        # CPU-only: this xla's thunk-runtime executable serialization is
        # not self-contained for large programs — deserialization dies
        # with "Symbols not found: [<fusion kernels>]" (JIT-resolved
        # symbols are not embedded in the payload; reproduced under the
        # test suite's 8-virtual-device platform).  The legacy runtime
        # plus single-module codegen serializes whole.  Scoped to
        # AOT-cached builds; TPU executables serialize self-contained.
        options = (
            {
                "xla_cpu_use_thunk_runtime": False,
                "xla_cpu_parallel_codegen_split_count": 1,
            }
            if jax.default_backend() == "cpu" else None
        )
        # bypass jax's own persistent compilation cache for THIS compile:
        # an executable returned from a cache HIT is itself deserialized,
        # and serialize(deserialized) is LOSSY on CPU (the payload drops
        # the JIT-resolved symbols -> "Symbols not found" on the next
        # start), so the artifact we persist must come from a genuine
        # backend compile.  Toggling the flag alone is not enough: once
        # jax's cache object is initialized, reads keep happening — so
        # reset the latch too (it re-initializes on the next ordinary jit
        # compile).  The two caches are redundant here anyway — ours is
        # the one keyed for replica reuse.
        prev = jax.config.jax_enable_compilation_cache
        try:
            jax.config.update("jax_enable_compilation_cache", False)
            _reset_jax_compilation_cache()
            compiled = lowered.compile(options)
        finally:
            jax.config.update("jax_enable_compilation_cache", prev)
            _reset_jax_compilation_cache()
        t2 = time.perf_counter()
        stats.trace_s += t1 - t0
        stats.compile_s += t2 - t1
        stats.compiles += 1
        XLA_COMPILES.labels(program=self._name).inc()
        self._observe(lowered, compiled, sig_hash)
        return compiled

    def _observe(self, lowered, compiled, sig_hash: str) -> None:
        """Post-compile extraction seam (cold compiles only — warm starts
        dispatch straight from the loaded executable and never get here):
        record the compile fingerprint, notify registered observers, and
        persist a best-effort oracle metrics snapshot next to the cached
        executable so the perf deltas of a fleet's cold starts are
        inspectable after the fact (AOTExecutableCache.oracle_reports)."""
        try:
            from .compiled import record_compile_fingerprint

            hlo_hash = sha256(lowered.as_text().encode()).hexdigest()[:12]
            record_compile_fingerprint(
                self._name, f"aot-sig:{sig_hash}", hlo_hash)
        except Exception:
            logger.debug("aot-fingerprint-failed program=%s",
                         self._name, exc_info=True)
        for obs in list(_COMPILE_OBSERVERS):
            try:
                obs(self._name, sig_hash, lowered, compiled)
            except Exception as exc:  # noqa: BLE001 — an observer must
                # never take down a compile that already succeeded
                logger.warning(
                    "aot-compile-observer-failed program=%s error=%s",
                    self._name, f"{type(exc).__name__}: {exc}")
        try:
            # donation intent is audited by the oracle's keep_unused
            # builds (analysis/hlo_oracle/oracle.py); the snapshot keeps
            # the artifact-level metrics + raw honored-alias count
            from ..analysis.hlo_oracle import extract as _extract

            report = _extract.compiled_report(compiled)
            hlo = _extract.hlo_text(compiled)
            if hlo is not None:
                report["alias_entries"] = len(_extract.alias_table(hlo))
            report["program"] = self._name
            report["sig_hash"] = sig_hash
            report["jax"] = jax.__version__
            path = os.path.join(
                self._cache.root, f"{self._name}.{sig_hash}.oracle.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(report, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except Exception:  # snapshots are diagnostics, never load-bearing
            logger.debug("aot-oracle-snapshot-failed program=%s",
                         self._name, exc_info=True)

    def _signature(self, args: Tuple) -> Tuple:
        """signature_of with a per-arg identity memo: stable big subtrees
        (params) skip re-flattening on the hot path."""
        parts = []
        for i, a in enumerate(args):
            memo = self._arg_memo.get(i)
            if memo is not None and memo[0] is a:
                parts.append(memo[1])
                continue
            leaves, treedef = jax.tree_util.tree_flatten(a)
            part = (treedef, tuple(_leaf_sig(leaf) for leaf in leaves))
            if len(leaves) > 8:
                self._arg_memo[i] = (a, part)
            parts.append(part)
        return tuple(parts)

    def __call__(self, *args):
        sig = self._signature(args)
        sig_hash = self._sig_hash.get(sig)
        if sig_hash is None:
            sig_hash = self._sig_hash[sig] = signature_digest(sig)
        exe = self._mem.get(sig_hash)
        if exe is None:
            exe = self._cache.load(self._name, sig_hash)
            if exe is None:
                exe = self._compile(args, sig_hash)
                self._cache.store(self._name, sig_hash, exe)
            self._mem[sig_hash] = exe
        return exe(*args)
