"""Paged KV cache: device arrays + host-side page allocator.

Layout per layer: [num_pages, 2, n_kv_heads, page_size, head_dim] —
page-MAJOR so one page is one contiguous block holding K and V for ALL
local KV heads: the Pallas decode kernel streams it with a single 64KB-class
DMA descriptor per page (K+V together), while tensor parallelism still
shards the head axis over the `model` mesh with no resharding at attention
time.  Sequences own pages through a page table [B_slots,
max_pages_per_seq]; page 0 is reserved as the null page so padded table
entries are always valid gathers.

Role parity: replaces vLLM's block allocator + CUDA paged attention cache
(the reference delegates this entirely to vLLM; see SURVEY.md §2.3) with an
XLA-native design.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class KVCacheConfig:
    n_layers: int
    n_kv_heads: int
    head_dim: int
    page_size: int = 16
    num_pages: int = 1024
    max_pages_per_seq: int = 128
    dtype: str = "bfloat16"

    @property
    def max_seq_len(self) -> int:
        return self.max_pages_per_seq * self.page_size

    def bytes_per_page(self) -> int:
        itemsize = 2 if self.dtype in ("bfloat16", "float16") else 4
        return 2 * self.n_kv_heads * self.page_size * self.head_dim * itemsize


def init_kv_pages(config: KVCacheConfig, sharding=None) -> List[jnp.ndarray]:
    """[n_layers] list of page-major K/V pages:
    [num_pages, 2, n_kv_heads, page_size, head_dim]."""
    shape = (config.num_pages, 2, config.n_kv_heads, config.page_size, config.head_dim)
    dtype = jnp.dtype(config.dtype)
    pages = []
    for _ in range(config.n_layers):
        arr = jnp.zeros(shape, dtype=dtype)
        if sharding is not None:
            arr = jax.device_put(arr, sharding)
        pages.append(arr)
    return pages


class PageAllocator:
    """Host-side free-list with refcounts; page 0 is reserved (null page for
    padding).  Refcounts let prefix-cached pages be shared by concurrent
    sequences AND the cache itself — a page returns to the free list only
    when its last reference drops."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free = list(range(num_pages - 1, 0, -1))  # stack, page 0 reserved
        self._refs = [0] * num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) >= n

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise MemoryError(f"KV cache exhausted: need {n} pages, {len(self._free)} free")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def share(self, pages: List[int]) -> None:
        for p in pages:
            if p != 0:
                self._refs[p] += 1

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p == 0:
                continue
            if self._refs[p] <= 0:
                # double-free must not duplicate the page on the free list
                # (two sequences would then share it and corrupt KV)
                continue
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)


def pages_needed(n_tokens: int, page_size: int) -> int:
    return (n_tokens + page_size - 1) // page_size


def write_prompt_kv(
    kv_pages: jnp.ndarray,  # [num_pages, 2, n_kv, ps, d]
    k: jnp.ndarray,  # [T, n_kv, d]
    v: jnp.ndarray,  # [T, n_kv, d]
    page_ids: jnp.ndarray,  # [max_pages_this_seq] int32 (padded with 0)
    n_tokens: jnp.ndarray,  # scalar int32: valid token count
    page_size: int,
) -> jnp.ndarray:
    """Scatter a prefilled prompt's K/V into its pages.  Writes the full
    padded T; positions >= n_tokens land on the null page (page 0)."""
    T = k.shape[0]
    t = jnp.arange(T, dtype=jnp.int32)
    valid = t < n_tokens
    page_of_t = jnp.where(valid, page_ids[t // page_size], 0)
    slot_of_t = t % page_size
    kv = jnp.stack([k, v]).astype(kv_pages.dtype)  # [2, T, n_kv, d]
    # non-adjacent advanced indices (dims 0,3) put the broadcast dim first:
    # the updated slice has shape [T, 2, n_kv, d]
    return kv_pages.at[page_of_t, :, :, slot_of_t, :].set(
        kv.transpose(1, 0, 2, 3), mode="drop", unique_indices=False
    )


def write_prompt_kv_batch(
    kv_pages: jnp.ndarray,  # [num_pages, 2, n_kv, ps, d]
    k: jnp.ndarray,  # [B, T, n_kv, d]
    v: jnp.ndarray,  # [B, T, n_kv, d]
    page_ids: jnp.ndarray,  # [B, max_pages] int32
    valid_len: jnp.ndarray,  # [B] int32
    page_size: int,
) -> jnp.ndarray:
    """Batched prompt scatter (one op for the whole prefill batch)."""
    B, T = k.shape[:2]
    t = jnp.arange(T, dtype=jnp.int32)
    page_idx = jnp.broadcast_to(t // page_size, (B, T))
    page_of = jnp.take_along_axis(page_ids, page_idx, axis=1)  # [B, T]
    page_of = jnp.where(t[None, :] < valid_len[:, None], page_of, 0)
    slot_of = jnp.broadcast_to(t % page_size, (B, T)).reshape(-1)
    pages_flat = page_of.reshape(-1)
    return _scatter_kv(kv_pages, k, v, pages_flat, slot_of)


def write_chunk_kv_batch(
    kv_pages,  # [num_pages, 2, nkv, ps, d] or (int8 pages, scales)
    k: jnp.ndarray,  # [B, C, n_kv, d] — chunk keys
    v: jnp.ndarray,  # [B, C, n_kv, d]
    page_ids: jnp.ndarray,  # [B, max_pages] int32 — the SEQUENCE's pages
    chunk_start: jnp.ndarray,  # [B] absolute position of chunk token 0
    valid_len: jnp.ndarray,  # [B] valid tokens within the chunk
    page_size: int,
):
    """write_prompt_kv_batch generalized to an offset chunk (chunked
    prefill): chunk token t lands at absolute position chunk_start+t."""
    B, C = k.shape[:2]
    t = jnp.arange(C, dtype=jnp.int32)
    pos = chunk_start[:, None] + t[None, :]  # [B, C]
    page_idx = pos // page_size
    page_of = jnp.take_along_axis(page_ids, page_idx, axis=1)
    page_of = jnp.where(t[None, :] < valid_len[:, None], page_of, 0)
    slot_of = (pos % page_size).reshape(-1)
    pages_flat = page_of.reshape(-1)
    return _scatter_kv(kv_pages, k, v, pages_flat, slot_of)


def _scatter_kv(kv_pages, k, v, pages_flat, slot_flat):
    """Scatter K/V rows (k/v: [N, ..., n_kv, d] flattened to [Nf, n_kv, d])
    into a plain or quantized ((int8 pages, scales)) cache at the given
    flat (page, slot) indices; updated slice shape [Nf, 2, n_kv, d]."""
    lead = int(np.prod(k.shape[:-2])) if k.ndim > 3 else k.shape[0]
    kf = k.reshape(lead, k.shape[-2], k.shape[-1])
    vf = v.reshape(lead, v.shape[-2], v.shape[-1])
    if isinstance(kv_pages, tuple):
        pages, scales = kv_pages
        qk, sk = quantize_rows(kf)  # [Nf, n_kv, d] int8, [Nf, n_kv]
        qv, sv = quantize_rows(vf)
        values = jnp.stack([qk, qv], axis=1)  # [Nf, 2, n_kv, d]
        svals = jnp.stack([sk, sv], axis=1)  # [Nf, 2, n_kv]
        pages = pages.at[pages_flat, :, :, slot_flat, :].set(
            values, mode="drop", unique_indices=False
        )
        scales = scales.at[pages_flat, :, :, slot_flat].set(
            svals, mode="drop", unique_indices=False
        )
        return pages, scales
    values = jnp.stack([kf, vf], axis=1).astype(kv_pages.dtype)
    return kv_pages.at[pages_flat, :, :, slot_flat, :].set(
        values, mode="drop", unique_indices=False
    )


def write_ragged_kv(
    kv_pages,  # [num_pages, 2, n_kv, ps, d] or (int8 pages, scales)
    k: jnp.ndarray,  # [T, n_kv, d] — packed ragged slice keys
    v: jnp.ndarray,  # [T, n_kv, d]
    page_table: jnp.ndarray,  # [B, max_pages_per_seq]
    token_seq: jnp.ndarray,  # [T] sequence index per packed token (-1 = pad)
    token_pos: jnp.ndarray,  # [T] absolute position per packed token
    page_size: int,
):
    """Ragged-batch scatter: each packed token lands at its sequence's
    (page, slot) for its absolute position; padding tokens (seq -1) write
    to the null page.  Decode steps (one token per sequence) and prompt
    chunks (many) are the same scatter — the write half of the ragged
    contract (docs/kernels.md)."""
    valid = token_seq >= 0
    seq_ix = jnp.maximum(token_seq, 0)
    page = jnp.where(
        valid, page_table[seq_ix, token_pos // page_size], 0)
    slot = token_pos % page_size
    return _scatter_kv(kv_pages, k[:, None], v[:, None], page, slot)


def append_token_kv(
    kv_pages: jnp.ndarray,  # [num_pages, 2, n_kv, ps, d]
    k: jnp.ndarray,  # [B, n_kv, d]
    v: jnp.ndarray,  # [B, n_kv, d]
    page_table: jnp.ndarray,  # [B, max_pages_per_seq]
    pos: jnp.ndarray,  # [B] position being written
    active: jnp.ndarray,  # [B] bool — inactive slots write to null page
    page_size: int,
) -> jnp.ndarray:
    """Decode-step scatter: one new token per active sequence."""
    B = k.shape[0]
    b = jnp.arange(B, dtype=jnp.int32)
    page = jnp.where(active, page_table[b, pos // page_size], 0)
    slot = pos % page_size
    return _scatter_kv(kv_pages, k[:, None], v[:, None], page, slot)


# ---------------- int8 KV quantization (opt-in, kv_quant="int8") ----------------
#
# Decode is KV-bandwidth-bound (the gather reads the live context every
# step); int8 halves that traffic vs bf16 and doubles KV capacity.  Scales
# are per (page, k/v, head, token-row) — absmax over head_dim — stored in a
# parallel [num_pages, 2, n_kv, ps] f32 array (~3% overhead at d=128).  A
# quantized layer cache travels as the tuple (pages_int8, scales).

def init_kv_scales(config: KVCacheConfig, sharding=None) -> List[jnp.ndarray]:
    shape = (config.num_pages, 2, config.n_kv_heads, config.page_size)
    out = []
    for _ in range(config.n_layers):
        arr = jnp.ones(shape, jnp.float32)
        if sharding is not None:
            arr = jax.device_put(arr, sharding)
        out.append(arr)
    return out


def quantize_rows(x: jnp.ndarray) -> tuple:
    """x [..., d] -> (int8 rows, f32 row scales): symmetric absmax."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return q, scale[..., 0]


def dequantize_rows(q: jnp.ndarray, scale: jnp.ndarray, dtype) -> jnp.ndarray:
    return (q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)).astype(dtype)
