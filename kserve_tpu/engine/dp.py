"""Engine-level data parallelism: dp independent LLMEngine replicas over
disjoint tp*sp-sized device groups, with least-loaded request routing.

Decode batches have no cross-request math, so a lockstep `data` mesh axis
would buy nothing and cost a synchronized schedule (every replica waiting on
the slowest prefill) plus per-step collectives.  Independent replicas are
the TPU-native answer and match the semantics the reference reaches through
vLLM's DP ranks (llm_inference_service_types.go:679-700 dataParallelism):
linear decode throughput, isolated failure domains, per-replica KV space.
"""

from __future__ import annotations

import asyncio
from dataclasses import replace
from typing import Any, AsyncIterator, List, Optional, Tuple

import jax
import numpy as np

from ..logging import logger
from ..models import llama
from .engine import EngineConfig, GenerationOutput, LLMEngine
from .sampling import SamplingParams
from .tokenizer import BaseTokenizer


class DataParallelEngine:
    """API-compatible with LLMEngine (start/stop/generate/...); routes each
    request to the least-loaded replica."""

    def __init__(
        self,
        model_config: llama.LlamaConfig,
        engine_config: EngineConfig,
        tokenizer: BaseTokenizer,
        params: Optional[Any] = None,
        rng_seed: int = 0,
        devices: Optional[list] = None,
        checkpoint_label: Optional[str] = None,
        lora_adapters: Optional[dict] = None,
    ):
        dp = engine_config.dp
        if dp < 2:
            raise ValueError("DataParallelEngine needs dp >= 2; use LLMEngine")
        devices = list(devices) if devices is not None else list(jax.devices())
        per_replica = engine_config.tp * engine_config.sp * engine_config.pp
        if dp * per_replica > len(devices):
            raise ValueError(
                f"dp={dp} x (tp*sp*pp)={per_replica} needs {dp * per_replica} "
                f"devices, have {len(devices)}"
            )
        self.config = engine_config
        self.model_config = model_config
        self.tokenizer = tokenizer
        # stack adapters ONCE; replicas shard the same host arrays
        lora_stacked = None
        if lora_adapters:
            from ..models import lora as lora_mod

            lora_stacked = lora_mod.stack_adapters(
                lora_adapters, model_config.n_layers, dtype=model_config.dtype
            )
        replica_cfg = replace(engine_config, dp=1)
        self.replicas: List[LLMEngine] = [
            LLMEngine(
                model_config,
                replica_cfg,
                tokenizer,
                params=params,
                rng_seed=rng_seed + g,
                devices=devices[g * per_replica : (g + 1) * per_replica],
                metrics_label=f"engine-dp{g}",
                # one weights identity shared by every dp group (NOT the
                # per-group metrics label): a checkpoint from any group
                # resumes on any other
                checkpoint_label=checkpoint_label or "engine",
                lora_stacked=lora_stacked,
            )
            for g in range(dp)
        ]
        self.cache_config = self.replicas[0].cache_config
        self.adapter_ids = self.replicas[0].adapter_ids
        self.mesh = self.replicas[0].mesh  # compat: a replica's submesh
        self._rr = 0  # round-robin cursor for equal-load tie-breaks

    # ---------------- lifecycle ----------------

    async def start(self):
        for eng in self.replicas:
            await eng.start()
        logger.info(
            "DP engine started: %d replicas x (tp=%d, sp=%d)",
            len(self.replicas), self.config.tp, self.config.sp,
        )

    async def stop(self):
        await asyncio.gather(*[eng.stop() for eng in self.replicas])

    @property
    def running(self) -> bool:
        return all(eng.running for eng in self.replicas)

    @property
    def wedged(self) -> bool:
        """Any replica wedged wedges the pod: its slice of traffic would
        hang forever, and a restart re-homes all replicas together."""
        return any(eng.wedged for eng in self.replicas)

    @property
    def draining(self) -> bool:
        return any(eng.draining for eng in self.replicas)

    async def drain(self, deadline=None, clock=None,
                    poll_s: float = 0.01) -> list:
        """Drain every dp group concurrently against the shared budget;
        the pod's checkpoints are the aggregate (lifecycle drain —
        docs/lifecycle.md)."""
        results = await asyncio.gather(
            *[eng.drain(deadline, clock=clock, poll_s=poll_s)
              for eng in self.replicas]
        )
        return [ckpt for per_replica in results for ckpt in per_replica]

    def resume_generation(
        self, checkpoint, request_id: Optional[str] = None,
    ) -> AsyncIterator[GenerationOutput]:
        """Re-seat a drained/preempted generation on the least-loaded dp
        group (all groups share one weights identity, so any accepts it)."""
        return self._pick().resume_generation(checkpoint, request_id=request_id)

    # ---------------- routing ----------------

    def _load(self, eng: LLMEngine) -> Tuple[int, int]:
        """(queued+active requests, -free pages): lower routes first."""
        active = sum(1 for s in eng._slots if s.request_id is not None)
        return (len(eng._waiting) + active, -eng.allocator.free_pages)

    def _pick(self) -> LLMEngine:
        """Least-loaded replica; equal loads rotate round-robin (submission
        happens before the request lands in a replica's queue — async
        generator bodies run lazily — so load alone can't separate a burst
        of simultaneous submissions)."""
        n = len(self.replicas)
        best = min(
            range(n),
            key=lambda g: (self._load(self.replicas[g]), (g - self._rr) % n),
        )
        self._rr = (best + 1) % n
        return self.replicas[best]

    # ---------------- request API (LLMEngine-compatible) ----------------

    def generate(
        self,
        prompt_ids: List[int],
        params: SamplingParams,
        request_id: Optional[str] = None,
        adapter: Optional[str] = None,
    ) -> AsyncIterator[GenerationOutput]:
        return self._pick().generate(
            prompt_ids, params, request_id=request_id, adapter=adapter
        )

    def generate_injected(
        self,
        prompt_ids: List[int],
        params: SamplingParams,
        kv_data: np.ndarray,
        first_token: int,
        request_id: Optional[str] = None,
        adapter: Optional[str] = None,
    ) -> AsyncIterator[GenerationOutput]:
        return self._pick().generate_injected(
            prompt_ids, params, kv_data, first_token, request_id=request_id,
            adapter=adapter,
        )

    async def prefill_detached(
        self, prompt_ids: List[int], params: SamplingParams,
        adapter: Optional[str] = None,
    ) -> Tuple[int, np.ndarray]:
        return await self._pick().prefill_detached(prompt_ids, params, adapter=adapter)

    def telemetry_snapshot(self) -> dict:
        """Per-group timelines/percentiles keyed by the group's metrics
        label (GET /admin/telemetry; the groups are independent engines,
        so their latency windows must not be merged into one percentile)."""
        return {
            eng._mlabel: eng.telemetry_snapshot() for eng in self.replicas
        }

    def cancel(self, request_id: str) -> None:
        for eng in self.replicas:
            eng.cancel(request_id)


def build_engine(
    model_config: llama.LlamaConfig,
    engine_config: EngineConfig,
    tokenizer: BaseTokenizer,
    params: Optional[Any] = None,
    rng_seed: int = 0,
    lora_adapters: Optional[dict] = None,
    checkpoint_label: Optional[str] = None,
):
    """LLMEngine for dp=1, DataParallelEngine for dp>1.

    checkpoint_label is the weights identity stamped into generation
    checkpoints — pass the served model's name so resume_generation can
    refuse checkpoints captured against different weights (every engine
    defaulting to the same label would make that guard vacuous)."""
    cls = DataParallelEngine if engine_config.dp > 1 else LLMEngine
    return cls(model_config, engine_config, tokenizer, params=params,
               rng_seed=rng_seed, lora_adapters=lora_adapters,
               checkpoint_label=checkpoint_label)
