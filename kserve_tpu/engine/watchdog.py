"""Engine watchdog: gray-failure stall detection inside LLMEngine.

Every failure mode the stack handled before this module was *binary* —
the loop crashed (streams fail, clients retry), a fetch blew the
step_deadline_s wedge (liveness flips, kubelet restarts the pod), or a
drain ran (checkpoints flow).  The dominant incidents at fleet scale are
*gray*: the replica is alive, answers /state, passes liveness — and has
quietly stopped retiring tokens (a wedged fetch worker under the wedge
deadline, a thrashing page-in, a degraded host).  Nothing restarts it,
the EPP keeps routing to it, and every stream seated on it hangs until
the client deadline.

The watchdog is a clock-injectable monitor the engine drives:

- **loop heartbeat / dispatch progress** — the engine stamps
  ``note_progress()`` whenever tokens retire, a prefill chunk advances,
  or an admission seats (any forward motion).  Seated-or-queued work
  with no progress for ``suspect_after_s`` flips the state to
  ``stall_suspected``; another ``confirm_after_s`` without progress
  confirms it.
- **fetch-worker liveness** — ``fetch_started()``/``fetch_done()``
  bracket the decode hot loop's device fetch, so a confirmed stall is
  diagnosed as ``fetch_stalled`` (the worker is stuck mid-fetch) vs
  ``no_progress`` (the loop spins without retiring anything).
- **page-in/persist task stalls** — the engine's tracked async tasks
  (``_track_task`` stamps a start time) are aged every tick; one alive
  past ``task_stall_s`` is cancelled and counted — a stuck page-in
  must not pin its held request forever, and an orphaned task is
  invisible to stall accounting (the jaxlint ``task-leak`` rule guards
  the other half of that invariant).

On ``stall_confirmed`` the engine self-drains with checkpoints (the
PR 5 path): in-flight tokens are salvaged into portable
`GenerationCheckpoint`s delivered to each stream, readiness flips (the
engine refuses admission; the ``on_stall_confirmed`` hook lets the
owning server flip its ReplicaLifecycle), and the structured state rides
``scheduler_state()["watchdog"]`` to the EPP, where fleet health scoring
quarantines the replica (scheduler/health.py).  The alternative — wait
for the client deadline, the binary wedge, or kubelet — burns minutes
and loses every in-flight token.

Off by default (`EngineConfig.watchdog`): a cold-compiling CPU engine
legitimately pauses for longer than any useful stall budget.  The fleet
simulator enables it with tight budgets (stub devices never compile);
production opts in via ``KSERVE_TPU_WATCHDOG`` once the AOT cache keeps
steady-state dispatch pause-free (docs/resilience.md).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

from ..logging import logger
from ..resilience import MONOTONIC, Clock

# the closed state vocabulary exported through scheduler_state()
WATCHDOG_OK = "ok"
WATCHDOG_SUSPECTED = "stall_suspected"
WATCHDOG_CONFIRMED = "stall_confirmed"
WATCHDOG_STATES = (WATCHDOG_OK, WATCHDOG_SUSPECTED, WATCHDOG_CONFIRMED)

WATCHDOG_ENV = "KSERVE_TPU_WATCHDOG"


def watchdog_enabled_from_env(env=None) -> bool:
    env = os.environ if env is None else env
    return str(env.get(WATCHDOG_ENV, "")).strip().lower() in (
        "1", "true", "on", "yes")


@dataclass
class WatchdogConfig:
    """Stall budgets.  suspect + confirm is the detection budget: how
    long a gray replica may hold streams hostage before it self-drains
    and the fleet routes around it."""

    interval_s: float = 0.5  # tick cadence
    suspect_after_s: float = 5.0  # busy + no progress this long -> suspected
    confirm_after_s: float = 5.0  # suspected this long -> confirmed
    task_stall_s: float = 30.0  # tracked async task alive this long -> cancelled
    salvage_grace_s: float = 0.0  # self-drain budget (0 = checkpoint now)


class EngineWatchdog:
    """The monitor object.  Pure state + probes — the engine supplies
    `busy` (seated or queued work exists) and progress/fetch stamps; the
    `run()` task evaluates on the injected clock, so the fleet simulator
    drives detection deterministically in virtual time."""

    def __init__(
        self,
        config: Optional[WatchdogConfig] = None,
        clock: Clock = MONOTONIC,
        *,
        busy: Callable[[], bool],
        on_confirmed: Callable[[str], None],
        tasks: Optional[Callable[[], Iterable]] = None,
    ):
        self.config = config or WatchdogConfig()
        self._clock = clock
        self._busy = busy
        self._on_confirmed = on_confirmed
        self._tasks = tasks
        self.state = WATCHDOG_OK
        self.reason: Optional[str] = None
        self.suspected_count = 0
        self.confirmed_count = 0
        self.cancelled_tasks = 0
        self._last_progress = clock.now()
        self._suspected_at: Optional[float] = None
        self._fetch_started: Optional[float] = None
        self._task = None
        self._stopped = False

    # ---------------- engine-side stamps ----------------

    def note_progress(self) -> None:
        """Forward motion: tokens routed, a prefill chunk advanced, an
        admission seated.  Clears a suspicion; a CONFIRMED stall is
        terminal for this engine life (the self-drain already ran)."""
        self._last_progress = self._clock.now()
        if self.state == WATCHDOG_SUSPECTED:
            self.state = WATCHDOG_OK
            self.reason = None
            self._suspected_at = None

    def fetch_started(self) -> None:
        self._fetch_started = self._clock.now()

    def fetch_done(self) -> None:
        self._fetch_started = None

    # ---------------- evaluation ----------------

    def _diagnose(self, now: float) -> str:
        if (self._fetch_started is not None
                and now - self._fetch_started >= self.config.suspect_after_s):
            return "fetch_stalled"
        return "no_progress"

    def _reap_stalled_tasks(self, now: float) -> None:
        """Cancel tracked async tasks (page-in / persist write-through)
        alive past the stall budget: they are optimizations whose finally
        blocks release their held requests, so cancellation un-sticks the
        work they pinned."""
        if self._tasks is None:
            return
        for task in list(self._tasks()):
            started = getattr(task, "_wd_started_s", None)
            if (started is not None and not task.done()
                    and now - started >= self.config.task_stall_s):
                task.cancel()
                self.cancelled_tasks += 1
                logger.warning(
                    "watchdog cancelled a stalled engine task "
                    "(alive %.1fs > budget %.1fs)",
                    now - started, self.config.task_stall_s)

    def tick(self) -> None:
        now = self._clock.now()
        self._reap_stalled_tasks(now)
        if self.state == WATCHDOG_CONFIRMED:
            return  # terminal: the self-drain already fired
        if not self._busy():
            # idle is not a stall; keep the baseline fresh so the first
            # seated request starts a clean window
            self._last_progress = now
            if self.state == WATCHDOG_SUSPECTED:
                self.state = WATCHDOG_OK
                self.reason = None
                self._suspected_at = None
            return
        stalled_for = now - self._last_progress
        if stalled_for < self.config.suspect_after_s:
            if self.state == WATCHDOG_SUSPECTED:
                self.state = WATCHDOG_OK
                self.reason = None
                self._suspected_at = None
            return
        if self.state == WATCHDOG_OK:
            self.state = WATCHDOG_SUSPECTED
            self._suspected_at = now
            self.reason = self._diagnose(now)
            self.suspected_count += 1
            logger.warning(
                "watchdog: stall suspected (%s; %.2fs without progress, "
                "work seated)", self.reason, stalled_for)
            return
        if now - self._suspected_at >= self.config.confirm_after_s:
            self.state = WATCHDOG_CONFIRMED
            self.reason = self._diagnose(now)
            self.confirmed_count += 1
            logger.error(
                "watchdog: stall CONFIRMED (%s; %.2fs without progress) — "
                "flipping readiness and self-draining with checkpoints",
                self.reason, stalled_for)
            try:
                self._on_confirmed(self.reason)
            except Exception:  # noqa: BLE001 — the monitor must survive a
                # broken handler; the state is already exported via /state
                logger.exception("watchdog on_confirmed handler failed")

    def snapshot(self) -> dict:
        """The structured block scheduler_state() exports (consumed by
        the EPP's fleet health scoring and /state observers)."""
        return {
            "state": self.state,
            "reason": self.reason,
            "suspected_total": self.suspected_count,
            "confirmed_total": self.confirmed_count,
            "cancelled_tasks_total": self.cancelled_tasks,
        }

    # ---------------- the tick task ----------------

    def start(self) -> None:
        import asyncio

        if self._task is None or self._task.done():
            self._stopped = False
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        while not self._stopped:
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a monitor crash must never
                # take the engine with it (and must keep monitoring)
                logger.exception("watchdog tick failed")
            await self._clock.sleep(self.config.interval_s)

    def stop(self) -> None:
        """Cancel the tick task.  Also what lets the simulator drain its
        timer heap at teardown — a live watchdog re-arms a timer every
        interval forever."""
        self._stopped = True
        if self._task is not None and not self._task.done():
            self._task.cancel()
        self._task = None
