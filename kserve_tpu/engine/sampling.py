"""Batched token sampling, fully inside jit.

Per-sequence parameters travel as a struct-of-arrays (`SamplingParams`
batch) so one compiled program serves any mix of greedy/temperature/top-k/
top-p/min-p requests — no recompiles per request.

Role parity: vLLM's Sampler (the reference delegates sampling to vLLM).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class SamplingParams:
    """Host-side per-request sampling config."""

    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    min_p: float = 0.0
    repetition_penalty: float = 1.0
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    max_tokens: int = 16
    min_tokens: int = 0
    ignore_eos: bool = False
    stop: Optional[List[str]] = None
    seed: Optional[int] = None
    logprobs: Optional[int] = None

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0

    @property
    def has_penalties(self) -> bool:
        """True when this request needs the penalized decode path (which
        carries a [B, V] output-count array; the fast path skips it)."""
        return (
            self.repetition_penalty != 1.0
            or self.frequency_penalty != 0.0
            or self.presence_penalty != 0.0
        )


@jax.tree_util.register_dataclass
@dataclass
class SamplingState:
    """Device-side struct-of-arrays for a batch of B slots (a jit-traversable
    pytree)."""

    temperature: jnp.ndarray  # [B] f32 (0 => greedy)
    top_p: jnp.ndarray  # [B] f32
    top_k: jnp.ndarray  # [B] i32 (0 => off)
    min_p: jnp.ndarray  # [B] f32
    seed: jnp.ndarray  # [B] i32 (-1 => draw from the shared batch rng)
    repetition_penalty: jnp.ndarray  # [B] f32 (1.0 => off)
    frequency_penalty: jnp.ndarray  # [B] f32 (0.0 => off)
    presence_penalty: jnp.ndarray  # [B] f32 (0.0 => off)

    @staticmethod
    def from_params(params_list: List[SamplingParams]) -> "SamplingState":
        return SamplingState(
            temperature=jnp.asarray([p.temperature for p in params_list], jnp.float32),
            top_p=jnp.asarray([p.top_p for p in params_list], jnp.float32),
            top_k=jnp.asarray([p.top_k for p in params_list], jnp.int32),
            min_p=jnp.asarray([p.min_p for p in params_list], jnp.float32),
            seed=jnp.asarray(
                [p.seed if p.seed is not None else -1 for p in params_list], jnp.int32
            ),
            repetition_penalty=jnp.asarray(
                [p.repetition_penalty for p in params_list], jnp.float32
            ),
            frequency_penalty=jnp.asarray(
                [p.frequency_penalty for p in params_list], jnp.float32
            ),
            presence_penalty=jnp.asarray(
                [p.presence_penalty for p in params_list], jnp.float32
            ),
        )

    @staticmethod
    def defaults(batch: int) -> "SamplingState":
        return SamplingState(
            temperature=jnp.ones((batch,), jnp.float32),
            top_p=jnp.ones((batch,), jnp.float32),
            top_k=jnp.zeros((batch,), jnp.int32),
            min_p=jnp.zeros((batch,), jnp.float32),
            seed=jnp.full((batch,), -1, jnp.int32),
            repetition_penalty=jnp.ones((batch,), jnp.float32),
            frequency_penalty=jnp.zeros((batch,), jnp.float32),
            presence_penalty=jnp.zeros((batch,), jnp.float32),
        )


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] f32
    state: SamplingState,
    rng: jax.Array,
    counters: Optional[jnp.ndarray] = None,  # [B] i32: tokens generated so far
) -> jnp.ndarray:
    """Returns [B] sampled token ids.  temperature==0 rows are greedy.
    Rows with state.seed >= 0 draw from their own PRNG stream
    (PRNGKey(seed) folded with the row's token counter) so a client-supplied
    seed reproduces output regardless of batching."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(state.temperature, 1e-6)[:, None]
    scaled = logits / temp

    # top-k: mask logits below the k-th largest (k==0 disables)
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]  # desc
    k = jnp.clip(state.top_k, 0, V)
    kth_idx = jnp.clip(k - 1, 0, V - 1)
    kth_val = jnp.take_along_axis(sorted_logits, kth_idx[:, None], axis=1)
    topk_mask = jnp.where(
        (state.top_k > 0)[:, None], scaled < kth_val, jnp.zeros_like(scaled, bool)
    )
    scaled = jnp.where(topk_mask, -jnp.inf, scaled)

    # top-p (nucleus): keep smallest prefix of sorted probs with cumsum >= p
    probs_sorted = jax.nn.softmax(jnp.sort(scaled, axis=-1)[:, ::-1], axis=-1)
    cumprobs = jnp.cumsum(probs_sorted, axis=-1)
    cutoff_count = jnp.sum(cumprobs - probs_sorted < state.top_p[:, None], axis=-1)
    cutoff_idx = jnp.clip(cutoff_count - 1, 0, V - 1)
    sorted_again = jnp.sort(scaled, axis=-1)[:, ::-1]
    cutoff_val = jnp.take_along_axis(sorted_again, cutoff_idx[:, None], axis=1)
    topp_mask = jnp.where(
        (state.top_p < 1.0)[:, None], scaled < cutoff_val, jnp.zeros_like(scaled, bool)
    )
    scaled = jnp.where(topp_mask, -jnp.inf, scaled)

    # min-p: drop tokens with prob < min_p * max_prob
    probs = jax.nn.softmax(scaled, axis=-1)
    max_prob = probs.max(axis=-1, keepdims=True)
    minp_mask = jnp.where(
        (state.min_p > 0.0)[:, None],
        probs < state.min_p[:, None] * max_prob,
        jnp.zeros_like(scaled, bool),
    )
    scaled = jnp.where(minp_mask, -jnp.inf, scaled)

    if counters is None:
        counters = jnp.zeros((B,), jnp.int32)
    batch_keys = jax.random.split(rng, B)
    seeded_keys = jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.PRNGKey(s), c)
    )(jnp.maximum(state.seed, 0), counters)
    keys = jnp.where((state.seed >= 0)[:, None], seeded_keys, batch_keys)
    sampled = jax.vmap(lambda k, row: jax.random.categorical(k, row))(keys, scaled)
    return jnp.where(state.temperature <= 0.0, greedy, sampled).astype(jnp.int32)


def compute_logprobs(
    logits: jnp.ndarray,  # [B, V] f32 — post-penalty, pre-temperature
    sampled: jnp.ndarray,  # [B] i32 sampled token ids
    k: int,  # static top-k width (engine config max_logprobs)
) -> tuple:
    """Log-probabilities for OpenAI `logprobs` surfaces.

    Computed from the post-penalty, pre-temperature/filter logits: reported
    logprobs describe the model's distribution, not the sampling filters
    (matches vLLM's default behaviour the reference inherits through
    `huggingfaceserver/vllm/vllm_model.py`).

    Returns (lp [B], top_vals [B, k], top_ids [B, k])."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    lp = jnp.take_along_axis(logp, sampled[:, None].astype(jnp.int32), axis=1)[:, 0]
    top_vals, top_ids = jax.lax.top_k(logp, k)
    return lp, top_vals, top_ids.astype(jnp.int32)


def apply_penalties(
    logits: jnp.ndarray,  # [B, V]
    output_counts: jnp.ndarray,  # [B, V] int32 — counts of generated tokens
    repetition_penalty: jnp.ndarray,  # [B]
    frequency_penalty: jnp.ndarray,  # [B]
    presence_penalty: jnp.ndarray,  # [B]
    prompt_mask: Optional[jnp.ndarray] = None,  # [B, V] bool — in-prompt tokens
) -> jnp.ndarray:
    """vLLM-parity penalty semantics: repetition_penalty applies to tokens
    seen in the prompt OR the output; frequency/presence (OpenAI) apply to
    generated output only."""
    seen_out = output_counts > 0
    seen_rep = seen_out if prompt_mask is None else (seen_out | prompt_mask)
    rp = repetition_penalty[:, None]
    penalized = jnp.where(logits > 0, logits / rp, logits * rp)
    logits = jnp.where(seen_rep, penalized, logits)
    logits = logits - frequency_penalty[:, None] * output_counts
    logits = logits - presence_penalty[:, None] * seen_out.astype(logits.dtype)
    return logits
