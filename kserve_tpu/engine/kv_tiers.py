"""Compatibility shim: the tiered KV offload store moved to
``kserve_tpu.kvstore`` (docs/kv_hierarchy.md), where it is one layer of
the hierarchical KV page store (host/disk tiers under the HBM prefix
cache, above the content-addressed persistent prefix layer) and is
clock-injectable for the fleet simulator.

Import from ``kserve_tpu.kvstore`` in new code."""

from ..kvstore.tiers import (  # noqa: F401 — re-exported public surface
    KVTierStore,
    Payload,
    TierConfig,
    payload_nbytes,
)

__all__ = ["KVTierStore", "Payload", "TierConfig", "payload_nbytes"]
