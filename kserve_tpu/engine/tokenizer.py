"""Tokenizer facade: HuggingFace tokenizers when a local artifact exists,
byte-level fallback otherwise (tests/bench run with zero egress).

Incremental detokenization follows the streaming rule: only emit text once
it is prefix-stable (no dangling UTF-8/byte-pair at the boundary).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from ..logging import logger


class BaseTokenizer:
    eos_token_id: int = -1
    bos_token_id: int = -1

    @property
    def vocab_size(self) -> int:
        raise NotImplementedError

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        raise NotImplementedError

    def decode(self, ids: Sequence[int]) -> str:
        raise NotImplementedError

    def apply_chat_template(self, messages: List[dict], add_generation_prompt: bool = True, **kwargs) -> str:
        """Fallback chat template (chatml-ish); HF tokenizers override."""
        parts = []
        for m in messages:
            role = m.get("role", "user")
            content = m.get("content") or ""
            parts.append(f"<|{role}|>\n{content}\n")
        if add_generation_prompt:
            parts.append("<|assistant|>\n")
        return "".join(parts)


class ByteTokenizer(BaseTokenizer):
    """256 byte tokens + BOS/EOS/PAD; reversible on arbitrary text."""

    PAD = 256
    BOS = 257
    EOS = 258

    def __init__(self, vocab_size: int = 512):
        self._vocab_size = max(vocab_size, 259)
        self.bos_token_id = self.BOS
        self.eos_token_id = self.EOS

    @property
    def vocab_size(self) -> int:
        return self._vocab_size

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.BOS] if add_bos else []) + ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer(BaseTokenizer):
    """tokenizers-backed (tokenizer.json) — no sentencepiece in this image."""

    def __init__(self, model_dir: str):
        from tokenizers import Tokenizer

        path = os.path.join(model_dir, "tokenizer.json")
        self._tok = Tokenizer.from_file(path)
        self.eos_token_id = -1
        self.bos_token_id = -1
        self._chat_template = None
        self._template_warned = False
        # read special tokens + chat template from tokenizer_config.json
        cfg_path = os.path.join(model_dir, "tokenizer_config.json")
        if os.path.exists(cfg_path):
            import json

            with open(cfg_path) as f:
                cfg = json.load(f)
            self._chat_template = cfg.get("chat_template")
            eos = cfg.get("eos_token")
            bos = cfg.get("bos_token")
            if isinstance(eos, dict):
                eos = eos.get("content")
            if isinstance(bos, dict):
                bos = bos.get("content")
            if eos:
                self.eos_token_id = self._tok.token_to_id(eos) or -1
            if bos:
                self.bos_token_id = self._tok.token_to_id(bos) or -1

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        if add_bos and self.bos_token_id >= 0:
            return [self.bos_token_id] + ids
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def apply_chat_template(self, messages, add_generation_prompt=True, **kwargs) -> str:
        if self._chat_template:
            try:
                import jinja2

                env = jinja2.Environment()
                tmpl = env.from_string(self._chat_template)
                return tmpl.render(
                    messages=messages,
                    add_generation_prompt=add_generation_prompt,
                    bos_token="",
                    eos_token="",
                    **kwargs,
                )
            except Exception:  # noqa: BLE001 — template syntax varies by model
                # a broken template fails identically on every request:
                # warn once with the traceback, then fall back silently
                # (this runs per chat request — no hot-path log spam)
                if not self._template_warned:
                    self._template_warned = True
                    logger.warning(
                        "chat template render failed; falling back to the "
                        "default template", exc_info=True)
        return super().apply_chat_template(messages, add_generation_prompt, **kwargs)


def load_tokenizer(model_dir: Optional[str], vocab_size: int = 512) -> BaseTokenizer:
    if model_dir and os.path.exists(os.path.join(model_dir, "tokenizer.json")):
        return HFTokenizer(model_dir)
    return ByteTokenizer(vocab_size)


class IncrementalDetokenizer:
    """Streams prefix-stable text deltas from a growing id sequence."""

    def __init__(self, tokenizer: BaseTokenizer):
        self._tok = tokenizer
        self._ids: List[int] = []
        self._emitted = ""

    def push(self, token_id: int) -> str:
        self._ids.append(token_id)
        text = self._tok.decode(self._ids)
        # hold back when the tail is an incomplete byte sequence
        if text.endswith("�"):
            return ""
        delta = text[len(self._emitted):]
        self._emitted = text
        return delta

    @property
    def text(self) -> str:
        return self._emitted
