"""Continuous-batching LLM engine on JAX/XLA.

The role vLLM's AsyncLLM plays for the reference huggingfaceserver
(python/huggingfaceserver/vllm/vllm_model.py:55, start_engine :83), rebuilt
TPU-first:

- fixed decode slots (static shapes: one compiled decode program, reused
  forever); prompts prefill into bucketed-length compiled programs
- paged KV in HBM (engine/kvcache.py), pages allocated incrementally as
  sequences grow, newest slot preempted back to the queue on exhaustion
- sampling fully on device (engine/sampling.py), per-slot params as arrays
- TP via the ("data","model") mesh (parallel/sharding.py) — weights, KV
  pages and logits sharded; XLA inserts ICI collectives
- async streaming: each request owns an asyncio queue fed by the decode loop

Host<->device traffic per step is one [B] token fetch + tiny control arrays.

Module layout (the r4 review asked for the scheduler and the device-step
code to live apart):
- engine/types.py     EngineConfig + runtime dataclasses + deadline fetcher
- engine/compiled.py  every jitted device program (prefill/decode/inject)
- engine/prefix_cache.py  shared-prefix page cache
- this file           admission, slots, chunked prefill, preemption,
                      offload, P/D, the run loop — the host-side scheduler
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..logging import logger
from ..metrics import (
    ENGINE_BATCH_OCCUPANCY,
    ENGINE_KV_DISK_BYTES,
    ENGINE_KV_OFFLOAD_BYTES,
    ENGINE_KV_PAGES_FREE,
    ENGINE_PREEMPTIONS,
    ENGINE_PREFILL_CHUNK_DURATION,
    ENGINE_QUEUE_DEPTH,
    ENGINE_STEP_BATCH_COMPOSITION,
    ENGINE_STEP_DURATION,
    ENGINE_WEDGED,
    GENERATED_TOKENS,
    PROMPT_TOKENS,
    observe_request_timeline,
    observe_startup_phase,
)
from ..metrics import (
    DEADLINE_REJECTED,
    GENERATION_CHECKPOINTS,
    GENERATION_RESUMES,
    KV_PAGEIN_SECONDS,
    KV_PREFIX_HIT_TOKENS,
    SPEC_TOKENS,
    TOKENS_SALVAGED,
)
from ..lifecycle.checkpoint import GenerationCheckpoint, GenerationPreempted
from ..lifecycle.state import ReplicaDrainingError
from ..models import llama
from ..observability import RequestTimeline, TimelineRecorder, emit_timeline_spans
from ..parallel import sharding as shd
from ..resilience import (
    MONOTONIC,
    Clock,
    Deadline,
    DeadlineExceededError,
    ReplicaCrashError,
    current_deadline,
)
from .kvcache import (
    KVCacheConfig,
    PageAllocator,
    init_kv_pages,
    init_kv_scales,
    pages_needed,
)
from .sampling import SamplingParams, SamplingState
from .tokenizer import BaseTokenizer, IncrementalDetokenizer


from .types import (  # noqa: F401 — re-exported: the public engine surface
    EngineConfig,
    EngineWedgedError,
    GenerationOutput,
    _DeadlineFetcher,
    _QueuedRequest,
    _Slot,
)


class LLMEngine:
    """Drive with `await engine.start()`, submit with `generate()`."""

    def __init__(
        self,
        model_config: llama.LlamaConfig,
        engine_config: EngineConfig,
        tokenizer: BaseTokenizer,
        params: Optional[Any] = None,
        rng_seed: int = 0,
        devices: Optional[list] = None,
        metrics_label: str = "engine",
        checkpoint_label: Optional[str] = None,  # weights identity for resume
        lora_adapters: Optional[Dict[str, str]] = None,
        lora_stacked=None,  # (adapter_ids, per-layer stacks) pre-loaded
        clock: Optional[Clock] = None,  # telemetry clock (FakeClock in chaos tests)
        # the fleet-simulator stub seam (kserve_tpu/sim): an object with
        # the CompiledPrograms attribute surface replaces the jitted device
        # programs, and a fetch/fetch_async/close duck of _DeadlineFetcher
        # replaces the daemon fetch worker — so admission, batching,
        # preemption, drain and checkpointing all run the REAL scheduler
        # against a cycle-accurate stub device, deterministically on the
        # event-loop thread (no fetch-thread scheduling jitter)
        compiled_programs=None,
        fetcher=None,
    ):
        if engine_config.dp > 1:
            raise ValueError(
                "LLMEngine is a single data-parallel replica (dp=1); use "
                "engine.dp.DataParallelEngine for dp>1 — decode batches are "
                "independent, so DP runs as disjoint replicas, not a lockstep "
                "mesh axis"
            )
        self.model_config = model_config
        # startup-phase accounting (docs/coldstart.md): wall seconds per
        # phase, observed into engine_startup_seconds once the engine is
        # serving.  perf_counter (not the injectable telemetry clock) —
        # startup is host wall time, and the sim replica injects stub
        # programs so this path never runs under virtual time.
        self._construct_t0 = time.perf_counter()
        self.startup_phases: Dict[str, float] = {}
        # wall seconds spent BEFORE engine construction that belong to
        # this replica's startup (the server's checkpoint read) — folded
        # into the ready phase so ready stays the true total and never
        # reads smaller than the weights phase it contains
        self.startup_external_s = 0.0
        self._startup_recorded = False
        # own copy: prefix_cache=None resolves below, and resolving in the
        # caller's dataclass would make a reused config look explicitly set
        engine_config = dataclasses.replace(engine_config)
        self.config = engine_config
        self.tokenizer = tokenizer
        if tokenizer is not None and tokenizer.vocab_size > model_config.vocab_size:
            # loud, not silent: ids past the embedding table clamp inside
            # jit (garbage lookups) and crash the host-side prompt mask
            raise ValueError(
                f"tokenizer vocab ({tokenizer.vocab_size}) exceeds model "
                f"vocab ({model_config.vocab_size}); ids past the embedding "
                "table would silently clamp under jit")
        self._mlabel = metrics_label
        # every lifecycle stamp goes through this injectable clock, so the
        # FakeClock chaos suite asserts exact TTFT/ITL/queue-wait values
        # (docs/observability.md); real time is the production default
        self._clock = clock or MONOTONIC
        # bounded ring of finished timelines + rolling percentile windows
        # behind GET /admin/telemetry
        self.telemetry = TimelineRecorder()
        # checkpoints carry this as model_name; resume_generation rejects a
        # mismatch.  Distinct from the metrics label so DP sub-engines
        # (engine-dp0, engine-dp1, ...) share one weights identity and a
        # checkpoint from any of them resumes on any other
        self._ckpt_label = checkpoint_label or metrics_label
        shd.validate_tp(model_config, engine_config.tp)
        if engine_config.sp > 1 and (
                model_config.sliding_window > 0
                or model_config.query_pre_attn_scalar is not None):
            raise NotImplementedError(
                "sp>1 (ring-attention prefill) does not support sliding "
                "windows or attention-scale overrides yet")
        if engine_config.sp > 1:
            bad = [b for b in engine_config.prefill_buckets if b % engine_config.sp]
            if bad:
                raise ValueError(
                    f"prefill buckets {bad} not divisible by sp={engine_config.sp} "
                    "(ring-attention prefill shards the prompt dim over seq)"
                )
        if engine_config.pp > 1:
            # supported composition today: pp x tp (x dp via disjoint
            # replica meshes).  Everything else raises loudly here rather
            # than inside a jitted trace.
            bad = []
            if engine_config.sp > 1:
                bad.append("sp")
            if bad:
                raise NotImplementedError(
                    f"pp>1 does not compose with {bad} yet")
            if model_config.n_layers % engine_config.pp != 0:
                raise ValueError(
                    f"n_layers={model_config.n_layers} not divisible by "
                    f"pp={engine_config.pp}")
        if engine_config.prefix_cache is None:
            engine_config.prefix_cache = True
        self.mesh = shd.create_mesh(
            tp=engine_config.tp, dp=1, sp=engine_config.sp,
            pp=engine_config.pp, devices=devices,
        )
        self._base_rng = jax.random.PRNGKey(rng_seed)
        self._step_counter = 0

        if engine_config.weight_quant not in ("none", "int8"):
            raise ValueError(f"weight_quant={engine_config.weight_quant!r}")
        _weights_t0 = time.perf_counter()
        if params is None:
            params = llama.init_params(
                model_config, jax.random.PRNGKey(1),
                weight_quant=engine_config.weight_quant,
            )
        elif engine_config.weight_quant == "int8":
            from ..models.quant import is_quantized, quantize_params

            if not any(
                is_quantized(v) for v in params["layers"][0].values()
                if isinstance(v, dict)
            ):
                params = quantize_params(params, model_config)
        # multi-adapter LoRA stacks load BEFORE any pp stacking so the
        # adapter tensors ride the same stage-sharded layer pytree
        self.adapter_ids: Dict[str, int] = {}
        lora_layer_stacks = None
        if lora_adapters or lora_stacked:
            if model_config.n_experts > 0:
                raise NotImplementedError("LoRA over MoE layers is not supported yet")
            from ..models import lora as lora_mod

            if lora_stacked is not None:
                self.adapter_ids, lora_layer_stacks = lora_stacked
            else:
                self.adapter_ids, lora_layer_stacks = lora_mod.stack_adapters(
                    lora_adapters, model_config.n_layers, dtype=model_config.dtype
                )
            logger.info("LoRA adapters loaded: %s", sorted(self.adapter_ids))
        if engine_config.pp > 1:
            if lora_layer_stacks is not None:
                # the stage-sharded stack needs UNIFORM adapter coverage:
                # every layer must carry the same projection set or the
                # layer pytrees cannot stack
                shape_sets = {
                    tuple(sorted(
                        (proj, tuple(t["A"].shape), tuple(t["B"].shape))
                        for proj, t in stack.items()
                    ))
                    for stack in lora_layer_stacks
                }
                if len(shape_sets) != 1:
                    # covers both ragged projection sets AND layer-varying
                    # ranks (PEFT rank_pattern) — jnp.stack would otherwise
                    # die with an opaque shape error
                    raise NotImplementedError(
                        "pp>1 requires every layer to share one LoRA "
                        "projection set and rank; got differing per-layer "
                        f"shapes: {sorted(shape_sets)[:2]}"
                    )
                for layer, stack in zip(params["layers"], lora_layer_stacks):
                    layer["lora"] = stack
            # stage-sharded layers: the per-layer list stacks into one
            # pytree with a leading L axis placed on the pipe mesh axis,
            # each leaf keeping its megatron TP spec on the trailing dims;
            # embed/final_norm/lm_head stay pipe-replicated with their
            # usual TP shardings
            params = llama.stack_layer_params(params)
            all_flat = shd.param_pspecs(model_config)
            flat_specs = shd.expand_quant_specs(
                {k: v for k, v in params.items() if k != "layers"},
                {k: v for k, v in all_flat.items() if k != "layers"},
            )
            layer_specs = shd.stacked_layer_pspecs(
                model_config, params["layers"],
                layer_specs=all_flat["layers"][0])
            if lora_layer_stacks is not None:
                layer_specs["lora"] = jax.tree.map(
                    lambda s: jax.sharding.PartitionSpec(shd.PIPE_AXIS, *s),
                    lora_mod.lora_pspecs(lora_layer_stacks[0]),
                    is_leaf=lambda x: isinstance(
                        x, jax.sharding.PartitionSpec),
                )
            specs = dict(flat_specs, layers=layer_specs)
            self.params = jax.tree.map(
                lambda arr, spec: jax.device_put(
                    arr, shd.named(self.mesh, spec)),
                params, specs,
                is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
            )
        else:
            self.params = shd.shard_params(params, model_config, self.mesh)

        # multi-adapter LoRA (pp==1 path): stacked [n_adapters, ...]
        # tensors attached per layer; a per-slot id selects at runtime
        # (models/lora.py).  Under pp the stacks were folded into the
        # stage-sharded pytree above.
        if lora_layer_stacks is not None and engine_config.pp == 1:
            for i, stack in enumerate(lora_layer_stacks):
                if not stack:
                    continue
                lspecs = lora_mod.lora_pspecs(stack)
                self.params["layers"][i]["lora"] = jax.tree.map(
                    lambda arr, spec: jax.device_put(
                        arr, jax.sharding.NamedSharding(self.mesh, spec)
                    ),
                    stack,
                    lspecs,
                    is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
                )

        # device placement done: everything from the quantize/init above
        # through the LoRA stacks landing on device is the weights phase
        self.startup_phases["weights"] = time.perf_counter() - _weights_t0

        cache_cfg = KVCacheConfig(
            n_layers=model_config.n_layers,
            n_kv_heads=model_config.n_kv_heads,
            head_dim=model_config.head_dim,
            page_size=engine_config.page_size,
            num_pages=engine_config.num_pages,
            max_pages_per_seq=engine_config.max_pages_per_seq,
            dtype=engine_config.dtype,
        )
        self.cache_config = cache_cfg
        if engine_config.kv_quant not in ("none", "int8"):
            raise ValueError(
                f"unknown kv_quant {engine_config.kv_quant!r}; supported: none, int8"
            )
        stacked_shape = (
            model_config.n_layers, cache_cfg.num_pages, 2,
            cache_cfg.n_kv_heads, cache_cfg.page_size, cache_cfg.head_dim,
        )
        if engine_config.kv_quant == "int8":
            if engine_config.use_pallas:
                # fail at init, not inside the jitted decode trace where the
                # error would kill the engine loop for all traffic
                raise NotImplementedError(
                    "the pallas kernel does not read int8 KV pages yet; "
                    "use kv_quant=int8 with use_pallas None/False"
                )
            if engine_config.pp > 1:
                # stacked quantized cache: an (int8 pages, scales) tuple,
                # layer axis on pipe, KV heads on model
                self.kv_pages = (
                    jax.device_put(
                        jnp.zeros(stacked_shape, jnp.int8),
                        shd.named(self.mesh, shd.stacked_kv_pages_pspec())),
                    jax.device_put(
                        jnp.ones(stacked_shape[:-1], jnp.float32),
                        shd.named(self.mesh, jax.sharding.PartitionSpec(
                            shd.PIPE_AXIS, None, None, shd.MODEL_AXIS,
                            None))),
                )
            else:
                pages = shd.shard_kv_pages(
                    init_kv_pages(
                        dataclasses.replace(cache_cfg, dtype="int8")),
                    self.mesh
                )
                scale_sharding = shd.named_canonical(
                    self.mesh,
                    jax.sharding.PartitionSpec(None, None, shd.MODEL_AXIS, None),
                )
                scales = init_kv_scales(cache_cfg, scale_sharding)
                self.kv_pages = list(zip(pages, scales))
        elif engine_config.pp > 1:
            # pipeline mode: one stacked [L, ...] array, layer axis on pipe
            # NOT canonicalized (unlike the flat cache): the staged pp
            # shard_map needs the explicit full-rank spec on this jax, and
            # pp keeps its benign one-time settle retrace anyway
            self.kv_pages = jax.device_put(
                jnp.zeros(stacked_shape, jnp.dtype(cache_cfg.dtype)),
                shd.named(self.mesh, shd.stacked_kv_pages_pspec()),
            )
        else:
            self.kv_pages = shd.shard_kv_pages(init_kv_pages(cache_cfg), self.mesh)
        self.allocator = PageAllocator(cache_cfg.num_pages)

        B = engine_config.max_batch_size
        self._slots: List[_Slot] = [_Slot() for _ in range(B)]
        self._waiting: List[_QueuedRequest] = []
        self._wake = asyncio.Event()
        self._detached_lock = asyncio.Lock()
        self._detached_queue: List[tuple] = []
        self._detached_task: Optional[asyncio.Task] = None
        self._stopped = False
        # lifecycle (kserve_tpu/lifecycle): once draining, new admission is
        # refused (503 upstream) and drain() checkpoints whatever the drain
        # budget cannot finish; resume_count/checkpointed_count are the
        # test/observability counters behind the prometheus metrics
        self._draining = False
        self.resume_count = 0
        self.checkpointed_count = 0
        # requests popped from _waiting by an in-flight _admit_batch; the
        # crash handler fails these too (they are otherwise unreachable)
        self._admitting: List[tuple] = []
        self._task: Optional[asyncio.Task] = None
        self._pipeline_busy = False
        self._deferred_free: List[int] = []
        # hierarchical KV store (kserve_tpu/kvstore, docs/kv_hierarchy.md):
        # host-RAM/disk tiers take preempted-sequence spills AND demoted
        # prefix-cache pages; the content-addressed persistent layer keeps
        # prefix pages across restarts.  Clock-injectable so sim spill
        # traffic stays byte-identical per seed.
        self._kv_store = None
        if engine_config.kv_offload == "host" or engine_config.kv_persist_dir:
            from ..kvstore import HierarchicalKVStore, KVStoreConfig

            self._kv_store = HierarchicalKVStore(KVStoreConfig(
                host_bytes=int(engine_config.kv_offload_gib * (1 << 30)),
                disk_bytes=int(engine_config.kv_offload_disk_gib * (1 << 30)),
                disk_dir=engine_config.kv_offload_dir,
                policy=engine_config.kv_offload_policy,
                persist_dir=engine_config.kv_persist_dir,
            ), clock=self._clock)
        # async prefix page-in / persist write-through bookkeeping: tasks
        # are tracked so stop() can cancel them, and in-flight persist
        # digests are deduplicated across admission passes
        self._pagein_tasks: set = set()
        self._persisting: set = set()
        # cross-replica page fabric (kvstore/peer.py): set_peer_client
        # attaches the verified peer fetch path; None = local tiers only
        self._peer_client = None
        self.preemption_count = 0
        # wedge detection: device fetches run on a DAEMON worker with a
        # deadline; a timeout flips `wedged` (liveness).  Daemon, not a
        # ThreadPoolExecutor: its non-daemon workers are joined at
        # interpreter exit, so one stuck fetch would hang process shutdown —
        # the exact failure mode this exists to escape.  The simulator
        # injects a synchronous fetcher instead (thread handoff order is
        # the one nondeterminism a deterministic fleet sim cannot keep).
        self._fetcher = fetcher if fetcher is not None else _DeadlineFetcher()
        self._wedged = False
        # chaos seam (resilience/faults.py): a FaultPlan whose "wedge"
        # specs targeting "engine.fetch" the device-fetch path honors
        self.fault_plan = None
        # gray-failure watchdog (engine/watchdog.py, docs/resilience.md):
        # seated-or-queued work with no forward motion past the stall
        # budget flips readiness and self-drains with checkpoints.  The
        # owning server (or SimReplica) hooks on_stall_confirmed to flip
        # its ReplicaLifecycle so readiness probes go red too.
        self._watchdog = None
        self.on_stall_confirmed = None
        if engine_config.watchdog:
            from .watchdog import EngineWatchdog, WatchdogConfig

            self._watchdog = EngineWatchdog(
                WatchdogConfig(
                    interval_s=engine_config.watchdog_interval_s,
                    suspect_after_s=engine_config.watchdog_suspect_s,
                    confirm_after_s=engine_config.watchdog_confirm_s,
                    task_stall_s=engine_config.watchdog_task_stall_s,
                    salvage_grace_s=engine_config.watchdog_salvage_grace_s,
                ),
                clock=self._clock,
                busy=self._has_live_work,
                on_confirmed=self._stall_confirmed,
                tasks=lambda: self._pagein_tasks,
            )
        # prefix cache (engine/prefix_cache.py): chained page key -> page
        # id, LRU-evicted on pressure; holds one allocator ref per page.
        # Evictions are offered to the hierarchical store's demote seam
        # instead of being dropped (HBM -> host RAM -> disk -> persist).
        from .prefix_cache import PrefixCache

        self._prefix_cache = PrefixCache(
            engine_config.page_size, engine_config.prefix_cache,
            self.allocator,
            demote_cb=self._demote_prefix_pages,
        )
        # device-resident [B, V] penalty state; row-level updates on batch
        # composition changes (dirty_rows None => full rebuild needed)
        self._penalty_counts = None
        self._penalty_prompt = None
        self._penalty_dirty_rows: Optional[set] = None
        # deterministic admission stamp: a strictly-increasing sequence the
        # preemption policy orders victims by (newest-first).  A sequence,
        # not a wall/virtual clock read — two admissions inside one virtual
        # instant must still have a defined age order or the simulator's
        # preemption choice (and therefore its whole report) would hinge on
        # a tie-break
        self._admission_seq = 0.0
        # packed-slice alignment: the Pallas ragged kernel walks BQ-token
        # blocks that each belong to ONE sequence, so slices must start at
        # BQ multiples wherever the kernel can be selected; the XLA
        # reference packs densely
        from ..ops.attention import _should_use_ragged_pallas
        from ..ops.pallas_paged_attention import RAGGED_BQ

        kernel_possible = engine_config.use_pallas or (
            engine_config.use_pallas is None
            and _should_use_ragged_pallas(
                model_config.head_dim, jax.default_backend())
        )
        self._ragged_align = RAGGED_BQ if kernel_possible else 1
        # unified ragged program (docs/kernels.md): resolve the use_ragged
        # knob against what the topology supports.  A pure-decode mixed
        # step packs max_batch_size aligned single-token slices, so the
        # largest prefill bucket must cover the batch.
        mixed_ok = (
            engine_config.pp == 1
            and engine_config.sp == 1
            and engine_config.max_batch_size * self._ragged_align
            <= engine_config.prefill_buckets[-1]
        )
        if engine_config.use_ragged and not mixed_ok:
            raise NotImplementedError(
                "use_ragged=True requires pp==1, sp==1 and max_batch_size "
                "(x the kernel's block alignment) <= the largest prefill "
                "bucket; set use_ragged=None/False for this topology"
            )
        self._use_mixed = (
            mixed_ok if engine_config.use_ragged is None
            else bool(engine_config.use_ragged)
        )
        # speculative decoding + dense decode packing (docs/kernels.md):
        # spec_decode_k=None keeps today's mixed-only behavior; an int K
        # adds the decode-only `mixed_decode` program — dense (K+1)-token
        # slices, on-device draft/verify/accept, depth-2 chaining
        spec_k = engine_config.spec_decode_k
        if spec_k is not None:
            if spec_k < 0:
                raise ValueError(
                    f"spec_decode_k must be >= 0, got {spec_k}")
            if not self._use_mixed:
                raise NotImplementedError(
                    "spec_decode_k requires the unified ragged (mixed) "
                    "path; it does not compose with use_ragged=False, "
                    "pp>1 or sp>1")
            from ..ops.attention import dense_stride_for

            stride = dense_stride_for(spec_k + 1, self._ragged_align)
            if (self._ragged_align > 1
                    and (engine_config.max_batch_size * stride)
                    % self._ragged_align):
                raise ValueError(
                    "spec_decode_k on the Pallas kernel path needs "
                    "max_batch_size * padded-slice stride "
                    f"({engine_config.max_batch_size}*{stride}) to be a "
                    f"multiple of the {self._ragged_align}-token block")
            # the [B, V] draft table shards lane rows over the model axis
            # (sharding.draft_table_pspec) — an indivisible batch would
            # only surface as a JAX sharding error at the first dense
            # dispatch, mid-serving
            tp_size = self.mesh.shape[shd.MODEL_AXIS]
            if engine_config.max_batch_size % tp_size:
                raise ValueError(
                    "spec_decode_k needs max_batch_size "
                    f"({engine_config.max_batch_size}) divisible by the "
                    f"tensor-parallel mesh axis ({tp_size}): the draft "
                    "table shards lane rows over it")
        self._spec_k = spec_k
        # worst-case per-lane advance of one dispatch: every round accepts
        # all K drafts plus the bonus token.  Page growth and the
        # predictable-finish chain gate both plan against it.
        self._max_step_advance = engine_config.steps_per_sync * (
            (spec_k or 0) + 1 if spec_k is not None else 1)
        # hard per-lane kv ceiling: a dense round needs a full (K+1)-token
        # write window, so a lane within K tokens of this cap can NEVER
        # run another dense round — _step_mixed hands such batches to the
        # plain mixed path (1 token/step, same tokens) for the final
        # stretch instead of livelocking on capacity-skipped dispatches
        self._dense_lane_cap = min(
            engine_config.max_model_len,
            engine_config.max_pages_per_seq * engine_config.page_size)
        # per-lane bigram draft table ([B, V] int32 on device, -1 = unseen)
        # + the dirty-row set driving host re-seeding from prompt +
        # generated tokens on every batch-composition change (None = all)
        self._draft_table = None
        self._draft_dirty: Optional[set] = None
        self.spec_stats = {"drafted": 0, "accepted": 0, "rejected": 0}
        # per-step mixed composition (prefill-token vs decode-token counts)
        # — exported via ENGINE_STEP_BATCH_COMPOSITION and inspectable by
        # tests/the telemetry endpoint
        self.last_step_composition: Dict[str, int] = {}
        self._build_compiled(compiled_programs)
        self._dense_ok = (
            self._use_mixed
            and self._spec_k is not None
            and self._mixed_decode_fn is not None
        )
        if self._spec_k is not None and not self._dense_ok:
            logger.info(
                "spec_decode_k=%s set but the program set has no "
                "mixed_decode; dense/speculative stepping disabled",
                self._spec_k)
        if self._mixed_fn is None and self._use_mixed:
            if engine_config.use_ragged:
                # an EXPLICIT opt-in must not silently serve the legacy
                # dispatch behavior (different compile-count budget and
                # batching) — same contract as the topology gate above
                raise NotImplementedError(
                    "use_ragged=True but the compiled program set has no "
                    "`mixed` program (pre-ragged stub or pp build)"
                )
            logger.info(
                "ragged mixed program unavailable in this program set; "
                "falling back to the legacy dispatch paths")
            self._use_mixed = False

    # ---------------- compiled programs ----------------

    def _next_admission_seq(self) -> float:
        self._admission_seq += 1.0
        return self._admission_seq

    def _build_compiled(self, override=None):
        """Jit the device programs (engine/compiled.py) and bind them under
        the historical attribute names the loop dispatches through.
        `override` (the simulator's stub seam) supplies a pre-built program
        set with the same attribute surface instead.

        With config.aot_cache_dir set, programs build as persistent AOT
        executables (engine/aot_cache.py) and every entry already on disk
        for this config digest is deserialized NOW — a warm start reaches
        its first request with zero traces, zero XLA compiles."""
        self._aot_cache = None
        if override is not None:
            p = override
        else:
            from .compiled import build_compiled

            cache = None
            if self.config.aot_cache_dir:
                from .aot_cache import AOTExecutableCache

                try:
                    cache = AOTExecutableCache(
                        self.config.aot_cache_dir, self.model_config,
                        self.config, self.mesh, label=self._mlabel,
                    )
                except OSError as exc:
                    # an unwritable cache volume must not take down the
                    # replica — it degrades to today's compile-on-start
                    logger.warning(
                        "aot-cache-disabled dir=%s error=%s",
                        self.config.aot_cache_dir,
                        f"{type(exc).__name__}: {exc}")
            if self.config.spec_decode_k is not None and cache is not None:
                # spec_decode_k is deliberately NOT in the AOT cache key
                # until hardware-validated: a spec engine sharing a
                # non-spec digest would load stale executables, so the
                # persistent cache is disabled outright for spec engines
                # (they compile on start like pre-AOT replicas)
                logger.info(
                    "aot-cache-disabled: spec_decode_k=%s is not part of "
                    "the AOT cache key yet", self.config.spec_decode_k)
                cache = None
            p = build_compiled(
                self.model_config, self.config, self.mesh, aot_cache=cache,
                spec_k=self.config.spec_decode_k)
            self._aot_cache = cache
            if cache is not None:
                loaded = sum(
                    prog.preload()
                    for prog in (
                        getattr(p, f.name)
                        for f in dataclasses.fields(type(p))
                    )
                    if prog is not None and hasattr(prog, "preload")
                )
                logger.info(
                    "aot-cache ready: digest=%s preloaded=%d executables "
                    "(%.3fs)", cache.digest, loaded,
                    cache.stats.aot_load_s)
        self._prefill_fn = p.prefill
        self._prefill_lp_fn = p.prefill_lp
        self._prefill_chunk_fn = p.prefill_chunk
        self._sample_first_fn = p.sample_first
        self._sample_first_lp_fn = p.sample_first_lp
        self._decode_fn = p.decode
        self._decode_lp_fn = p.decode_lp
        self._decode_penalized_fn = p.decode_penalized
        self._decode_penalized_lp_fn = p.decode_penalized_lp
        self._inject_fn = p.inject
        self._inject_q_fn = p.inject_q
        # the unified ragged program; absent on program sets that predate
        # it (or pp>1 builds), which forces the legacy dispatch paths
        self._mixed_fn = getattr(p, "mixed", None)
        # dense/speculative decode-only program (docs/kernels.md); present
        # only when spec_decode_k is configured (stubs included)
        self._mixed_decode_fn = getattr(p, "mixed_decode", None)

    # ---------------- public API ----------------

    async def start(self):
        if self._task is None:
            self._task = asyncio.create_task(self._run_loop())
            if self._watchdog is not None:
                self._watchdog.start()
            logger.info(
                "LLM engine started: slots=%d pages=%d page_size=%d tp=%d",
                self.config.max_batch_size, self.config.num_pages,
                self.config.page_size, self.config.tp,
            )
            warmup = self.config.aot_warmup
            if warmup is None:
                warmup = self._aot_cache is not None
            if warmup and not self._stopped:
                await self._aot_warmup()
            self._record_startup_ready()

    async def _aot_warmup(self):
        """Drive one tiny generation per prefill bucket through the REAL
        serving loop before the replica turns ready, so every
        steady-state program signature is compiled (cold start — and
        persisted to the AOT cache) or deserialized (warm start) ahead
        of the first real request.  Driving generate() instead of
        hand-building abstract signatures means warmup can never drift
        from what the scheduler actually dispatches."""
        params = SamplingParams(
            max_tokens=min(4, max(1, self.config.steps_per_sync)),
            temperature=0.0, ignore_eos=True,
        )
        for bucket in self.config.prefill_buckets:
            n = min(bucket, self.config.max_model_len - params.max_tokens)
            if n <= 0:
                continue
            try:
                async for _ in self.generate(
                    [1] * n, params, request_id=f"aot-warmup-{bucket}"
                ):
                    pass
            except Exception:  # noqa: BLE001 — warmup is an optimization;
                # a failure here must surface in logs, not block serving
                logger.exception("aot warmup failed for bucket %d", bucket)
        # warmup generations are not traffic: give the telemetry ring a
        # clean start (prometheus counters do keep the handful of warmup
        # observations — documented in docs/coldstart.md)
        self.telemetry = TimelineRecorder()

    def _record_startup_ready(self) -> None:
        """Stamp the ready phase and export every startup phase once
        (engine_startup_seconds — docs/coldstart.md)."""
        if self._startup_recorded:
            return
        self._startup_recorded = True
        if self._aot_cache is not None:
            s = self._aot_cache.stats
            self.startup_phases["trace"] = s.trace_s
            self.startup_phases["compile"] = s.compile_s
            self.startup_phases["aot_load"] = s.aot_load_s
        self.startup_phases["ready"] = (
            time.perf_counter() - self._construct_t0
            + self.startup_external_s)
        for phase, seconds in self.startup_phases.items():
            observe_startup_phase(self._mlabel, phase, seconds)

    async def stop(self):
        self._stopped = True
        self.stop_watchdog()
        self._wake.set()
        # fail queued-but-unseated requests NOW, before waiting on the loop
        # task: their asyncio queues would otherwise never see another put
        # and the consumer side would hang forever (a stop mid-drain leaves
        # exactly these behind)
        self._fail_waiting(lambda req: RuntimeError(
            f"engine stopped before request {req.request_id} was seated"
        ))
        # fail queued detached-prefill waiters before cancelling the worker —
        # otherwise prefill-role HTTP handlers awaiting prefill_detached()
        # hang until client timeout
        pending, self._detached_queue = self._detached_queue, []
        for _, _, fut, _ in pending:
            if not fut.done():
                fut.set_exception(RuntimeError("engine stopped"))
        if self._detached_task is not None and not self._detached_task.done():
            self._detached_task.cancel()
            self._detached_task = None
        if self._task is not None:
            try:
                await asyncio.wait_for(self._task, timeout=5)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                self._task.cancel()
            self._task = None
        # the loop is down: fail whatever is still seated (and anything the
        # loop's final iteration re-queued) so no stream outlives the engine
        self._fail_waiting(lambda req: RuntimeError(
            f"engine stopped before request {req.request_id} was seated"
        ))
        for slot in self._slots:
            if slot.request_id is not None:
                self._evict_slot(slot, RuntimeError("engine stopped"))
        # page-in / persist write-through tasks park on the fetch worker;
        # cancel them before closing it so none awakens into a dead engine
        for task in list(self._pagein_tasks):
            task.cancel()
        self._pagein_tasks.clear()
        # close AFTER the loop task is done: an in-flight chunk draining
        # through _fetch must reach a live worker (close-first would stall
        # the drain a full step deadline, then false-flag a wedge)
        self._fetcher.close()
        if self._kv_store is not None:
            self._kv_store.close()

    def _discard_resume_kv(self, req) -> None:
        """Release a queued request's spilled resume KV to the tier store
        (shared by every path that fails/checkpoints waiting requests)."""
        if (req.resume is not None and req.resume["kv"] is not None
                and self._kv_store is not None):
            self._kv_store.discard(req.resume["kv"])
            self._set_offload_gauges()

    def _fail_waiting(self, make_exc) -> None:
        """Fail every queued-but-unseated request with make_exc(req),
        releasing any spilled resume KV back to the tier store."""
        pending, self._waiting = self._waiting, []
        for req in pending:
            self._discard_resume_kv(req)
            req.queue.put_nowait(make_exc(req))
            self._record_terminal(req.timeline, "error")
        self._set_queue_gauge()

    @property
    def running(self) -> bool:
        return self._task is not None and not self._task.done()

    @property
    def wedged(self) -> bool:
        """True once a device fetch blew the step deadline (a wedged device
        tunnel); consumed by liveness so the pod restarts."""
        return self._wedged

    @property
    def queue_depth(self) -> int:
        """Requests waiting for admission (the EPP's primary load signal)."""
        return len(self._waiting)

    def scheduler_state(self, max_digests: int = 512) -> dict:
        """Snapshot for the EPP endpoint picker: live load plus the
        hottest prefix-cache digests (hex, most-recently-used last) so
        the picker can route prefix-sharing requests back here.  Parity:
        the role the GIE EPP's metrics scrape plays for the reference
        (ref llmisvc/scheduler.go:73-521)."""
        digests = self._prefix_cache.hottest_digests(max_digests)
        state = {
            "queue_depth": self.queue_depth,
            # seated generations: the "work already admitted" half of the
            # autoscaler's load signal (queue_depth is the waiting half)
            "inflight": sum(
                1 for s in self._slots if s.request_id is not None),
            "free_pages": self.allocator.free_pages,
            "page_size": self.config.page_size,
            "running": self.running,
            "wedged": self._wedged,
            "prefix_digests": digests,
            # rolling TTFT/ITL percentile windows (observability ring):
            # previously internal to telemetry, surfaced here so the EPP —
            # and the autoscaler behind it — sees SLO pressure per replica
            "telemetry": self.telemetry.signal_windows(),
        }
        if self._spec_k is not None and self._spec_k > 0:
            # speculative-decoding block (docs/kernels.md): lifetime
            # draft/accept tallies — accepted/drafted is this replica's
            # live acceptance rate, the signal a drafter regression
            # surfaces on before it surfaces as tok/s
            state["spec"] = dict(self.spec_stats)
        if self._watchdog is not None:
            # gray-failure watchdog block (docs/resilience.md): the EPP's
            # fleet health scoring quarantines on stall_suspected /
            # stall_confirmed — the signal a liveness probe cannot see
            state["watchdog"] = self._watchdog.snapshot()
        if self._kv_store is not None:
            # hierarchical prefix-store block (docs/kv_hierarchy.md): the
            # resident-digest count + hit/miss/demotion/page-in tallies the
            # EPP fleet block re-exports — the first cut of item 2's global
            # prefix index.  adopted_hit_tokens counts hits served from
            # pages this process NEVER prefilled (the hot-wake proof).
            stats = self._kv_store.stats_dict()
            stats["adopted_hit_tokens"] = (
                self._prefix_cache.adopted_hits * self.config.page_size)
            state["prefix_store"] = stats
            # peer-servable digest set (kvstore/peer.py digest_set_wire):
            # the bounded, generation-stamped summary the EPP re-serves so
            # a woken replica knows WHICH peer holds which pages.  A
            # separate key, not a prefix_store field — the picker's
            # multi-model prefix_store merge sums numbers and would mangle
            # a nested digest list.
            wire = self._kv_store.resident_digest_wire()
            if wire is not None:
                state["peer_pages"] = wire
        if self._peer_client is not None:
            # peer-fetch outcomes + per-peer bad-page evidence: the
            # production channel health.note_bad_page rides (the EPP
            # diffs bad_pages counts per poll — scheduler/picker.py)
            state["peer"] = self._peer_client.snapshot()
        return state

    # -------- cross-replica page fabric (docs/kv_hierarchy.md) --------

    def set_peer_client(self, client) -> None:
        """Attach a kvstore.peer.PeerPageClient: _maybe_page_in then
        extends its longest-run search past the local tiers into
        peer-resident digests, and _page_in fetches + verifies them."""
        self._peer_client = client

    def read_peer_page(self, digest: bytes):
        """Wire-encoded page bytes for the REST page server, or None.
        Pure store read — never touches the engine loop."""
        if self._kv_store is None:
            return None
        return self._kv_store.read_peer_page(digest)

    @property
    def _offload_bytes(self) -> int:
        """Bytes currently parked in the offload tiers (host + disk).
        Returns to 0 once every spilled sequence has been restored or
        discarded — the observable the spill/restore tests assert on."""
        if self._kv_store is None:
            return 0
        return int(self._kv_store.host_used + self._kv_store.disk_used)

    def _set_offload_gauges(self) -> None:
        if self._kv_store is None:
            return
        ENGINE_KV_OFFLOAD_BYTES.labels(model_name=self._mlabel).set(
            self._kv_store.host_used)
        ENGINE_KV_DISK_BYTES.labels(model_name=self._mlabel).set(
            self._kv_store.disk_used)

    # ---------------- hierarchical prefix store (docs/kv_hierarchy.md) ----------------

    def _gather_pages_device(self, page_ids: List[int]) -> Dict[str, Any]:
        """Dispatch-only gather of whole KV pages into host-layout device
        arrays ({name: [L, P, ...]}).  Callers either fetch synchronously
        (the preemption spill) or hand the arrays to the fetch worker
        (persist write-through) — the dispatch itself never blocks, and
        the four cache layouts (plain/int8 x flat/pp-stacked) live in ONE
        place instead of one per caller."""
        ids = jnp.asarray(np.asarray(page_ids, np.int32))
        if self.config.kv_quant == "int8" and self.config.pp > 1:
            pages, scales = self.kv_pages
            return {"kv_q": pages[:, ids], "kv_s": scales[:, ids]}
        if self.config.kv_quant == "int8":
            return {
                "kv_q": jnp.stack([layer[0][ids] for layer in self.kv_pages]),
                "kv_s": jnp.stack([layer[1][ids] for layer in self.kv_pages]),
            }
        if self.config.pp > 1:
            # stacked cache: one gather covers every stage's layers
            return {"kv": self.kv_pages[:, ids]}
        return {"kv": jnp.stack([layer[ids] for layer in self.kv_pages])}

    def _demote_prefix_pages(self, evicted: List[tuple]) -> None:
        """PrefixCache eviction seam: gather the evicted pages' KV (one
        device gather + fetch) and demote them into the host/disk tiers
        keyed by their digest chain keys.  The fetch is SYNCHRONOUS by
        design — the allocator reuses these pages the moment the seam
        returns, so their contents must be captured first (the same
        contract as the preemption spill); the cost is bounded by the
        eviction burst.  Demotion is tiers-only (persist=False): the
        persistent layer is fed exclusively by persist-on-REUSE, so
        one-shot prompts being evicted can never grow the uncapped
        durable directory.  Content addressing makes re-demotion free:
        digests already resident below HBM skip the gather.  Skipped
        while a chained decode chunk is in flight (the gather would read
        a cache version the in-flight program is superseding) — those
        pages simply drop, the pre-store behavior, and a drop is a perf
        event never a correctness one."""
        store = self._kv_store
        if (store is None or not store.accepts_prefix_pages
                or self._pipeline_busy or self._stopped):
            return
        pairs = [(k, p) for k, p in evicted
                 if store.prefix_tier_of(k) is None]
        if not pairs:
            return
        dev = self._gather_pages_device([p for _, p in pairs])
        fetched = {name: self._fetch(v) for name, v in dev.items()}
        for i, (key, _) in enumerate(pairs):
            # contiguous copy, not a view: a view would pin the WHOLE
            # multi-page gather in host RAM while the tier accounts for
            # one page of it
            store.put_prefix(
                key,
                {name: np.ascontiguousarray(arr[:, i:i + 1])
                 for name, arr in fetched.items()},
                persist=False,
            )
        store.record_demotion(len(pairs))
        self._set_offload_gauges()

    def _count_prefix_hits(self, keys: List[bytes], hits: List[int]) -> None:
        """Admission served `hits` pages from the HBM prefix cache: count
        pages + tokens, and trigger the persist-on-reuse write-through —
        a HIT proves the prefix is shared, which is exactly the page
        worth keeping across restarts (one-shot prompts never reach the
        persistent layer, so it cannot thrash)."""
        if not hits:
            return
        self._prefix_cache.hits += len(hits)
        # adopted hits are counted HERE, per admission actually served —
        # counting inside lookup_run would tally every retried lookup of
        # a held request and inflate the hot-wake metric
        if keys:
            self._prefix_cache.count_adopted_hits(keys[:len(hits)])
        KV_PREFIX_HIT_TOKENS.labels(model_name=self._mlabel, tier="hbm").inc(
            len(hits) * self.config.page_size)
        if self._kv_store is not None and keys:
            self._maybe_persist_prefix(keys[:len(hits)], hits)

    def _track_task(self, coro) -> None:
        task = asyncio.get_running_loop().create_task(coro)
        # start stamp for the watchdog's task-stall accounting: a tracked
        # task alive past the stall budget is cancelled, not left pinning
        # the request it was supposed to unblock
        task._wd_started_s = self._clock.now()
        self._pagein_tasks.add(task)
        task.add_done_callback(self._pagein_tasks.discard)

    def _maybe_persist_prefix(self, keys: List[bytes],
                              pages: List[int]) -> None:
        store = self._kv_store
        if self._stopped:
            return
        need = [k for k in store.needs_persist(keys)
                if k not in self._persisting]
        if not need:
            return
        page_of = dict(zip(keys, pages))
        # the gather is DISPATCHED now, while the pages are live and
        # referenced; the blocking device->host read and the file writes
        # ride the fetch worker so decode never waits on them
        dev = self._gather_pages_device([page_of[k] for k in need])
        self._persisting.update(need)
        self._track_task(self._persist_pages(need, dev))

    async def _persist_pages(self, keys: List[bytes], dev: Dict) -> None:
        try:
            fetched = await self._fetcher.fetch_async(
                lambda: {k: np.asarray(v) for k, v in dev.items()},
                self.config.step_deadline_s)
            store = self._kv_store
            for i, key in enumerate(keys):
                store.put_prefix(
                    key,
                    {name: np.ascontiguousarray(arr[:, i:i + 1])
                     for name, arr in fetched.items()})
            self._set_offload_gauges()
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — persistence is an optimization;
            # the page stays HBM-resident and serving continues
            logger.exception("prefix persist write-through failed")
        finally:
            for key in keys:
                self._persisting.discard(key)

    def _maybe_page_in(self, req: "_QueuedRequest", keys: List[bytes],
                       n_hbm: int) -> bool:
        """Hierarchical-store admission hook: when a request's digest
        chain continues past its HBM-cached run into tier-resident pages,
        schedule an ASYNC page-in (tier/disk read on the fetch worker,
        one inject dispatch, adopt into the HBM cache) and hold the
        request back; decode keeps running under the upload, and the
        retried admission prefills only the still-uncached tail.  True =
        page-in pending, do not seat this request yet."""
        if req.pagein == "pending":
            return True
        if (req.pagein == "done" or self._kv_store is None
                or self._draining or self._stopped or len(keys) <= n_hbm):
            return False
        run = self._kv_store.longest_prefix_run(keys[n_hbm:])
        # peer leg (docs/kv_hierarchy.md "Cross-replica page serving"):
        # the longest-run search continues past the local tiers into
        # digests some OTHER replica advertises as persist-resident.
        # Chain contiguity is preserved — peer entries only ever extend
        # the local run's tail, and _page_in truncates back to the
        # longest VERIFIED prefix if a fetch fails mid-transfer.
        peer = self._peer_client
        if peer is not None:
            for digest in keys[n_hbm + len(run):]:
                if not any(u != peer.self_url
                           for u in peer.index.peers_for(digest)):
                    break
                run.append((digest, "peer"))
        # room for the incoming pages may come from evicting COLD cached
        # pages (which demote in turn — hierarchy rotation, not loss);
        # only a cache that stays full of hotter pages vetoes the page-in
        if not run or not self._prefix_cache.ensure_allocatable(len(run)):
            # nothing resident (or no headroom worth competing for):
            # remember the verdict so every admission retry is O(1)
            req.pagein = "done"
            return False
        req.pagein = "pending"
        self._track_task(self._page_in(req, run))
        return True

    async def _page_in(self, req: "_QueuedRequest", run: List[tuple]) -> None:
        """Upload one tier-resident prefix run back into device pages.
        The tier/disk reads happen off the event loop (fetch_async — the
        PR 5 seam, so decode overlaps the I/O); the device upload is the
        same inject scatter the P/D and spill-resume paths already
        dispatch, so no new program shape is traced and steady-state
        compile counts hold.  NO host syncs on this path: the inject is
        dispatch-only, nothing fetches its result (jaxlint
        pagein-host-sync guards exactly this)."""
        store = self._kv_store
        t0 = self._clock.now()
        try:
            # peer entries only ever sit at the tail (how _maybe_page_in
            # builds the run); the local head reads off the fetch worker,
            # the peer tail fetches over the verified fabric
            n_local = sum(1 for _, tier in run if tier != "peer")
            digests = [d for d, _ in run[:n_local]]
            peer_digests = [d for d, _ in run[n_local:]]

            def read():
                out = []
                for digest in digests:
                    got = store.get_prefix(digest)
                    if got is None:
                        break  # dropped/corrupt underneath us: truncate
                    out.append(got)
                return out

            try:
                payloads = await self._fetcher.fetch_async(
                    read, self.config.step_deadline_s)
            except (RuntimeError, TimeoutError):
                return  # engine stopping / fetcher closed
            if self._stopped or self._draining:
                return
            entries = []  # (digest, payload, source tier)
            for digest, got in zip(digests, payloads):
                if self._prefix_cache.contains_key(digest):
                    continue  # a concurrent page-in/prefill won the race
                entries.append((digest, got[0], got[1]))
            # peer leg: chain contiguity first — a truncated LOCAL run
            # means the peer tail no longer extends a verified prefix, so
            # drop it; otherwise fetch + verify page by page, truncating
            # at the first failure (mid-transfer peer death degrades to
            # the longest verified prefix run, never a failed admission)
            adopted_from_peer = []  # (digest, payload) for write-through
            if (peer_digests and self._peer_client is not None
                    and len(payloads) == len(digests)):
                for digest in peer_digests:
                    if self._stopped or self._draining:
                        return
                    if self._prefix_cache.contains_key(digest):
                        continue
                    payload = await self._peer_client.fetch_page(digest)
                    if payload is None:
                        break  # verify failure / partition / deadline
                    entries.append((digest, payload, "peer"))
                    adopted_from_peer.append((digest, payload))
            if self._stopped or self._draining:
                return
            if not entries or not self.allocator.can_allocate(len(entries)):
                return
            pages = self.allocator.allocate(len(entries))
            try:
                n = len(entries)
                bucket = self.config.page_bucket(n)
                ids = np.zeros((bucket,), np.int32)
                ids[:n] = pages

                def packed(name: str):
                    arr = np.concatenate(
                        [payload[name] for _, payload, _ in entries], axis=1)
                    out = np.zeros(
                        arr.shape[:1] + (bucket,) + arr.shape[2:], arr.dtype)
                    out[:, :n] = arr
                    return jnp.asarray(out)

                if "kv_q" in entries[0][1]:
                    self.kv_pages = self._inject_q_fn(
                        self.kv_pages, packed("kv_q"), packed("kv_s"),
                        jnp.asarray(ids))
                else:
                    self.kv_pages = self._inject_fn(
                        self.kv_pages, packed("kv"), jnp.asarray(ids))
                # the cache takes ownership of the freshly-allocated refs
                self._prefix_cache.adopt(
                    [(digest, page) for (digest, _, _), page
                     in zip(entries, pages)])
            except BaseException:
                self.allocator.free(pages)
                raise
            # write-through: a page fetched from a peer becomes locally
            # resident (tiers + persistent layer) so the NEXT wake in
            # this zone serves it without crossing the fabric again, and
            # this replica starts advertising it in its digest-set wire
            for digest, payload in adopted_from_peer:
                store.put_prefix(digest, payload)
            ps = self.config.page_size
            pages_by_tier: Dict[str, int] = {}
            for _, _, tier in entries:
                pages_by_tier[tier] = pages_by_tier.get(tier, 0) + 1
            tokens_by_tier = {t: c * ps for t, c in pages_by_tier.items()}
            store.record_pagein(pages_by_tier, tokens_by_tier)
            for tier, tokens in tokens_by_tier.items():
                KV_PREFIX_HIT_TOKENS.labels(
                    model_name=self._mlabel, tier=tier).inc(tokens)
            KV_PAGEIN_SECONDS.labels(model_name=self._mlabel).observe(
                self._clock.now() - t0)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — page-in is an optimization;
            # the held request re-prefills its whole tail instead
            logger.exception("prefix page-in failed")
        finally:
            req.pagein = "done"
            self._wake.set()

    def _set_queue_gauge(self) -> None:
        """THE queue-depth gauge writer.  Every mutation of _waiting calls
        this unconditionally — a conditional zeroing on one path (the r5
        fail-all bug) left the gauge stale after stop/drain whenever the
        queue happened to be empty at flush time."""
        ENGINE_QUEUE_DEPTH.labels(model_name=self._mlabel).set(
            len(self._waiting))

    def _set_composition_gauge(self, n_decoding: int) -> None:
        """Per-step batch composition: how the fixed decode slots split
        between decoding lanes, long-prompt prefills, and free capacity."""
        n_prefilling = sum(
            1 for s in self._slots
            if s.request_id is not None and s.prefilling is not None
        )
        g = ENGINE_STEP_BATCH_COMPOSITION
        g.labels(model_name=self._mlabel, role="decoding").set(n_decoding)
        g.labels(model_name=self._mlabel, role="prefilling").set(n_prefilling)
        g.labels(model_name=self._mlabel, role="free").set(
            self.config.max_batch_size - n_decoding - n_prefilling)

    def telemetry_snapshot(self) -> dict:
        """Rolling latency percentiles + recent request timelines (the
        GET /admin/telemetry payload; observability/introspection.py)."""
        snap = self.telemetry.snapshot()
        snap["queue_depth"] = self.queue_depth
        snap["prefix_cache_hits"] = self.prefix_cache_hits
        snap["preemptions"] = self.preemption_count
        return snap

    def _record_terminal(self, tl: Optional[RequestTimeline],
                         reason: Optional[str]) -> None:
        """A timeline reached a terminal state: stamp it, feed the ring
        buffer, export the Prometheus series (finished generations only),
        and emit the engine child spans when a tracer is configured."""
        if tl is None or tl.recorded:
            return
        tl.recorded = True
        tl.mark_finished(self._clock.now(), reason)
        self.telemetry.observe(tl)
        if reason in ("stop", "length"):
            observe_request_timeline(self._mlabel, tl)
        from ..tracing import get_tracer

        tracer = get_tracer()
        if tracer is not None:
            try:
                emit_timeline_spans(tracer, tl)
            except Exception:  # noqa: BLE001 — telemetry must never kill the loop
                logger.exception("engine span emission failed")

    # ---------------- gray-failure watchdog (docs/resilience.md) ----------------

    def _has_live_work(self) -> bool:
        """Watchdog busy probe: anything seated or queued that should be
        making forward progress."""
        return bool(self._waiting) or any(
            s.request_id is not None for s in self._slots)

    def _note_progress(self) -> None:
        if self._watchdog is not None:
            self._watchdog.note_progress()

    def stop_watchdog(self) -> None:
        """Stop the watchdog tick task (engine.stop does this; the fleet
        simulator also calls it before draining its timer heap — a live
        watchdog re-arms a virtual timer every interval forever)."""
        if self._watchdog is not None:
            self._watchdog.stop()

    def _stall_confirmed(self, reason: str) -> None:
        """Watchdog confirm hook: flip readiness and self-drain with
        checkpoints.  The drain salvages every in-flight token through
        the PR 5 checkpoint path — each stream sees GenerationPreempted
        with a portable checkpoint and resumes on a healthy replica —
        instead of holding streams hostage until the client deadline or
        a kubelet SIGKILL loses everything."""
        if self.on_stall_confirmed is not None:
            try:
                self.on_stall_confirmed(reason)
            except Exception:  # noqa: BLE001 — a broken lifecycle hook must
                # not block the salvage drain below
                logger.exception("on_stall_confirmed hook failed")
        # tracked for stop() cancellation but deliberately NOT stamped
        # with _wd_started_s (_track_task would): the watchdog's task
        # reaper must never cancel its own salvage drain mid-checkpoint
        task = asyncio.get_running_loop().create_task(
            self._stall_self_drain())
        self._pagein_tasks.add(task)
        task.add_done_callback(self._pagein_tasks.discard)

    async def _stall_self_drain(self) -> None:
        deadline = Deadline.after(
            self.config.watchdog_salvage_grace_s, self._clock)
        try:
            checkpoints = await self.drain(
                deadline=deadline, clock=self._clock, reason="stall")
            logger.error(
                "watchdog self-drain complete: %d generation(s) "
                "checkpointed for migration", len(checkpoints))
        except Exception:  # noqa: BLE001 — the stall state is already
            # exported; a failed salvage must not crash the process
            logger.exception("watchdog self-drain failed")

    def _fetch_fault_check(self) -> None:
        """Shared fault seam for _fetch/_fetch_async — one copy, so a new
        fault kind can't be honored in one fetch path and not the other."""
        if self.fault_plan is not None:
            spec = self.fault_plan.decide("engine.fetch")
            if spec is not None and spec.kind == "wedge":
                raise self._wedge("injected wedge (fault plan)")
            if spec is not None and spec.kind == "replica_crash":
                # the process died: no wedge flag, no drain, no checkpoint —
                # the run loop's crash handler fails every in-flight stream
                # and clients must recover by retrying from scratch
                raise ReplicaCrashError("injected replica crash (fault plan)")

    def _wedge(self, msg: str) -> EngineWedgedError:
        self._wedged = True
        ENGINE_WEDGED.labels(model_name=self._mlabel).set(1)
        return EngineWedgedError(msg)

    def _fetch_timeout(self) -> EngineWedgedError:
        return self._wedge(
            f"device fetch exceeded step_deadline_s="
            f"{self.config.step_deadline_s}s — device tunnel wedged?"
        )

    def _fetch(self, x) -> np.ndarray:
        """Device->host fetch with the wedge deadline (see step_deadline_s)."""
        self._fetch_fault_check()
        try:
            return self._fetcher.fetch(
                lambda: np.asarray(x), self.config.step_deadline_s)
        except TimeoutError:
            raise self._fetch_timeout() from None

    async def _fetch_async(self, x) -> np.ndarray:
        """_fetch for the decode hot loop: AWAITS the device->host fetch so
        the event loop keeps serving (probes, /admin/drain, the drain
        budget loop, admission rejects) while the chunk computes — a
        blocking wait here starves every other coroutine for the full step
        duration.  Same fault seam and wedge mapping as _fetch."""
        self._fetch_fault_check()
        wd = self._watchdog
        if wd is not None:
            wd.fetch_started()
        try:
            return await self._fetcher.fetch_async(
                lambda: np.asarray(x), self.config.step_deadline_s)
        except TimeoutError:
            raise self._fetch_timeout() from None
        finally:
            if wd is not None:
                wd.fetch_done()

    def generate(
        self,
        prompt_ids: List[int],
        params: SamplingParams,
        request_id: Optional[str] = None,
        adapter: Optional[str] = None,
    ) -> AsyncIterator[GenerationOutput]:
        """Submit a request; yields GenerationOutput per emitted token.
        `adapter` selects a loaded LoRA adapter by name (None = base).
        Validation runs HERE, not at first __anext__ — callers get their
        ValueError before any stream machinery is involved.  Prompts longer
        than max_prefill_len prefill in chunks (one compiled program per
        chunk bucket), so only the model length bounds them."""
        if len(prompt_ids) + params.max_tokens > self.config.max_model_len:
            raise ValueError(
                f"prompt+max_tokens exceeds max_model_len {self.config.max_model_len}"
            )
        self._check_accepting()
        deadline = self._admission_deadline()
        queue: asyncio.Queue = asyncio.Queue()
        rid = request_id or f"req-{time.monotonic_ns()}"
        req = _QueuedRequest(
            rid, list(prompt_ids), params, queue,
            adapter_id=self._resolve_adapter(adapter),
            deadline=deadline,
            timeline=self._new_timeline(rid, len(prompt_ids)),
        )
        return self._submit_and_stream(req)

    def _new_timeline(self, rid: str, n_prompt: int) -> RequestTimeline:
        """Stamp `received` NOW (the sync part of submit) and capture the
        caller's trace context so engine spans join the request's trace."""
        from ..tracing import current_trace_context

        tl = RequestTimeline(rid, model_name=self._mlabel,
                             trace=current_trace_context())
        tl.n_prompt_tokens = n_prompt
        tl.mark_received(self._clock.now())
        return tl

    def _check_accepting(self) -> None:
        """Admission gate for the lifecycle layer: a draining (or stopped)
        engine refuses new work synchronously — 503 + Retry-After upstream —
        instead of queueing it into a replica that is going away."""
        if self._stopped or self._draining:
            raise ReplicaDrainingError(
                "engine is "
                + ("stopped" if self._stopped else "draining")
                + "; retry another replica"
            )

    def _admission_deadline(self):
        """The propagated request deadline (resilience contextvar), checked
        HERE so an already-dead budget is rejected synchronously — before
        any stream machinery, queue slot, or prefill work is committed."""
        deadline = current_deadline()
        if deadline is not None and deadline.expired:
            DEADLINE_REJECTED.labels(component="engine").inc()
            raise DeadlineExceededError(
                "request deadline expired before engine admission"
            )
        return deadline

    def _resolve_adapter(self, adapter: Optional[str]) -> int:
        if adapter is None:
            return -1
        if adapter not in self.adapter_ids:
            raise ValueError(
                f"unknown LoRA adapter {adapter!r}; loaded: "
                f"{sorted(self.adapter_ids) or 'none'}"
            )
        return self.adapter_ids[adapter]

    def generate_injected(
        self,
        prompt_ids: List[int],
        params: SamplingParams,
        kv_data: np.ndarray,  # [L, P, 2, n_kv, ps, d] from prefill_detached
        first_token: int,
        request_id: Optional[str] = None,
        adapter: Optional[str] = None,
    ) -> AsyncIterator[GenerationOutput]:
        """P/D disaggregation, decode side: admit a request whose prompt KV
        was computed by a prefill-role server.  The KV pages are scattered
        into this engine's cache and decoding starts at pos=len(prompt).
        Sync validation, async stream (see generate)."""
        if len(prompt_ids) + params.max_tokens > self.config.max_model_len:
            raise ValueError(
                f"prompt+max_tokens exceeds max_model_len {self.config.max_model_len}"
            )
        if self.config.kv_quant != "none":
            raise NotImplementedError(
                "KV injection over a quantized cache is not supported yet"
            )
        # validation runs HERE (sync), not at first __anext__: a shape
        # mismatch inside _run_loop would kill the engine for all traffic,
        # not just this request (version-skewed prefill peer)
        kv_data = np.asarray(kv_data)
        cc = self.cache_config
        expect = (
            cc.n_layers, pages_needed(len(prompt_ids), cc.page_size), 2,
            cc.n_kv_heads, cc.page_size, cc.head_dim,
        )
        if tuple(kv_data.shape) != expect:
            raise ValueError(
                f"injected KV shape {tuple(kv_data.shape)} incompatible with "
                f"this engine's cache (expected {expect}); prefill peer and "
                "decode server must share model + page_size configuration"
            )
        self._check_accepting()
        deadline = self._admission_deadline()
        queue: asyncio.Queue = asyncio.Queue()
        rid = request_id or f"req-{time.monotonic_ns()}"
        req = _QueuedRequest(
            rid, list(prompt_ids), params, queue,
            kv_data=kv_data, first_token=int(first_token),
            adapter_id=self._resolve_adapter(adapter),
            deadline=deadline,
            timeline=self._new_timeline(rid, len(prompt_ids)),
        )
        return self._submit_and_stream(req)

    async def _submit_and_stream(self, req: "_QueuedRequest"):
        # re-check admission at ENQUEUE time: _check_accepting ran in the
        # sync part of the caller, but the first __anext__ can land after a
        # drain that already flushed _waiting for the last time — appending
        # now would strand this request forever (nothing re-flushes once
        # drain() has returned)
        self._check_accepting()
        self._waiting.append(req)
        self._set_queue_gauge()
        self._wake.set()
        try:
            while True:
                out = await req.queue.get()
                if isinstance(out, Exception):
                    raise out
                yield out
                if out.finished:
                    return
        finally:
            # client went away (generator closed / task cancelled): release
            # the slot and pages instead of decoding to max_tokens for nobody
            self.cancel(req.request_id)

    async def prefill_detached(
        self, prompt_ids: List[int], params: SamplingParams,
        adapter: Optional[str] = None,
    ) -> Tuple[int, np.ndarray]:
        """P/D disaggregation, prefill side: compute the prompt's KV and the
        first sampled token, extract the KV pages to host, release the pages.
        Returns (first_token, kv [L, P, 2, n_kv, ps, d]).

        Concurrent callers are micro-batched: a worker drains the queue and
        prefills up to `prefill_batch` prompts per compiled call, so a
        prefill-role server gets the same batching as co-located admission.

        Parity: the KV-connector role of the reference's disaggregated
        serving (workload_kvcache.go, llm_inference_service_types.go:105-110)
        with the transfer payload produced TPU-side in one gather."""
        if self.config.kv_quant != "none":
            raise NotImplementedError(
                "detached prefill (P/D transfer) over a quantized KV cache "
                "is not supported yet"
            )
        if params.logprobs is not None:
            # the P/D wire format carries (kv, first_token) only; the decode
            # role would be missing the first token's logprobs.  Explicit
            # here beats a silently-None first entry.
            raise ValueError(
                "logprobs is not supported with prefill/decode disaggregation"
            )
        n = len(prompt_ids)
        if n > self.config.max_prefill_len:
            raise ValueError(
                f"prompt length {n} exceeds max_prefill_len "
                f"{self.config.max_prefill_len}"
            )
        self._check_accepting()
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._detached_queue.append(
            (list(prompt_ids), params, fut, self._resolve_adapter(adapter))
        )
        if self._detached_task is None or self._detached_task.done():
            self._detached_task = asyncio.create_task(self._detached_worker())
        return await fut

    async def _detached_worker(self):
        """Drains queued detached prefills in micro-batches; exits when the
        queue empties (restarted lazily by the next request)."""
        while self._detached_queue and not self._stopped:
            batch = self._detached_queue[: self.config.prefill_batch]
            del self._detached_queue[: len(batch)]
            async with self._detached_lock:
                try:
                    self._prefill_detached_batch(batch)
                except Exception as e:  # noqa: BLE001 — fail the waiters, not the engine
                    for _, _, fut, _ in batch:
                        if not fut.done():
                            fut.set_exception(e)
            await asyncio.sleep(0)
        if self._stopped:
            # exiting on shutdown: fail anything enqueued after stop()'s drain
            pending, self._detached_queue = self._detached_queue, []
            for _, _, fut, _ in pending:
                if not fut.done():
                    fut.set_exception(RuntimeError("engine stopped"))

    def _prefill_detached_batch(self, batch) -> None:
        """One compiled prefill over up to prefill_batch detached prompts;
        per-row KV extraction; pages freed after extraction."""
        runnable = []
        for prompt_ids, params, fut, adapter_id in batch:
            n_pages = pages_needed(len(prompt_ids), self.config.page_size)
            if not self.allocator.can_allocate(n_pages):
                fut.set_exception(
                    MemoryError("KV pages exhausted for detached prefill")
                )
                continue
            runnable.append(
                (prompt_ids, params, fut, adapter_id,
                 self.allocator.allocate(n_pages))
            )
        if not runnable:
            return
        bucket = self._bucket_for(max(len(r[0]) for r in runnable))
        Bp = 1
        while Bp < len(runnable):
            Bp *= 2
        tokens = np.zeros((Bp, bucket), np.int32)
        valid = np.zeros((Bp,), np.int32)
        page_ids = np.zeros((Bp, self.config.max_pages_per_seq), np.int32)
        adapter_arr = np.full((Bp,), -1, np.int32)
        params_list = [SamplingParams() for _ in range(Bp)]
        for j, (prompt_ids, params, _, adapter_id, pages) in enumerate(runnable):
            n = len(prompt_ids)
            tokens[j, :n] = prompt_ids
            valid[j] = n
            page_ids[j, : len(pages)] = pages
            adapter_arr[j] = adapter_id
            params_list[j] = params
        state = SamplingState.from_params(params_list)
        rng = jax.random.fold_in(self._base_rng, self._next_step())
        try:
            first, self.kv_pages = self._prefill_fn(
                self.params,
                jnp.asarray(tokens),
                jnp.asarray(valid),
                self.kv_pages,
                jnp.asarray(page_ids),
                state,
                rng,
                jnp.asarray(adapter_arr),
            )
            first_np = self._fetch(first)
            for j, (prompt_ids, _, fut, _, pages) in enumerate(runnable):
                ids = jnp.asarray(np.asarray(pages, np.int32))
                # deadline-guarded: this is the engine's LARGEST device->
                # host copy — a tunnel wedge mid-DMA must trip liveness,
                # not hang the prefill-role handlers forever
                if self.config.pp > 1:
                    # stacked cache: one cross-stage gather; the wire
                    # payload layout ([L, P, 2, nkv, ps, d]) is identical,
                    # so prefill and decode tiers may run DIFFERENT
                    # pp/tp topologies
                    kv = self._fetch(self.kv_pages[:, ids])
                else:
                    kv = self._fetch(
                        jnp.stack([layer[ids] for layer in self.kv_pages])
                    )
                if not fut.done():
                    fut.set_result((int(first_np[j]), kv))
        finally:
            for *_, pages in runnable:
                self._free_pages(pages)

    def cancel(self, request_id: str) -> None:
        kept = []
        for r in self._waiting:
            if r.request_id != request_id:
                kept.append(r)
            else:
                self._discard_resume_kv(r)
                self._record_terminal(r.timeline, "cancelled")
        self._waiting = kept
        self._set_queue_gauge()
        for i, slot in enumerate(self._slots):
            if slot.request_id == request_id:
                tl = slot.timeline
                if tl is not None and tl.finished_at is None:
                    # client went away mid-generation (stream closed):
                    # terminal for telemetry even though nothing was sent
                    self._record_terminal(tl, "cancelled")
                self._free_pages(slot.pages)
                slot.reset()
                self._mark_penalty_dirty(i)
                self._wake.set()

    # ---------------- lifecycle: drain + resumable generation ----------------

    @property
    def draining(self) -> bool:
        return self._draining

    def _adapter_name(self, adapter_id: int) -> Optional[str]:
        if adapter_id < 0:
            return None
        for name, i in self.adapter_ids.items():
            if i == adapter_id:
                return name
        return None

    def _checkpoint(self, request_id, prompt_ids, generated, params,
                    adapter_id, deadline, reason) -> GenerationCheckpoint:
        ckpt = GenerationCheckpoint.capture(
            request_id=request_id,
            prompt_ids=prompt_ids,
            generated=generated,
            params=params,
            adapter=self._adapter_name(adapter_id),
            model_name=self._ckpt_label,
            deadline=deadline,
            reason=reason,
        )
        self.checkpointed_count += 1
        GENERATION_CHECKPOINTS.labels(
            model_name=self._mlabel, reason=reason).inc()
        return ckpt

    def _checkpoint_slot(self, slot: _Slot, reason: str) -> GenerationCheckpoint:
        """Snapshot a seated slot.  A slot still chunk-prefilling has
        emitted nothing; its checkpoint carries only the prompt (plus any
        prior resume progress), so resume costs exactly one prefill."""
        if slot.prefilling is not None:
            req = slot.prefilling["req"]
            generated = req.resume["generated"] if req.resume is not None else []
            return self._checkpoint(
                req.request_id, req.prompt_ids, generated, req.params,
                req.adapter_id, req.deadline, reason,
            )
        return self._checkpoint(
            slot.request_id, slot.prompt_ids, slot.generated, slot.params,
            slot.adapter_id, slot.deadline, reason,
        )

    def _evict_slot(self, slot: _Slot, exc: Exception) -> None:
        """Deliver exc to the slot's stream and release its resources
        (deferred-free-safe: legal while a chained chunk is in flight)."""
        slot.queue.put_nowait(exc)
        if slot.timeline is not None:
            if isinstance(exc, GenerationPreempted):
                slot.timeline.add_event(self._clock.now(), "checkpoint")
                self._record_terminal(slot.timeline, "preempted")
            else:
                self._record_terminal(slot.timeline, "error")
        self._free_pages(slot.pages)
        idx = self._slots.index(slot)
        slot.reset()
        self._mark_penalty_dirty(idx)

    def _checkpoint_waiting(self, reason: str,
                            out: List[GenerationCheckpoint]) -> None:
        """Checkpoint + fail every queued-but-unseated request (fresh
        arrivals and KV-pressure preemptions alike).  Their streams see
        GenerationPreempted; spilled resume KV is released."""
        pending, self._waiting = self._waiting, []
        for req in pending:
            self._discard_resume_kv(req)
            generated = (
                list(req.resume["generated"]) if req.resume is not None else []
            )
            ckpt = self._checkpoint(
                req.request_id, req.prompt_ids, generated, req.params,
                req.adapter_id, req.deadline, reason,
            )
            out.append(ckpt)
            req.queue.put_nowait(GenerationPreempted(ckpt))
            if req.timeline is not None:
                req.timeline.add_event(
                    self._clock.now(), "checkpoint", reason=reason)
                self._record_terminal(req.timeline, "preempted")
        self._set_queue_gauge()

    async def drain(self, deadline: Optional[Deadline] = None,
                    clock=None, poll_s: float = 0.01,
                    reason: str = "drain") -> List[GenerationCheckpoint]:
        """Graceful drain (SIGTERM / POST /admin/drain): stop admitting,
        give in-flight generations until `deadline` (the replica's drain
        budget — lifecycle.begin_drain()) to finish, then snapshot whatever
        remains into portable GenerationCheckpoints delivered to each
        stream as GenerationPreempted.  Queued-but-unseated requests are
        checkpointed immediately — re-seating them here would burn budget a
        healthy replica could spend better.  `clock` is the chaos-test seam
        (FakeClock => the wait is virtual); escalation (second SIGTERM)
        expires `deadline` in place, which this loop observes on its next
        poll.  `reason` labels the checkpoints ("drain" for lifecycle
        drains, "stall" for the watchdog's self-drain — the sim's client
        layer counts stall-reason resumes as migrations).  Returns the
        checkpoints, newest last."""
        self._draining = True
        clk = clock or MONOTONIC
        checkpoints: List[GenerationCheckpoint] = []
        while True:
            # KV-pressure preemptions during the drain land back in
            # _waiting; flush them each pass instead of re-seating
            self._checkpoint_waiting(reason, checkpoints)
            active = [s for s in self._slots if s.request_id is not None]
            if not active:
                break
            if deadline is not None and deadline.expired:
                for slot in active:
                    ckpt = self._checkpoint_slot(slot, reason)
                    checkpoints.append(ckpt)
                    self._evict_slot(slot, GenerationPreempted(ckpt))
                self._wake.set()
                break
            await clk.sleep(poll_s)
        if checkpoints:
            logger.info(
                "drain: %d generation(s) checkpointed (%d tokens salvaged)",
                len(checkpoints),
                sum(c.tokens_salvaged for c in checkpoints),
            )
        return checkpoints

    def resume_generation(
        self,
        checkpoint: GenerationCheckpoint,
        request_id: Optional[str] = None,
    ) -> AsyncIterator[GenerationOutput]:
        """Admit a checkpointed generation from another (drained/preempted)
        replica.  Resume rides the existing preemption-resume machinery: a
        prefill of prompt+generated[:-1] (cheap under the prefix cache)
        re-creates the KV, the detokenizer is replayed to the checkpoint
        point, and decoding continues at the NEXT token — the re-prefill
        emits nothing, so the spliced stream has zero duplicated and zero
        dropped tokens.  Sync validation, async stream (see generate)."""
        if checkpoint.model_name and checkpoint.model_name != self._ckpt_label:
            raise ValueError(
                f"checkpoint was captured on model {checkpoint.model_name!r} "
                f"but this engine serves {self._ckpt_label!r}; resume "
                "requires identical weights"
            )
        # header-sourced checkpoints are untrusted input: normalize token
        # ids and sampling types HERE, synchronously, so a malformed value
        # fails this request instead of crashing the shared run loop
        checkpoint.validate(self.model_config.vocab_size)
        params = checkpoint.sampling_params()
        prompt_ids = list(checkpoint.prompt_ids)
        if len(prompt_ids) + params.max_tokens > self.config.max_model_len:
            raise ValueError(
                f"prompt+max_tokens exceeds max_model_len {self.config.max_model_len}"
            )
        # max_tokens is the TOTAL budget (pre-drain tokens count toward it),
        # so this bound plus the one above also caps prompt+generated at
        # max_model_len — an oversized crafted checkpoint must fail HERE
        # with a 400, not detonate allocation inside the shared run loop
        if len(checkpoint.generated) >= params.max_tokens:
            raise ValueError(
                f"checkpoint already holds {len(checkpoint.generated)} "
                f"generated tokens with max_tokens={params.max_tokens}; "
                "nothing left to resume"
            )
        self._check_accepting()
        # the effective budget is the min of the snapshot-time remainder
        # and the retry's own propagated deadline: the time a client spent
        # backing off between drain and resume is SLA time spent, and the
        # snapshot must not re-grant it (an expired propagated deadline is
        # rejected synchronously inside _admission_deadline)
        deadline = self._admission_deadline()
        if checkpoint.deadline_remaining_s is not None:
            if checkpoint.deadline_remaining_s <= 0:
                DEADLINE_REJECTED.labels(component="engine").inc()
                raise DeadlineExceededError(
                    "checkpoint deadline budget exhausted before resume"
                )
            # anchored on the ENGINE's clock (clock-injection audit): under
            # a virtual clock the snapshot budget must expire in virtual
            # time like every other deadline, or resumes would outlive the
            # budget their checkpoint carried
            snapshot = Deadline.after(
                checkpoint.deadline_remaining_s, self._clock)
            if deadline is None or snapshot.remaining() < deadline.remaining():
                deadline = snapshot
        generated = [int(t) for t in checkpoint.generated]
        queue: asyncio.Queue = asyncio.Queue()
        # the engine-side id must be unique even when the SAME checkpoint
        # is replayed twice (exactly the retry-storm case this feature
        # serves): cancel() tears down every slot matching the id, so two
        # resumes sharing checkpoint.request_id would have the first
        # finisher silently evict its live sibling and hang that stream.
        # The suffix keeps the original id traceable in logs/checkpoints.
        if request_id is not None:
            rid = request_id
        elif checkpoint.request_id:
            rid = f"{checkpoint.request_id}~r{time.monotonic_ns()}"
        else:
            rid = f"req-{time.monotonic_ns()}"
        tl = self._new_timeline(rid, len(prompt_ids))
        tl.add_event(self._clock.now(), "resume",
                     tokens_salvaged=len(generated))
        req = _QueuedRequest(
            rid, prompt_ids, params, queue,
            adapter_id=self._resolve_adapter(checkpoint.adapter),
            deadline=deadline,
            timeline=tl,
        )
        if generated:
            # replay the detokenizer so continuation text deltas pick up
            # exactly where the drained replica's stream stopped
            detok = IncrementalDetokenizer(self.tokenizer)
            for t in generated:
                detok.push(t)
            req.resume = {
                "generated": generated,
                "detok": detok,
                "stop_texts": list(params.stop or []),
                "pos": len(prompt_ids) + len(generated) - 1,
                "admitted_at": self._next_admission_seq(),
                "kv": None,  # cross-replica: always re-prefill
            }
        self.resume_count += 1
        GENERATION_RESUMES.labels(model_name=self._mlabel).inc()
        TOKENS_SALVAGED.labels(model_name=self._mlabel).inc(len(generated))
        return self._submit_and_stream(req)

    # ---------------- engine loop ----------------

    async def _run_loop(self):
        try:
            while not self._stopped:
                did_work = False
                # deadline enforcement: a queued request whose budget died
                # is failed upfront — seating it would burn prefill+decode
                # on an answer nobody is waiting for
                self._drop_expired_waiting()
                # admission: seat waiting requests into free slots.  Paused
                # while draining — anything queued (including KV-pressure
                # preemptions) belongs to drain()'s checkpoint flush, not a
                # re-seat on a replica that is going away.  Under the
                # unified ragged program admission is pure bookkeeping
                # (every request enters as a prefilling slot; its chunks
                # ride the next mixed dispatches); the legacy path
                # dispatches the batched prefill program here.
                admit = self._admit_mixed if self._use_mixed else self._admit_batch
                while (not self._draining and self._waiting
                       and self._free_slot_index() is not None):
                    if not admit():
                        break
                    did_work = True
                self._set_queue_gauge()
                if self._use_mixed:
                    if await self._step_mixed():
                        did_work = True
                else:
                    if self._advance_prefills():
                        did_work = True
                    active = self._active_decode_slots()
                    self._set_occupancy_gauges(active)
                    if active:
                        await self._decode_once()
                        did_work = True
                if did_work:
                    # watchdog heartbeat: the loop completed an iteration
                    # that moved work forward (admission, prefill chunk,
                    # or a routed dispatch)
                    self._note_progress()
                if not did_work:
                    self._wake.clear()
                    await self._wake.wait()
                else:
                    # yield to the event loop so streams flush between steps
                    await asyncio.sleep(0)
        except Exception as e:  # noqa: BLE001 — engine death must surface
            logger.exception("engine loop crashed")
            self._pipeline_busy = False  # frees must not defer post-mortem
            for slot in self._slots:
                if slot.request_id is not None:
                    slot.queue.put_nowait(e)
                    self._record_terminal(slot.timeline, "error")
                    # release the seat's pages: the allocator outlives the
                    # loop (stop() can no longer evict a reset slot)
                    self._free_pages(slot.pages)
                    slot.reset()
            for req in self._waiting:
                req.queue.put_nowait(e)
                self._record_terminal(req.timeline, "error")
            self._waiting.clear()
            self._set_queue_gauge()
            # requests a crashed _admit_batch popped but never seated: fail
            # their streams and release the pages admission allocated
            for _, req, pages, _, _ in self._admitting:
                self.allocator.free(pages)
                req.queue.put_nowait(e)
                self._record_terminal(req.timeline, "error")
            self._admitting = []

    def _drop_expired_waiting(self) -> None:
        """Fail queued requests whose propagated deadline expired before a
        slot freed up (504 at the protocol layer); spilled resume KV is
        released back to the tier store."""
        kept: List[_QueuedRequest] = []
        for req in self._waiting:
            if req.deadline is None or not req.deadline.expired:
                kept.append(req)
                continue
            self._discard_resume_kv(req)
            DEADLINE_REJECTED.labels(component="engine").inc()
            req.queue.put_nowait(DeadlineExceededError(
                f"request {req.request_id} deadline expired while queued"
            ))
            self._record_terminal(req.timeline, "error")
        if len(kept) != len(self._waiting):
            self._waiting = kept
            self._set_queue_gauge()

    def _free_slot_index(self) -> Optional[int]:
        for i, slot in enumerate(self._slots):
            if slot.request_id is None:
                return i
        return None

    def _bucket_for(self, n: int) -> int:
        for b in self.config.prefill_buckets:
            if n <= b:
                return b
        return self.config.prefill_buckets[-1]

    def _admit_batch(self) -> bool:
        """Prefill up to `prefill_batch` waiting requests in ONE compiled
        call (padded to the widest TAIL bucket among them); False when no
        request can be admitted (no slots / no pages).

        Each row carries its own chunk_start, so prefix-cache hits stay
        BATCHED: a row with cached pages prefills only its uncached tail
        while attending to the cached history.  sp>1 engines use the fused
        ring-attention prefill instead (no cache; whole prompt per row)."""
        use_fused = self.config.sp > 1
        ps = self.config.page_size
        chunk_cap = self.config.prefill_buckets[-1]
        admitted: List[tuple] = []  # (slot_index, request, pages, n_cached, seq)
        # aliased (not assigned after the loop) so the run-loop crash
        # handler sees every popped-but-unseated request even when a later
        # iteration raises mid-admission: a prefill or allocation that
        # raises must fail these requests (they are in neither _waiting nor
        # a slot — losing them hangs their streams forever)
        self._admitting = admitted
        free = [i for i, s in enumerate(self._slots) if s.request_id is None]
        while (
            self._waiting
            and free
            and len(admitted) < self.config.prefill_batch
        ):
            req = self._waiting[0]
            has_kv = req.kv_data is not None or (
                req.resume is not None and req.resume["kv"] is not None
            )
            if has_kv:
                if admitted:
                    break  # flush the batched prefill first
                return self._admit_injected(req)
            seq = (
                req.prompt_ids + req.resume["generated"][:-1]
                if req.resume is not None else req.prompt_ids
            )
            if req.adapter_id < 0 and not use_fused:
                hits, pkeys = self._prefix_cache.lookup_run(seq)
                if self._maybe_page_in(req, pkeys, len(hits)):
                    # tier-resident prefix uploading; hold this request
                    # (decode keeps running) and flush what we have
                    if admitted:
                        break
                    return False
            else:
                hits, pkeys = [], []
            tail = req.kv_len - len(hits) * ps
            if tail > chunk_cap:
                if admitted:
                    break  # flush the batched prefill first
                return self._admit_chunked(req, hits, pkeys)
            need = pages_needed(req.kv_len + 1, ps)
            # pin cache hits before eviction can free them (see
            # _admit_chunked for why this must precede _ensure_allocatable)
            self.allocator.share(hits)
            if not self._prefix_cache.ensure_allocatable(
                self._admission_pages(req, need - len(hits))
            ):
                self.allocator.free(hits)
                break
            # allocate BEFORE popping: if allocate raises, the request is
            # still in _waiting and the crash handler fails it there
            pages = list(hits) + self.allocator.allocate(need - len(hits))
            self._waiting.pop(0)
            if req.timeline is not None:
                req.timeline.mark_admitted(self._clock.now())
            self._count_prefix_hits(pkeys, hits)
            admitted.append((free.pop(0), req, pages, len(hits), seq))
        if not admitted:
            return False

        bucket = self._bucket_for(
            max(len(seq) - c * ps for _, _, _, c, seq in admitted)
        )
        # pad the batch dim to pow2 so the compile cache stays small
        Bp = 1
        while Bp < len(admitted):
            Bp *= 2
        # history-attending chunk prefill only pays off when a row actually
        # HAS history: cold batches take the fused program (no masked
        # history gather, on-device prompt mask, single dispatch)
        use_fused_call = use_fused or all(c == 0 for _, _, _, c, _ in admitted)
        tokens = np.zeros((Bp, bucket), np.int32)
        valid = np.zeros((Bp,), np.int32)
        width = (
            self.config.max_pages_per_seq if use_fused_call
            else self.config.page_bucket(
                max(len(pages) for _, _, pages, _, _ in admitted)
            )
        )
        page_ids = np.zeros((Bp, width), np.int32)
        adapter_arr = np.full((Bp,), -1, np.int32)
        params_list = [SamplingParams() for _ in range(Bp)]
        if not use_fused_call:
            chunk_start = np.zeros((Bp,), np.int32)
            in_prompt = np.zeros((Bp, self.model_config.vocab_size), bool)
        for j, (_, req, pages, n_cached, seq) in enumerate(admitted):
            start = n_cached * ps
            tail_tokens = seq[start:]
            tokens[j, : len(tail_tokens)] = tail_tokens
            valid[j] = len(tail_tokens)
            page_ids[j, : len(pages)] = pages
            adapter_arr[j] = req.adapter_id
            params_list[j] = req.params
            if not use_fused_call:
                chunk_start[j] = start
                in_prompt[j, np.asarray(seq, np.int64)] = True
        state = SamplingState.from_params(params_list)
        rng = jax.random.fold_in(self._base_rng, self._next_step())
        # logprob-emitting program variants only when some fresh row asked —
        # ordinary admissions never pay the top_k
        want_lp = any(
            req.resume is None and req.params.logprobs is not None
            for _, req, _, _, _ in admitted
        )
        lp_tuple = None
        prefill_t0 = self._clock.now()
        if use_fused_call:
            prefill_fn = self._prefill_lp_fn if want_lp else self._prefill_fn
            out = prefill_fn(
                self.params,
                jnp.asarray(tokens),
                jnp.asarray(valid),
                self.kv_pages,
                jnp.asarray(page_ids),
                state,
                rng,
                jnp.asarray(adapter_arr),
            )
            if want_lp:
                first, lp_tuple, self.kv_pages = out
            else:
                first, self.kv_pages = out
        else:
            logits, self.kv_pages = self._prefill_chunk_fn(
                self.params,
                jnp.asarray(tokens),
                jnp.asarray(chunk_start),
                jnp.asarray(valid),
                self.kv_pages,
                jnp.asarray(page_ids),
                jnp.asarray(adapter_arr),
            )
            if want_lp:
                first, lp_tuple = self._sample_first_lp_fn(
                    logits, state, rng, jnp.asarray(in_prompt)
                )
            else:
                first = self._sample_first_fn(
                    logits, state, rng, jnp.asarray(in_prompt)
                )
        first_np = self._fetch(first)
        lp_np = (
            tuple(self._fetch(a) for a in lp_tuple)
            if lp_tuple is not None else None
        )
        prefill_t1 = self._clock.now()
        ENGINE_PREFILL_CHUNK_DURATION.labels(model_name=self._mlabel).observe(
            prefill_t1 - prefill_t0)
        self.telemetry.record_prefill_chunk(prefill_t1 - prefill_t0)
        for j, (idx, req, pages, _, seq) in enumerate(admitted):
            if req.timeline is not None:
                req.timeline.mark_prefill_start(prefill_t0)
                req.timeline.mark_prefill_end(prefill_t1)
            if req.resume is None:
                # resume re-prefills are recompute overhead, not new prompt
                # traffic — don't double-count them
                PROMPT_TOKENS.labels(model_name=self._mlabel).inc(len(seq))
            slot = self._slots[idx]
            if req.resume is not None:
                # stream state survives preemption; the re-prefill's sampled
                # token is discarded (the real next token comes from decode)
                self._seat_resumed(slot, req, pages)
                self._mark_penalty_dirty(idx)
                continue
            first_token = int(first_np[j])
            self._seat_fresh(slot, req, pages, first_token)
            if req.adapter_id < 0:
                self._prefix_cache.register(req.prompt_ids, pages)
            self._mark_penalty_dirty(idx)
            self._emit(slot, first_token, *self._lp_for(req.params, lp_np, j))
        self._admitting = []
        return True

    @staticmethod
    def _lp_for(params: SamplingParams, lp_np, j: int, s: Optional[int] = None):
        """(logprob, top_logprobs) for row j (step s) of a device lp tuple,
        sliced to the request's asked-for top-k; (None, None) when the
        request didn't ask or the chunk didn't compute them."""
        if lp_np is None or params.logprobs is None:
            return None, None
        lp, tv, ti = lp_np
        if s is not None:
            lp, tv, ti = lp[s], tv[s], ti[s]
        k = min(int(params.logprobs), tv.shape[-1])
        top = [(int(ti[j, i]), float(tv[j, i])) for i in range(k)]
        return float(lp[j]), top

    def _seat_fresh(self, slot: _Slot, req: "_QueuedRequest",
                    pages: List[int], first_token: int) -> None:
        """Single source of truth for seating a freshly-prefilled request —
        the batched, chunked and injected admission paths all use it."""
        n_prompt = len(req.prompt_ids)
        slot.request_id = req.request_id
        slot.prompt_len = n_prompt
        slot.prompt_ids = req.prompt_ids
        slot.pages = pages
        slot.pos = n_prompt  # position of the token being decoded next
        slot.generated = [first_token]
        slot.params = req.params
        slot.queue = req.queue
        slot.detok = IncrementalDetokenizer(self.tokenizer)
        slot.stop_texts = list(req.params.stop or [])
        slot.admitted_at = self._next_admission_seq()
        slot.adapter_id = req.adapter_id
        slot.deadline = req.deadline
        slot.timeline = req.timeline

    @property
    def prefix_cache_hits(self) -> int:
        """Pages reused via the prefix cache (observability/tests)."""
        return self._prefix_cache.hits

    def _active_decode_slots(self) -> List[_Slot]:
        return [
            s for s in self._slots
            if s.request_id is not None and s.prefilling is None
        ]

    def _set_occupancy_gauges(self, active: List[_Slot]) -> None:
        ENGINE_BATCH_OCCUPANCY.labels(model_name=self._mlabel).set(len(active))
        ENGINE_KV_PAGES_FREE.labels(model_name=self._mlabel).set(
            self.allocator.free_pages
        )
        self._set_composition_gauge(len(active))

    def _admit_mixed(self) -> bool:
        """Admission under the unified ragged program: requests with
        host-resident KV (P/D transfer, tier-store resume) take the inject
        path; everything else seats as a prefilling slot whose chunks —
        whether one covering the whole prompt or many — ride the mixed
        dispatches.  No prefill program runs here."""
        req = self._waiting[0]
        has_kv = req.kv_data is not None or (
            req.resume is not None and req.resume["kv"] is not None
        )
        if has_kv:
            return self._admit_injected(req)
        return self._admit_prefilling(req)

    def _admit_chunked(self, req: "_QueuedRequest",
                       hits: Optional[List[int]] = None,
                       keys: Optional[List[bytes]] = None) -> bool:
        """Admit one long-prompt request by chunked prefill (legacy path:
        the run loop advances its chunks through the prefill_chunk
        program).  Unblocks prompts up to max_model_len without sequence
        parallelism."""
        return self._admit_prefilling(req, hits, keys)

    def _admit_prefilling(self, req: "_QueuedRequest",
                          hits: Optional[List[int]] = None,
                          keys: Optional[List[bytes]] = None) -> bool:
        """Seat one request as a prefilling slot: allocate its pages (with
        prefix-cache hits pinned), pop it from the queue, and record the
        chunk cursor.  Shared by the legacy chunked admission and by EVERY
        mixed-mode admission (where even short prompts are a single chunk
        riding the next mixed dispatch)."""
        idx = self._free_slot_index()
        if idx is None:
            return False
        total = req.kv_len
        need = pages_needed(total + 1, self.config.page_size)
        if need > self.config.max_pages_per_seq:
            self._waiting.remove(req)
            self._set_queue_gauge()
            req.queue.put_nowait(ValueError(
                f"prompt needs {need} pages > max_pages_per_seq "
                f"{self.config.max_pages_per_seq}"
            ))
            self._record_terminal(req.timeline, "error")
            return True
        if req.resume is not None:
            seq = req.prompt_ids + req.resume["generated"][:-1]
        else:
            seq = req.prompt_ids
        # LoRA adapters produce adapter-specific KV: only base-model
        # requests share the prefix cache
        if hits is None:
            if req.adapter_id < 0:
                hits, keys = self._prefix_cache.lookup_run(seq)
                if self._maybe_page_in(req, keys, len(hits)):
                    return False  # tier pages uploading; retried on wake
            else:
                hits = []
        cached = list(hits)
        # take our reference BEFORE eviction runs: eviction may drop these
        # pages from the cache, but a live ref keeps them off the free list
        # (evicted-then-shared pages would otherwise be re-allocated while
        # this sequence reads them)
        self.allocator.share(cached)
        fresh_needed = need - len(cached)
        # decode headroom only for genuinely long admissions (many chunks
        # in flight before first token) — a short mixed-mode admission
        # must not demand more pages than the legacy batched path did
        headroom = (
            total - len(cached) * self.config.page_size
            > self.config.prefill_buckets[-1]
        )
        if not self._prefix_cache.ensure_allocatable(
            self._admission_pages(req, fresh_needed, headroom=headroom)
        ):
            self.allocator.free(cached)  # release the early reference
            return False
        # allocate BEFORE popping: if allocate raises, the request is still
        # in _waiting and the crash handler fails it there (everything after
        # this is infallible python bookkeeping until the slot — whose queue
        # the handler covers — owns the request)
        pages = cached + self.allocator.allocate(fresh_needed)
        self._waiting.remove(req)
        self._set_queue_gauge()
        if req.timeline is not None:
            req.timeline.mark_admitted(self._clock.now())
        self._count_prefix_hits(keys or [], cached)
        # the slot enters "prefilling" state immediately and the run loop
        # advances ONE chunk per iteration — in-flight decode streams keep
        # emitting between chunks, and the queue behind this request isn't
        # head-of-line blocked for its whole prefill
        slot = self._slots[idx]
        slot.request_id = req.request_id
        slot.pages = pages
        slot.queue = req.queue  # engine-crash propagation needs the stream
        slot.prefilling = {
            "req": req,
            "seq": seq,
            "done": len(cached) * self.config.page_size,
            "logits": None,
        }
        return True

    def _advance_prefills(self) -> bool:
        """One chunk of progress for every prefilling slot; completes slots
        whose prompt is fully prefilled (sampling the first token)."""
        progressed = False
        chunk_cap = self.config.prefill_buckets[-1]
        for idx, slot in enumerate(self._slots):
            pf = slot.prefilling
            if slot.request_id is None or pf is None:
                continue
            seq, done = pf["seq"], pf["done"]
            total = len(seq)
            if done < total:
                n = min(chunk_cap, total - done)
                bucket = self._bucket_for(n)
                tokens = np.zeros((1, bucket), np.int32)
                tokens[0, :n] = seq[done : done + n]
                page_ids = np.zeros((self.config.max_pages_per_seq,), np.int32)
                page_ids[: len(slot.pages)] = slot.pages
                # table width must cover this chunk's writes (the history
                # gather reads the same table, masked by history length)
                width = self.config.page_bucket(
                    pages_needed(done + n, self.config.page_size)
                )
                chunk_t0 = self._clock.now()
                tl = pf["req"].timeline
                if tl is not None:
                    tl.mark_prefill_start(chunk_t0)
                pf["logits"], self.kv_pages = self._prefill_chunk_fn(
                    self.params,
                    jnp.asarray(tokens),
                    jnp.asarray(np.asarray([done], np.int32)),
                    jnp.asarray(np.asarray([n], np.int32)),
                    self.kv_pages,
                    jnp.asarray(page_ids[None, :width]),
                    jnp.asarray(np.asarray([pf["req"].adapter_id], np.int32)),
                )
                pf["done"] = done + n
                chunk_t1 = self._clock.now()
                ENGINE_PREFILL_CHUNK_DURATION.labels(
                    model_name=self._mlabel).observe(chunk_t1 - chunk_t0)
                self.telemetry.record_prefill_chunk(chunk_t1 - chunk_t0)
                if tl is not None:
                    tl.mark_prefill_end(chunk_t1)
                if pf["req"].adapter_id < 0 and pf["req"].resume is None:
                    # register only the pages COMPLETED by this chunk — a
                    # full re-register would re-hash the whole prefix per
                    # chunk (O(L^2) host work on the engine loop)
                    covered = min(pf["done"], len(pf["req"].prompt_ids))
                    self._prefix_cache.register(
                        pf["req"].prompt_ids[:covered],
                        slot.pages,
                        start_page=pf.get("registered", 0),
                    )
                    pf["registered"] = covered // self.config.page_size
                progressed = True
            if pf["done"] >= total:
                self._finish_prefilling(idx, slot, pf)
                progressed = True
        return progressed

    def _complete_prefilling(self, idx: int, slot: _Slot, req,
                             first_token: Optional[int],
                             lp: tuple = (None, None)) -> None:
        """A prefilling slot's prompt is fully in the cache: seat it and
        (fresh path) emit its first token.  The single completion path
        shared by the legacy chunk loop (_finish_prefilling, which samples
        the token itself) and the mixed route (where the token is the
        dispatch's step-0 sample) — the two dispatchers must not drift."""
        pages = slot.pages
        slot.prefilling = None
        if req.resume is not None:
            if req.adapter_id < 0:
                # non-resume prompts registered incrementally per chunk;
                # the resume path registers its prompt prefix once here
                self._prefix_cache.register(req.prompt_ids, pages)
            self._seat_resumed(slot, req, pages)
            self._mark_penalty_dirty(idx)
            return
        PROMPT_TOKENS.labels(model_name=self._mlabel).inc(
            len(req.prompt_ids))
        self._seat_fresh(slot, req, pages, first_token)
        self._mark_penalty_dirty(idx)
        self._emit(slot, first_token, *lp)

    def _finish_prefilling(self, idx: int, slot: _Slot, pf: dict) -> None:
        req = pf["req"]
        if req.resume is not None:
            self._complete_prefilling(idx, slot, req, None)
            return
        seq = pf["seq"]
        state = SamplingState.from_params([req.params])
        rng = jax.random.fold_in(self._base_rng, self._next_step())
        in_prompt = np.zeros((1, self.model_config.vocab_size), bool)
        in_prompt[0, np.asarray(seq, np.int64)] = True
        lp_np = None
        if req.params.logprobs is not None:
            first, lp_tuple = self._sample_first_lp_fn(
                pf["logits"], state, rng, jnp.asarray(in_prompt)
            )
            lp_np = tuple(np.asarray(a) for a in lp_tuple)
        else:
            first = self._sample_first_fn(
                pf["logits"], state, rng, jnp.asarray(in_prompt)
            )
        first_token = int(self._fetch(first)[0])
        self._complete_prefilling(
            idx, slot, req, first_token,
            self._lp_for(req.params, lp_np, 0))

    def _admission_pages(self, req: "_QueuedRequest", need: int,
                         headroom: bool = False) -> int:
        """Pages that must be free to admit.  Resumes and long chunked
        admissions additionally require a couple of chunks of decode
        headroom (capped at what the cache can ever provide) — admitting
        into an immediately-starving cache would just bounce the work back
        out (KV ping-pong for resumes, aborted prefills for long prompts)."""
        if req.resume is None and not headroom:
            return need
        extra = pages_needed(2 * self.config.steps_per_sync, self.config.page_size)
        return min(need + extra, self.config.num_pages - 1)

    def _seat_resumed(self, slot: _Slot, req: "_QueuedRequest", pages: List[int]) -> None:
        r = req.resume
        slot.request_id = req.request_id
        slot.prompt_len = len(req.prompt_ids)
        slot.prompt_ids = req.prompt_ids
        slot.pages = pages
        slot.pos = r["pos"]
        slot.generated = r["generated"]
        slot.params = req.params
        slot.queue = req.queue
        slot.detok = r["detok"]
        slot.stop_texts = r["stop_texts"]
        slot.admitted_at = r["admitted_at"]
        slot.adapter_id = req.adapter_id
        slot.deadline = req.deadline
        slot.timeline = req.timeline
        if req.timeline is not None:
            req.timeline.mark_admitted(self._clock.now())

    def _admit_injected(self, req: "_QueuedRequest") -> bool:
        """Admit a request whose KV already exists on host: either P/D
        transfer from a prefill peer (seat at pos=len(prompt), emit the
        peer's first token) or a preemption resume from the host tier
        (restore the full stream state, emit nothing)."""
        idx = self._free_slot_index()
        if idx is None:
            return False
        total = req.kv_len
        need = pages_needed(total + 1, self.config.page_size)
        if need > self.config.max_pages_per_seq:
            return False
        if not self._prefix_cache.ensure_allocatable(self._admission_pages(req, need)):
            return False
        # fetch AFTER the capacity checks — get() consumes the spill, and a
        # transient no-capacity return must leave it stored
        if req.resume is not None:
            payload = (self._kv_store.get(req.resume["kv"])
                       if self._kv_store is not None else None)
            if payload is None:
                # dropped under tier pressure: recompute on the normal
                # re-prefill path (returning True = progress; the next
                # admission pass takes the prefill branch)
                req.resume["kv"] = None
                self._set_offload_gauges()
                return True
            self._set_offload_gauges()
        else:
            payload = {"kv": req.kv_data}
        quantized = "kv_q" in payload
        kv = payload["kv_q"] if quantized else payload["kv"]
        # allocate BEFORE popping (a raise leaves req in _waiting for the
        # crash handler), then register the popped request in _admitting so
        # a device inject that raises fails this stream instead of hanging
        # it — same contract as the batched-prefill path
        pages = self.allocator.allocate(need)
        self._waiting.remove(req)
        self._set_queue_gauge()
        if req.timeline is not None:
            req.timeline.mark_admitted(self._clock.now())
            req.timeline.mark_prefill_start(self._clock.now())
        entry = (idx, req, pages, 0, None)
        self._admitting.append(entry)
        P = kv.shape[1]
        # pad the page dim to the standard width buckets (small compile cache)
        bucket = self.config.page_bucket(P)
        ids = np.zeros((bucket,), np.int32)
        ids[:P] = pages[:P]

        def pad(arr):
            out = np.zeros(arr.shape[:1] + (bucket,) + arr.shape[2:], arr.dtype)
            out[:, :P] = arr
            return out

        if quantized:
            self.kv_pages = self._inject_q_fn(
                self.kv_pages, jnp.asarray(pad(kv)),
                jnp.asarray(pad(payload["kv_s"])), jnp.asarray(ids)
            )
        else:
            self.kv_pages = self._inject_fn(
                self.kv_pages, jnp.asarray(pad(kv)), jnp.asarray(ids)
            )
        if req.timeline is not None:
            # KV injection replaces prefill for this request (P/D transfer
            # or tier-store resume): the scatter IS its prefill phase
            req.timeline.mark_prefill_end(self._clock.now())
        slot = self._slots[idx]
        if req.resume is not None:
            self._seat_resumed(slot, req, pages)
            self._admitting.remove(entry)
            self._mark_penalty_dirty(idx)
            return True
        self._seat_fresh(slot, req, pages, req.first_token)
        self._admitting.remove(entry)
        PROMPT_TOKENS.labels(model_name=self._mlabel).inc(len(req.prompt_ids))
        self._mark_penalty_dirty(idx)
        self._emit(slot, req.first_token)
        return True

    def _ensure_pages_at(self, slot: _Slot, base: int, extra: int) -> bool:
        """Best-effort grow of the slot's page list toward positions
        base..base+extra-1 (capped at the per-seq limit); partial growth is
        kept — the chunk capacity mask lets a lane run however many steps
        its pages cover.  Returns True when the full range is covered."""
        needed = min(
            pages_needed(base + extra, self.config.page_size),
            self.config.max_pages_per_seq,
        )
        while len(slot.pages) < needed and self.allocator.can_allocate(1):
            slot.pages.extend(self.allocator.allocate(1))
        return len(slot.pages) >= pages_needed(base + extra, self.config.page_size)

    def _grow_and_preempt(self) -> None:
        """Before an unchained chunk: grow every active slot's pages toward
        the chunk's writes; on allocator exhaustion, preempt the NEWEST
        non-oldest slot back to the queue (freeing its pages) and retry.
        The oldest slot is never preempted, so it always finishes — liveness.
        A single slot that exhausts the whole cache alone is truncated
        honestly (config smaller than one max-length sequence)."""
        # worst-case advance of ONE dispatch: steps_per_sync tokens on the
        # plain paths, steps_per_sync * (K+1) under speculative decoding
        # (every round accepts everything)
        steps = self._max_step_advance
        ps = self.config.page_size
        # chaos seam (resilience/faults.py): a "preempt" spec targeting
        # "engine.preempt" forcibly requeues the newest active sequence —
        # the deterministic stand-in for spot/KV-pressure preemption the
        # drain/resume chaos tests fire under FakeClock
        if self.fault_plan is not None:
            spec = self.fault_plan.decide("engine.preempt")
            if spec is not None and spec.kind == "preempt":
                victims = [
                    s for s in self._slots
                    if s.request_id is not None and s.prefilling is None
                ]
                if victims:
                    self._preempt(max(victims, key=lambda s: s.admitted_at))
        while True:
            active = [
                s for s in self._slots
                if s.request_id is not None and s.prefilling is None
            ]
            if not active:
                return
            starved = []
            for slot in active:
                base = slot.pos
                if base >= self.config.max_model_len:
                    continue  # finished as "length" in _prepare_chunk
                grow = min(steps, self.config.max_model_len - base)
                self._ensure_pages_at(slot, base, grow)
                if len(slot.pages) * ps <= base:
                    starved.append(slot)
            if not starved:
                return
            # cold cached pages go before anyone gets preempted
            if self._prefix_cache.ensure_allocatable(1):
                continue
            # a long admission still prefilling is the preferred victim: it
            # has emitted nothing, its pages requeue cleanly, and truncating
            # a LIVE decode stream to protect it would be backwards
            prefilling = [
                s for s in self._slots
                if s.request_id is not None and s.prefilling is not None
            ]
            if prefilling:
                self._preempt_prefilling(prefilling[-1])
                continue
            oldest = min(active, key=lambda s: s.admitted_at)
            candidates = [
                s for s in active if s is not oldest and self._can_preempt(s)
            ]
            if not candidates:
                # nothing can legally be preempted (kv_offload contract:
                # "none"/exhausted budget must not pin host RAM, and a
                # too-long sequence can't re-prefill)
                if len(starved) < len(active):
                    # other lanes are still decoding and will free pages on
                    # finish; starved lanes pause (capacity mask) and retry
                    return
                for s in starved:
                    if self._draining:
                        # mid-drain, a starved lane must not be truncated
                        # with a dishonest "length": checkpoint it so a
                        # healthy replica finishes the generation
                        ckpt = self._checkpoint_slot(s, "preempt")
                        self._evict_slot(s, GenerationPreempted(ckpt))
                    else:
                        self._finish(s, "length")  # no page source left anywhere
                continue
            self._preempt(max(candidates, key=lambda s: s.admitted_at))

    def _can_preempt(self, slot: _Slot) -> bool:
        """Every slot has a resume path now: chunked re-prefill covers any
        length, and the host tier (when budgeted) avoids the recompute."""
        return True

    def _preempt_prefilling(self, slot: _Slot) -> None:
        """Abort an in-progress long admission: requeue its request (front)
        and free its pages.  Nothing was emitted, so nothing is lost but
        the chunks already computed."""
        req = slot.prefilling["req"]
        if req.timeline is not None:
            req.timeline.add_event(self._clock.now(), "preempt",
                                   phase="prefill")
        self._free_pages(slot.pages)
        self._mark_penalty_dirty(self._slots.index(slot))
        slot.reset()
        self._waiting.insert(0, req)
        self._set_queue_gauge()
        self.preemption_count += 1
        ENGINE_PREEMPTIONS.labels(model_name=self._mlabel).inc()
        logger.info("preempted prefilling request %s", req.request_id)

    def _preempt(self, slot: _Slot) -> None:
        """Requeue a running slot (front of queue), freeing its pages.  With
        the host tier enabled (and budget left) its KV spills to host RAM
        and re-injects on resume; otherwise resume re-prefills
        prompt+generated[:-1].  Nothing is emitted — the client stream just
        pauses.  Parity: vLLM preemption + KVCacheOffloadingSpec
        (llm_inference_service_types.go:188-232)."""
        pos = slot.pos  # KV on device covers positions 0..pos-1
        P = pages_needed(pos, self.config.page_size)
        kv_key = None
        nbytes = (
            P * self.model_config.n_layers * self.cache_config.bytes_per_page()
        )
        # spill into the tier store when it can fit; otherwise chunked
        # re-prefill recomputes the KV on resume.  Quantized caches spill
        # both tensors (int8 pages + scales) as one payload.  Mid-drain the
        # spill is skipped outright: the drain loop checkpoints the requeued
        # request on its next pass and discards any resume KV (resume is
        # cross-replica, always re-prefilled), so the device fetch would
        # only burn drain budget and stall the loop for zero benefit.
        if (
            self._kv_store is not None
            and not self._draining
            and self._kv_store.would_fit(nbytes)
        ):
            payload = {
                name: self._fetch(v)
                for name, v in self._gather_pages_device(
                    slot.pages[:P]).items()
            }
            if self._kv_store.put(slot.request_id, payload):
                kv_key = slot.request_id
            self._set_offload_gauges()
        req = _QueuedRequest(slot.request_id, slot.prompt_ids, slot.params, slot.queue,
                             adapter_id=slot.adapter_id, deadline=slot.deadline,
                             timeline=slot.timeline)
        if slot.timeline is not None:
            slot.timeline.add_event(
                self._clock.now(), "preempt", pos=pos,
                spilled=kv_key is not None)
        req.resume = {
            "generated": slot.generated,
            "detok": slot.detok,
            "stop_texts": slot.stop_texts,
            "pos": pos,
            "admitted_at": slot.admitted_at,
            # the spill, if stored, lives in the tier store under this key
            # (None = recompute on resume)
            "kv": kv_key,
        }
        self._free_pages(slot.pages)
        self._mark_penalty_dirty(self._slots.index(slot))
        slot.reset()
        self._waiting.insert(0, req)
        self._set_queue_gauge()
        self.preemption_count += 1
        ENGINE_PREEMPTIONS.labels(model_name=self._mlabel).inc()
        logger.info(
            "preempted %s at pos=%d (%s)", req.request_id, pos,
            "KV spilled to tier store" if kv_key is not None
            else "will re-prefill",
        )

    def _free_pages(self, pages: List[int]) -> None:
        """Page frees are deferred while a chained chunk is in flight — a
        reused page could otherwise be written by the stale lanes of the
        in-flight program."""
        if self._pipeline_busy:
            self._deferred_free.extend(pages)
        else:
            self.allocator.free(pages)

    def _flush_deferred_frees(self) -> None:
        if self._deferred_free:
            self.allocator.free(self._deferred_free)
            self._deferred_free = []

    def _prepare_chunk(self, prev: Optional[dict]) -> Optional[dict]:
        """Build host-side inputs for a decode chunk.  `prev` chains the
        chunk after an in-flight one: positions advance speculatively by
        min(steps, prev capacity) without reading prev's tokens."""
        B = self.config.max_batch_size
        steps = self.config.steps_per_sync
        if prev is None:
            # page growth + preemption happen only between pipelines (the KV
            # extraction in _preempt needs no chunk in flight)
            self._grow_and_preempt()
        tokens = np.zeros((B,), np.int32)
        pos = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        capacity = np.zeros((B,), np.int32)
        params_list = [SamplingParams() for _ in range(B)]
        max_owned = 1
        for i, slot in enumerate(self._slots):
            if slot.request_id is None or slot.prefilling is not None:
                continue
            if prev is not None:
                if not prev["active"][i]:
                    continue
                base = min(int(prev["pos"][i]) + steps, int(prev["capacity"][i]))
            else:
                base = slot.pos
                tokens[i] = slot.generated[-1]
            grow = min(self._max_step_advance,
                       self.config.max_model_len - base)
            if grow <= 0:
                if prev is None:
                    self._finish(slot, "length")  # genuinely at max_model_len
                continue
            if prev is not None:
                # best-effort growth for chained chunks; no preemption while
                # the previous chunk is in flight
                self._ensure_pages_at(slot, base, grow)
            if len(slot.pages) * self.config.page_size <= base:
                continue  # no capacity this chunk; retried after the drain
            pos[i] = base
            active[i] = True
            capacity[i] = len(slot.pages) * self.config.page_size
            params_list[i] = slot.params
            max_owned = max(max_owned, len(slot.pages))
        if not active.any():
            return None
        # bucketed page-table width: attention gathers only ~longest-seq pages
        width = self.config.page_bucket(max_owned)
        page_table = np.zeros((B, width), np.int32)
        for i, slot in enumerate(self._slots):
            if slot.request_id is not None and active[i]:
                page_table[i, : len(slot.pages)] = slot.pages
        counters = np.zeros((B,), np.int32)
        adapters = np.full((B,), -1, np.int32)
        for i, slot in enumerate(self._slots):
            if slot.request_id is not None and active[i]:
                # tokens generated when this chunk starts (for seeded lanes)
                counters[i] = int(pos[i]) - slot.prompt_len + 1
                adapters[i] = slot.adapter_id
        # penalized chunks use device-resident [B, V] count/prompt arrays,
        # rebuilt from the host-side slot lists only when batch composition
        # changed; such chunks are never pipeline-chained so the counts are
        # always accurate at dispatch time
        penalized = any(
            slot.request_id is not None and active[i] and slot.params.has_penalties
            for i, slot in enumerate(self._slots)
        )
        if penalized:
            self._refresh_penalty_state()
        want_logprobs = any(
            slot.request_id is not None and active[i]
            and slot.params.logprobs is not None
            for i, slot in enumerate(self._slots)
        )
        return {
            "tokens": tokens,
            "pos": pos,
            "active": active,
            "capacity": capacity,
            "page_table": page_table,
            "counters": counters,
            "adapters": adapters,
            "state": SamplingState.from_params(params_list),
            "penalized": penalized,
            "want_logprobs": want_logprobs,
        }

    def _refresh_penalty_state(self) -> None:
        """Bring the device [B, V] count/prompt arrays up to date.  Rows for
        lanes that stayed resident are already correct on device (the
        penalized decode returns updated counts); only rows touched by
        admission/finish/cancel are re-uploaded — O(changed rows), not O(B)."""
        V = self.model_config.vocab_size
        B = self.config.max_batch_size

        def row_data(i):
            counts_row = np.zeros((V,), np.int32)
            prompt_row = np.zeros((V,), bool)
            slot = self._slots[i]
            # gate on residency, NOT on active[i]: a resident lane skipped
            # from this chunk (KV-page starvation) must keep its counts —
            # zeroing it during a full rebuild would silently drop its
            # penalties for the rest of the request (it is not marked dirty
            # when it reactivates)
            if slot.request_id is not None:
                np.add.at(counts_row, slot.generated, 1)
                prompt_row[slot.prompt_ids] = True
            return counts_row, prompt_row

        if self._penalty_counts is None or self._penalty_dirty_rows is None:
            rows = [row_data(i) for i in range(B)]
            self._penalty_counts = jnp.asarray(np.stack([r[0] for r in rows]))
            self._penalty_prompt = jnp.asarray(np.stack([r[1] for r in rows]))
        elif self._penalty_dirty_rows:
            idx = sorted(self._penalty_dirty_rows)
            rows = [row_data(i) for i in idx]
            at = jnp.asarray(idx)
            self._penalty_counts = self._penalty_counts.at[at].set(
                jnp.asarray(np.stack([r[0] for r in rows]))
            )
            self._penalty_prompt = self._penalty_prompt.at[at].set(
                jnp.asarray(np.stack([r[1] for r in rows]))
            )
        self._penalty_dirty_rows = set()

    def _mark_penalty_dirty(self, slot_index: Optional[int]) -> None:
        """Record a batch-composition change; None invalidates everything.
        The speculative draft table shares the same dirty tracking: any
        seat/finish/preempt that changes a row's occupant must re-seed
        that row from the new occupant's prompt + generated tokens."""
        if slot_index is None:
            self._penalty_dirty_rows = None
            self._draft_dirty = None
        else:
            if self._penalty_dirty_rows is not None:
                self._penalty_dirty_rows.add(slot_index)
            if self._draft_dirty is not None:
                self._draft_dirty.add(slot_index)

    def _refresh_draft_table(self) -> None:
        """Bring the device [B, V] bigram draft table up to date for rows
        whose occupant changed: each dirty row is re-seeded host-side from
        prompt + generated bigrams (later occurrences win — numpy fancy
        assignment applies in order), empty rows reset to -1 (unseen).
        Rows that stayed resident are NOT touched: the device keeps the
        bigrams it learned from accepted tokens between dispatches.

        Every path commits the table to ONE replicated NamedSharding —
        the spelling the program pins its table output to.  A host-fresh
        table (UnspecifiedValue) and a device-output table would
        otherwise be two different jit signatures: one retrace per
        composition change (the kv_pages settle hazard again, pinned by
        tests/test_retrace_budget.py)."""
        if self._spec_k is None or self._spec_k == 0:
            if self._spec_k == 0 and self._draft_table is None:
                # K=0 (dense packing alone): the program never reads the
                # table, but the signature still carries one — a [B, 1]
                # placeholder keeps the dispatch shape static
                self._draft_table = jax.device_put(
                    jnp.zeros((self.config.max_batch_size, 1), jnp.int32),
                    self._table_sharding)
            return
        V = self.model_config.vocab_size
        B = self.config.max_batch_size

        def row_data(i):
            row = np.full((V,), -1, np.int32)
            slot = self._slots[i]
            if slot.request_id is not None and slot.prefilling is None:
                seq = np.asarray(
                    slot.prompt_ids + slot.generated, np.int64)
                if seq.shape[0] >= 2:
                    row[seq[:-1]] = seq[1:]
            return row

        if self._draft_table is None or self._draft_dirty is None:
            self._draft_table = jnp.asarray(
                np.stack([row_data(i) for i in range(B)]))
        elif self._draft_dirty:
            idx = sorted(self._draft_dirty)
            rows = np.stack([row_data(i) for i in idx])
            self._draft_table = self._draft_table.at[
                jnp.asarray(idx)].set(jnp.asarray(rows))
        self._draft_table = jax.device_put(
            self._draft_table, self._table_sharding)
        self._draft_dirty = set()

    @property
    def _replicated_sharding(self):
        """The canonical replicated NamedSharding small per-lane control
        arrays commit to before a mixed_decode dispatch, matching the
        program's pinned output spelling (one jit signature whether the
        array came from the host or from a previous dispatch's carry)."""
        return shd.named(self.mesh, jax.sharding.PartitionSpec())

    @property
    def _table_sharding(self):
        """Commit target for the draft table: the spelling GSPMD settles
        the mixed_decode table output on (parallel/sharding.py
        draft_table_pspec) — refresh-built and dispatch-output tables
        must share one jit signature."""
        return shd.named(self.mesh, shd.draft_table_pspec())

    def _dispatch_chunk(self, meta: dict, tokens_dev=None):
        """Launch one decode chunk (async); tokens_dev chains the previous
        chunk's device-resident last tokens, skipping a host round-trip."""
        meta["_dispatched_at"] = self._clock.now()
        rng = jax.random.fold_in(self._base_rng, self._next_step())
        tokens = tokens_dev if tokens_dev is not None else jnp.asarray(meta["tokens"])
        args = (
            self.params,
            tokens,
            jnp.asarray(meta["pos"]),
            self.kv_pages,
            jnp.asarray(meta["page_table"]),
            jnp.asarray(meta["active"]),
            jnp.asarray(meta["capacity"]),
            jnp.asarray(meta["counters"]),
            meta["state"],
            rng,
            jnp.asarray(meta["adapters"]),
        )
        want_lp = meta.get("want_logprobs", False)
        if meta.get("penalized"):
            fn = self._decode_penalized_lp_fn if want_lp else self._decode_penalized_fn
            chunk, self.kv_pages, self._penalty_counts = fn(
                *args, self._penalty_prompt, self._penalty_counts
            )
        else:
            fn = self._decode_lp_fn if want_lp else self._decode_fn
            chunk, self.kv_pages = fn(*args)
            if self._penalty_counts is not None:
                # a non-penalized chunk advances lanes without updating the
                # device counts; they are stale for every resident row now
                self._mark_penalty_dirty(None)
        return chunk

    async def _route_chunk(self, meta: dict, chunk) -> bool:
        """Read a finished chunk and stream its tokens.  True when any slot
        finished (the pipeline must drain: chained lanes are stale).  Async
        because the fetch awaits the device (loop stays responsive); slot
        state is only mutated in the sync stretch after the fetches, so a
        drain evicting a slot during the await is observed (request_id
        None) rather than raced."""
        steps = self.config.steps_per_sync
        if isinstance(chunk, tuple):  # logprobs variant: (tokens, lp, tv, ti)
            chunk_np = await self._fetch_async(chunk[0])  # [steps, B]
            lp_np = tuple([await self._fetch_async(a) for a in chunk[1:]])
        else:
            chunk_np = await self._fetch_async(chunk)  # [steps, B]
            lp_np = None
        step_s = self._clock.now() - meta["_dispatched_at"]
        ENGINE_STEP_DURATION.labels(model_name=self._mlabel).observe(step_s)
        self.telemetry.record_step(step_s)
        active = meta["active"]
        finished_any = False
        routed = 0  # tokens actually delivered — the speculative tail after
        # a mid-chunk EOS/stop is discarded and must not count as generated
        for i, slot in enumerate(self._slots):
            if slot.request_id is None or not active[i]:
                continue
            lane_steps = min(steps, int(meta["capacity"][i]) - int(meta["pos"][i]))
            for s in range(lane_steps):
                if slot.request_id is None:
                    break  # finished mid-chunk; discard speculative tail
                token = int(chunk_np[s, i])
                slot.pos += 1
                slot.generated.append(token)
                self._emit(slot, token, *self._lp_for(slot.params, lp_np, i, s))
                routed += 1
            if slot.request_id is None:
                finished_any = True
            elif slot.pos >= self.config.max_model_len:
                self._finish(slot, "length")
                finished_any = True
        GENERATED_TOKENS.labels(model_name=self._mlabel).inc(routed)
        if routed or finished_any:
            # stamp here, not only in the run loop: the depth-2 pipeline
            # can chain chunks for a long stretch without returning to it
            self._note_progress()
        return finished_any

    async def _decode_once(self):
        """Decode with a depth-2 dispatch pipeline: chunk N+1 launches
        (chained on N's device tokens) before N's tokens are fetched, so the
        host round-trip hides behind device compute."""
        meta = self._prepare_chunk(prev=None)
        if meta is None:
            return
        chunk = self._dispatch_chunk(meta)
        while True:
            meta2 = None
            chunk2 = None
            # chain when admission couldn't run anyway (no waiting work, or
            # no free slot to admit into) and no lane is guaranteed to finish
            # inside the in-flight chunk (a predictable max_tokens finish
            # would force a drain, wasting the whole chained chunk)
            admission_blocked = (
                not self._waiting or self._free_slot_index() is None
            )
            prefill_pending = any(
                s.prefilling is not None for s in self._slots
            )
            predictable_finish = any(
                s.request_id is not None
                and meta["active"][i]
                and len(s.generated) + self.config.steps_per_sync
                >= s.params.max_tokens
                for i, s in enumerate(self._slots)
            )
            if (
                admission_blocked
                and not predictable_finish
                and not prefill_pending  # alternate with prefill chunks
                and not meta.get("penalized")
                # draining: no chaining — the drain loop must observe the
                # budget (and the preempt fault seam must run) between
                # every chunk, not once per arbitrarily long pipeline
                and not (self._stopped or self._draining)
            ):
                meta2 = self._prepare_chunk(prev=meta)
            if meta2 is not None:
                last_tokens = (
                    chunk[0][-1] if isinstance(chunk, tuple) else chunk[-1]
                )
                chunk2 = self._dispatch_chunk(meta2, tokens_dev=last_tokens)
                self._pipeline_busy = True
            finished_any = await self._route_chunk(meta, chunk)
            # flush streams while the chained chunk runs on device
            await asyncio.sleep(0)
            if chunk2 is None:
                break
            meta, chunk = meta2, chunk2
            if finished_any or self._stopped or self._draining or (
                self._waiting and self._free_slot_index() is not None
            ):
                # in-flight chunk has stale lanes (or admission can now
                # proceed); drain and re-plan
                self._pipeline_busy = False
                await self._route_chunk(meta, chunk)
                break
        self._pipeline_busy = False
        self._flush_deferred_frees()

    # ---------------- unified ragged (mixed) stepping ----------------

    def _needs_legacy_step(self) -> bool:
        """Per-iteration fallback gate: the mixed program covers neither
        per-step logprobs nor sampling penalties (engine/compiled.py), so
        an iteration with any such lane seated runs the legacy dispatches
        — chunked prefill via prefill_chunk, decode via the penalized /
        logprob program variants."""
        for s in self._slots:
            if s.request_id is None:
                continue
            p = (s.prefilling["req"].params if s.prefilling is not None
                 else s.params)
            if p.has_penalties or p.logprobs is not None:
                return True
        return False

    async def _step_mixed(self) -> bool:
        """One engine step under the unified ragged program
        (docs/kernels.md): every prefilling slot contributes its next
        prompt chunk and every decode lane its next token slice — ONE
        device dispatch per step, so decode lanes keep advancing while
        prompts prefill (the prefill/decode scheduler barrier the legacy
        paths worked around).  Lanes whose prompt completes inside the
        dispatch seat and keep decoding in the same program (the scan
        tail), so a short request can prefill AND decode its whole budget
        in a single dispatch."""
        if self._needs_legacy_step():
            did = self._advance_prefills()
            active = self._active_decode_slots()
            self._set_occupancy_gauges(active)
            if active:
                await self._decode_once()
                did = True
            return did
        meta = self._prepare_chunk(prev=None)
        prefilling = [
            (i, s) for i, s in enumerate(self._slots)
            if s.request_id is not None and s.prefilling is not None
        ]
        self._set_occupancy_gauges(self._active_decode_slots())
        if meta is None and not prefilling:
            return False
        if self._dense_ok and not prefilling and meta is not None:
            # pure-decode step with the dense/speculative program
            # available: every lane packs a (K+1)-token slice at the
            # dense stride, K draft tokens verify per round, and the
            # next dispatch chains on this one's device carries
            # (docs/kernels.md) — the decode-heavy fast path.  A lane
            # within K tokens of its hard kv ceiling can never fit
            # another full (K+1)-token slice: the whole batch runs the
            # plain mixed path for that lane's final stretch (<= K+1
            # tokens, token-identical) instead of dispatching rounds the
            # device would skip forever.
            kp = (self._spec_k or 0) + 1
            if all(
                s.request_id is None or not meta["active"][i]
                or s.pos + kp <= self._dense_lane_cap
                for i, s in enumerate(self._slots)
            ):
                await self._step_dense(meta)
                return True
        plan = self._plan_ragged(meta, prefilling)
        dispatched_at = self._clock.now()
        rng = jax.random.fold_in(self._base_rng, self._next_step())
        out, self.kv_pages = self._mixed_fn(
            self.params,
            jnp.asarray(plan["q_tokens"]),
            jnp.asarray(plan["token_seq"]),
            jnp.asarray(plan["token_pos"]),
            jnp.asarray(plan["q_start"]),
            jnp.asarray(plan["q_len"]),
            jnp.asarray(plan["kv_start"]),
            jnp.asarray(plan["last_idx"]),
            self.kv_pages,
            jnp.asarray(plan["page_table"]),
            jnp.asarray(plan["joins"]),
            jnp.asarray(plan["scan_tok0"]),
            jnp.asarray(plan["scan_pos0"]),
            jnp.asarray(plan["step0_emits"]),
            jnp.asarray(plan["capacity"]),
            jnp.asarray(plan["counters"]),
            plan["state"],
            rng,
            jnp.asarray(plan["adapters"]),
        )
        chunk_np = await self._fetch_async(out)
        self._route_mixed(plan, chunk_np, dispatched_at)
        return True

    def _plan_ragged(self, meta: Optional[dict], prefilling) -> dict:
        """Pack this step's ragged token buffer (host side, numpy): decode
        lanes first (one token each), then each prefilling slot's next
        chunk, within one largest-prefill-bucket token budget.  Slices
        start at self._ragged_align multiples (the Pallas kernel's
        one-sequence-per-block invariant; 1 on the XLA reference path).
        Returns the packed arrays plus per-lane routing windows."""
        B = self.config.max_batch_size
        ps = self.config.page_size
        steps = self.config.steps_per_sync
        align = self._ragged_align
        budget = self.config.prefill_buckets[-1]

        def aligned(n: int) -> int:
            return -(-n // align) * align

        q_start = np.zeros((B,), np.int32)
        q_len = np.zeros((B,), np.int32)
        kv_start = np.zeros((B,), np.int32)
        last_idx = np.zeros((B,), np.int32)
        joins = np.zeros((B,), bool)
        scan_tok0 = np.full((B,), -1, np.int32)
        scan_pos0 = np.zeros((B,), np.int32)
        step0_emits = np.zeros((B,), np.int32)
        capacity = np.zeros((B,), np.int32)
        counters = np.zeros((B,), np.int32)
        adapters = np.full((B,), -1, np.int32)
        params_list = [SamplingParams() for _ in range(B)]
        tok_list: List[int] = []
        seq_list: List[int] = []
        pos_list: List[int] = []
        consume: Dict[int, tuple] = {}  # lane -> (first row, n rows)
        chunks: List[tuple] = []  # (lane, chunk len, final?)
        offset = 0
        n_decode = 0

        def place(lane: int, tokens: List[int], positions: List[int]):
            nonlocal offset, budget
            n = len(tokens)
            pad = aligned(n) - n
            tok_list.extend(tokens + [0] * pad)
            seq_list.extend([lane] * n + [-1] * pad)
            pos_list.extend(positions + [0] * pad)
            q_start[lane] = offset
            q_len[lane] = n
            last_idx[lane] = offset + n - 1
            offset += aligned(n)
            budget -= aligned(n)

        if meta is not None:
            for i, slot in enumerate(self._slots):
                if not meta["active"][i]:
                    continue
                pos = int(meta["pos"][i])
                cap = int(meta["capacity"][i])
                place(i, [int(meta["tokens"][i])], [pos])
                kv_start[i] = pos
                joins[i] = True
                scan_pos0[i] = pos + 1
                step0_emits[i] = 1
                capacity[i] = cap
                counters[i] = int(meta["counters"][i])
                adapters[i] = int(meta["adapters"][i])
                params_list[i] = slot.params
                consume[i] = (0, min(steps, cap - pos))
                n_decode += 1

        n_prefill_tokens = 0
        for i, slot in prefilling:
            pf = slot.prefilling
            req = pf["req"]
            seq, done = pf["seq"], pf["done"]
            total = len(seq)
            n = min(total - done, budget)
            if n <= 0:
                continue  # out of token budget; this lane rides next step
            place(i, list(seq[done:done + n]),
                  list(range(done, done + n)))
            kv_start[i] = done
            cap = len(slot.pages) * ps
            capacity[i] = cap
            adapters[i] = req.adapter_id
            params_list[i] = req.params
            final = done + n >= total
            if final:
                joins[i] = True
                if req.resume is not None:
                    # the ragged sample at a re-prefill boundary is
                    # discarded; the scan continues from the checkpoint's
                    # last generated token at its original position
                    gen = req.resume["generated"]
                    scan_tok0[i] = int(gen[-1])
                    scan_pos0[i] = int(req.resume["pos"])
                    counters[i] = len(gen)
                    consume[i] = (1, max(0, min(
                        steps - 1, cap - int(req.resume["pos"]))))
                else:
                    scan_pos0[i] = total
                    step0_emits[i] = 1
                    # row 0 (the first token) is emitted at seating; the
                    # consume window covers the scan tail only
                    consume[i] = (1, max(0, min(steps - 1, cap - total)))
            else:
                consume[i] = (0, 0)
            chunks.append((i, n, final))
            n_prefill_tokens += n

        T = -(-self._bucket_for(max(offset, 1)) // align) * align
        pad = T - offset
        tok_list.extend([0] * pad)
        seq_list.extend([-1] * pad)
        pos_list.extend([0] * pad)
        width = self.config.page_bucket(max(
            [len(s.pages) for s in self._slots if s.request_id is not None]
            or [1]
        ))
        page_table = np.zeros((B, width), np.int32)
        for i, slot in enumerate(self._slots):
            if slot.request_id is not None and slot.pages:
                page_table[i, : len(slot.pages)] = slot.pages
        return {
            "q_tokens": np.asarray(tok_list, np.int32),
            "token_seq": np.asarray(seq_list, np.int32),
            "token_pos": np.asarray(pos_list, np.int32),
            "q_start": q_start,
            "q_len": q_len,
            "kv_start": kv_start,
            "last_idx": last_idx,
            "page_table": page_table,
            "joins": joins,
            "scan_tok0": scan_tok0,
            "scan_pos0": scan_pos0,
            "step0_emits": step0_emits,
            "capacity": capacity,
            "counters": counters,
            "adapters": adapters,
            "state": SamplingState.from_params(params_list),
            "consume": consume,
            "chunks": chunks,
            "prefill_tokens": n_prefill_tokens,
            "decode_tokens": n_decode,
        }

    def _route_mixed(self, plan: dict, chunk_np: np.ndarray,
                     dispatched_at: float) -> None:
        """Consume one mixed dispatch's [steps, B] tokens: advance chunk
        cursors, seat lanes whose prompt completed (emitting their first
        token), then stream each joining lane's scan window.  Slots
        evicted while the dispatch was in flight (drain) are observed as
        empty and their speculative tokens discarded — same contract as
        the legacy _route_chunk."""
        now = self._clock.now()
        step_s = now - dispatched_at
        ENGINE_STEP_DURATION.labels(model_name=self._mlabel).observe(step_s)
        self.telemetry.record_step(step_s)
        if plan["chunks"] and plan["decode_tokens"] == 0:
            # prefill-chunk duration stays meaningful only for dispatches
            # that carried NO decode lanes: a fused mixed step's time is
            # dominated by the decode scan, and recording it here would
            # inflate prefill-chunk percentiles by the whole scan cost
            ENGINE_PREFILL_CHUNK_DURATION.labels(
                model_name=self._mlabel).observe(step_s)
            self.telemetry.record_prefill_chunk(step_s)
        comp = {
            "prefill_tokens": plan["prefill_tokens"],
            "decode_tokens": plan["decode_tokens"],
        }
        self.last_step_composition = comp
        g = ENGINE_STEP_BATCH_COMPOSITION
        g.labels(model_name=self._mlabel, role="prefill_tokens").set(
            comp["prefill_tokens"])
        g.labels(model_name=self._mlabel, role="decode_tokens").set(
            comp["decode_tokens"])
        for i, n, final in plan["chunks"]:
            slot = self._slots[i]
            if slot.request_id is None or slot.prefilling is None:
                continue  # evicted mid-dispatch
            pf = slot.prefilling
            req = pf["req"]
            pf["done"] += n
            tl = req.timeline
            if tl is not None:
                tl.mark_prefill_start(dispatched_at)
                tl.mark_prefill_end(now)
            if req.adapter_id < 0 and req.resume is None:
                covered = min(pf["done"], len(req.prompt_ids))
                self._prefix_cache.register(
                    req.prompt_ids[:covered], slot.pages,
                    start_page=pf.get("registered", 0))
                pf["registered"] = covered // self.config.page_size
            if not final:
                continue
            self._complete_prefilling(i, slot, req, int(chunk_np[0, i]))
        routed = 0
        for i in sorted(plan["consume"]):
            first_row, n_rows = plan["consume"][i]
            slot = self._slots[i]
            for s in range(first_row, first_row + n_rows):
                if slot.request_id is None:
                    break  # finished (or evicted); discard speculative tail
                token = int(chunk_np[s, i])
                slot.pos += 1
                slot.generated.append(token)
                self._emit(slot, token)
                routed += 1
        GENERATED_TOKENS.labels(model_name=self._mlabel).inc(routed)
        if routed or plan["chunks"]:
            self._note_progress()

    # ---------------- dense / speculative decode stepping ----------------

    def _plan_dense(self, meta: dict) -> dict:
        """Host inputs for one `mixed_decode` dispatch, derived from a
        _prepare_chunk meta (growth + preemption already ran there).  The
        draft table is re-seeded for dirty rows first, so every lane's
        drafter knows its prompt + everything emitted so far."""
        self._refresh_draft_table()
        return {
            "tokens": meta["tokens"],
            "pos": meta["pos"],
            "live": meta["active"],
            "capacity": meta["capacity"],
            "counters": meta["counters"],
            "adapters": meta["adapters"],
            "page_table": meta["page_table"],
            "state": meta["state"],
        }

    def _plan_dense_chained(self, prev: dict) -> Optional[dict]:
        """Plan a dispatch chained on an in-flight one: positions, tokens
        and counters come from the DEVICE carry (never fetched), so the
        host only refreshes what it owns — page capacity (grown toward
        the worst case of two in-flight dispatches) and the page table.
        No preemption while the pipeline is busy, same as the legacy
        depth-2 chain."""
        B = self.config.max_batch_size
        adv = self._max_step_advance
        kp = (self._spec_k or 0) + 1
        live = prev["live"]
        capacity = np.zeros((B,), np.int32)
        max_owned = 1
        any_live = False
        for i, slot in enumerate(self._slots):
            if slot.request_id is None or not live[i]:
                continue
            if slot.pos + adv + kp > self._dense_lane_cap:
                # the in-flight dispatch may carry this lane into the
                # zone where no further (K+1)-token slice fits its hard
                # kv ceiling — drain the pipeline instead of chaining a
                # dispatch the device could only skip (the unchained
                # re-plan falls back to the mixed path for the stretch)
                return None
            # device pos after the in-flight dispatch is at most
            # slot.pos + adv; cover one more full dispatch beyond that,
            # capped at max_model_len — positions past it can never hold
            # usable tokens, and growing pages for them steals allocator
            # headroom from other lanes (same cap _prepare_chunk applies)
            grow = min(2 * adv, self.config.max_model_len - slot.pos)
            if grow > 0:
                self._ensure_pages_at(slot, slot.pos, grow)
            capacity[i] = len(slot.pages) * self.config.page_size
            max_owned = max(max_owned, len(slot.pages))
            any_live = True
        if not any_live:
            return None
        width = self.config.page_bucket(max_owned)
        page_table = np.zeros((B, width), np.int32)
        for i, slot in enumerate(self._slots):
            if slot.request_id is not None and live[i]:
                page_table[i, : len(slot.pages)] = slot.pages
        return {
            "tokens": prev["tokens"],  # unused (device carry chains)
            "pos": prev["pos"],
            "live": live,
            "capacity": capacity,
            "counters": prev["counters"],
            "adapters": prev["adapters"],
            "page_table": page_table,
            "state": prev["state"],
        }

    def _dispatch_dense(self, plan: dict, chain: Optional[dict] = None):
        """Launch one mixed_decode dispatch; `chain` threads the previous
        dispatch's device (token, pos, counters) carry so the chained
        program starts exactly where the in-flight one ends — no host
        round-trip between them."""
        plan["_dispatched_at"] = self._clock.now()
        rng = jax.random.fold_in(self._base_rng, self._next_step())
        if chain is not None:
            tok, pos, cnt = chain["carry"]
        else:
            # committed to the same replicated spelling the program pins
            # its carry outputs to: chained and unchained dispatches must
            # share ONE jit signature (see _refresh_draft_table)
            rep = self._replicated_sharding
            tok = jax.device_put(jnp.asarray(plan["tokens"]), rep)
            pos = jax.device_put(jnp.asarray(plan["pos"]), rep)
            cnt = jax.device_put(jnp.asarray(plan["counters"]), rep)
        out = self._mixed_decode_fn(
            self.params,
            tok,
            pos,
            self.kv_pages,
            jnp.asarray(plan["page_table"]),
            jnp.asarray(plan["live"]),
            jnp.asarray(plan["capacity"]),
            cnt,
            self._draft_table,
            plan["state"],
            rng,
            jnp.asarray(plan["adapters"]),
        )
        toks, n_emit_dev, self.kv_pages, self._draft_table, tok_o, pos_o, cnt_o = out
        return {"toks": toks, "n": n_emit_dev, "carry": (tok_o, pos_o, cnt_o)}

    async def _route_dense(self, plan: dict, chunk: dict) -> bool:
        """Consume one mixed_decode dispatch: per round, each live lane
        emits its accepted-prefix + bonus tokens (0 when the round was
        skipped for capacity).  Slots evicted while the dispatch was in
        flight are observed empty and their tokens discarded — only
        ACCEPTED, routed tokens ever reach slot.generated, so checkpoints
        (drain/preempt/hedge) can never carry an unverified draft tail.
        Returns (any lane finished, any token routed)."""
        toks_np = await self._fetch_async(chunk["toks"])  # [rounds, B, K+1]
        n_np = await self._fetch_async(chunk["n"])  # [rounds, B]
        step_s = self._clock.now() - plan["_dispatched_at"]
        ENGINE_STEP_DURATION.labels(model_name=self._mlabel).observe(step_s)
        self.telemetry.record_step(step_s)
        k_drafts = self._spec_k or 0
        rounds = toks_np.shape[0]
        live = plan["live"]
        routed = 0
        drafted = 0
        accepted = 0
        finished_any = False
        for i, slot in enumerate(self._slots):
            if not live[i]:
                continue
            if slot.request_id is None:
                # evicted (cancel/preempt/drain) while the dispatch was in
                # flight: the whole lane is discarded — no stream consumed
                # its drafts, so the acceptance-rate signal skips it too
                finished_any = True
                continue
            for r in range(rounds):
                n = int(n_np[r, i])
                if n <= 0:
                    continue  # capacity-skipped round (or inactive)
                emitted = 0
                for j in range(n):
                    token = int(toks_np[r, i, j])
                    slot.pos += 1
                    slot.generated.append(token)
                    self._emit(slot, token)
                    routed += 1
                    emitted += 1
                    if slot.request_id is None:
                        break  # finished at this token; discard the tail
                # count only what the stream actually consumed: of the
                # emitted tokens, all but the round's bonus sample are
                # accepted drafts (a mid-round finish consumed drafts
                # only), keeping spec_stats an emitted-token-exact signal
                drafted += k_drafts
                accepted += min(emitted, n - 1)
                if slot.request_id is None:
                    finished_any = True
                    break
            if (slot.request_id is not None
                    and slot.pos >= self.config.max_model_len):
                self._finish(slot, "length")
                finished_any = True
        GENERATED_TOKENS.labels(model_name=self._mlabel).inc(routed)
        if k_drafts > 0:
            s = SPEC_TOKENS
            s.labels(model_name=self._mlabel, outcome="drafted").inc(drafted)
            s.labels(model_name=self._mlabel, outcome="accepted").inc(accepted)
            s.labels(model_name=self._mlabel,
                     outcome="rejected").inc(drafted - accepted)
            self.spec_stats["drafted"] += drafted
            self.spec_stats["accepted"] += accepted
            self.spec_stats["rejected"] += drafted - accepted
        comp = {
            "prefill_tokens": 0,
            # token counts, matching the mixed program's semantics: each
            # live lane contributes a (K+1)-token verify slice to the
            # packed buffer per round
            "decode_tokens": int(np.count_nonzero(live)) * (k_drafts + 1),
            "spec_accepted_tokens": accepted,
        }
        self.last_step_composition = comp
        g = ENGINE_STEP_BATCH_COMPOSITION
        for role, value in comp.items():
            g.labels(model_name=self._mlabel, role=role).set(value)
        if routed or finished_any:
            self._note_progress()
        return finished_any, routed > 0

    async def _step_dense(self, meta: dict) -> None:
        """Dense/speculative decode with the depth-2 dispatch pipeline
        restored on the mixed path: dispatch N+1 launches — chained on
        N's device (token, pos, counters) carry — before N's tokens are
        fetched, so draft+verify of step N+1 overlaps routing of step N
        and the host round-trip hides behind device compute."""
        plan = self._plan_dense(meta)
        chunk = self._dispatch_dense(plan)
        while True:
            plan2 = None
            chunk2 = None
            admission_blocked = (
                not self._waiting or self._free_slot_index() is None
            )
            # a lane guaranteed to hit max_tokens inside the in-flight
            # dispatch forces a pipeline drain anyway — don't chain into
            # a dispatch that would be wholly discarded
            predictable_finish = any(
                s.request_id is not None
                and plan["live"][i]
                and len(s.generated) + self._max_step_advance
                >= s.params.max_tokens
                for i, s in enumerate(self._slots)
            )
            if (
                admission_blocked
                and not predictable_finish
                and not (self._stopped or self._draining)
            ):
                plan2 = self._plan_dense_chained(plan)
            if plan2 is not None:
                chunk2 = self._dispatch_dense(plan2, chain=chunk)
                self._pipeline_busy = True
            finished_any, routed_any = await self._route_dense(plan, chunk)
            # flush streams while the chained dispatch runs on device
            await asyncio.sleep(0)
            if chunk2 is None:
                break
            plan, chunk = plan2, chunk2
            if (finished_any or not routed_any
                    or self._stopped or self._draining or (
                        self._waiting
                        and self._free_slot_index() is not None)):
                # in-flight dispatch has stale lanes, admission can
                # proceed, or every round was capacity-skipped (the
                # lanes need host-side growth or the mixed-path ceiling
                # fallback): drain the pipeline and re-plan
                self._pipeline_busy = False
                await self._route_dense(plan, chunk)
                break
        self._pipeline_busy = False
        self._flush_deferred_frees()

    def _emit(self, slot: _Slot, token: int,
              logprob: Optional[float] = None,
              top_logprobs: Optional[List[tuple]] = None):
        """Stream one token; apply stop conditions."""
        if slot.timeline is not None:
            slot.timeline.mark_token(self._clock.now())
        n_gen = len(slot.generated)
        params = slot.params
        finish_reason = None
        is_eos = (
            token == self.tokenizer.eos_token_id
            and not params.ignore_eos
            and n_gen > params.min_tokens
        )
        delta = "" if is_eos else slot.detok.push(token)
        text = slot.detok.text
        if is_eos:
            finish_reason = "stop"
        elif n_gen >= params.max_tokens:
            finish_reason = "length"
        else:
            for stop in slot.stop_texts:
                if stop and stop in text:
                    cut = text.index(stop)
                    delta = delta[: max(0, len(delta) - (len(text) - cut))]
                    finish_reason = "stop"
                    break
        out = GenerationOutput(
            token_id=token,
            text_delta=delta,
            finished=finish_reason is not None,
            finish_reason=finish_reason,
            num_generated=n_gen,
            num_prompt_tokens=slot.prompt_len,
            cumulative_text=text,
            logprob=logprob,
            top_logprobs=top_logprobs,
        )
        slot.queue.put_nowait(out)
        if finish_reason is not None:
            self._record_terminal(slot.timeline, finish_reason)
            self._free_pages(slot.pages)
            slot.reset()
            self._mark_penalty_dirty(self._slots.index(slot))
            self._wake.set()

    def _finish(self, slot: _Slot, reason: str):
        out = GenerationOutput(
            token_id=-1,
            text_delta="",
            finished=True,
            finish_reason=reason,
            num_generated=len(slot.generated),
            num_prompt_tokens=slot.prompt_len,
            cumulative_text=slot.detok.text,
        )
        slot.queue.put_nowait(out)
        self._record_terminal(slot.timeline, reason)
        self._free_pages(slot.pages)
        slot.reset()
        self._mark_penalty_dirty(self._slots.index(slot))

    def _next_step(self) -> int:
        self._step_counter += 1
        return self._step_counter
