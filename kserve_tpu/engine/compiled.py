"""The engine's compiled device programs (VERDICT r4 weak #8: split from
the scheduler/loop module).

`build_compiled(model_config, engine_config, mesh)` jits every program the
serving loop dispatches: batched + chunked prefill, multi-step decode (the
penalized and logprob-emitting variants compiled separately so ordinary
requests never pay their per-step cost), first-token sampling for chunked
admission, and the P/D KV injection scatters.  All sharding-aware pieces
(TP decode attention under shard_map, SP ring-attention prefill, PP staged
execution) are chosen here from the engine config.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp

from ..metrics import XLA_COMPILES
from ..models import llama
from ..parallel import sharding as shd
from .sampling import apply_penalties, compute_logprobs, sample_tokens


class _CompileCounting:
    """Wrap a jitted program and count its jit-cache misses (compiles AND
    retraces) into the engine_xla_compiles_total counter, labeled by the
    program's fixed name.  A growing count at steady state is the recompile
    alarm ROADMAP item 2's perf oracle needs (shape-bucket drift, weak-type
    wobble, donation mismatch all show up here before they show up as tail
    latency)."""

    __slots__ = ("_name", "_fn", "_seen")

    def __init__(self, name: str, fn: Callable):
        self._name = name
        self._fn = fn
        self._seen = 0

    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        try:
            n = self._fn._cache_size()
        except AttributeError:  # older jax: counting degrades to a no-op
            return out
        if n > self._seen:
            XLA_COMPILES.labels(program=self._name).inc(n - self._seen)
            self._seen = n
        return out


def _counted(**named) -> dict:
    return {k: _CompileCounting(k, v) for k, v in named.items()}


@dataclass(frozen=True)
class CompiledPrograms:
    prefill: Callable
    prefill_lp: Callable
    prefill_chunk: Callable
    sample_first: Callable
    sample_first_lp: Callable
    decode: Callable
    decode_lp: Callable
    decode_penalized: Callable
    decode_penalized_lp: Callable
    inject: Callable
    inject_q: Callable


def build_compiled(model_config, engine_config, mesh) -> CompiledPrograms:
    cfg = engine_config
    mc = model_config

    # the pallas kernel has no GSPMD partitioning rule; under tp/sp>1
    # decode attention runs under shard_map over the model axis instead
    # (each device: its LOCAL heads — q and KV heads shard together so
    # GQA groups stay intact; no collectives) so the kernel's
    # auto-dispatch stays available on the multi-chip path
    decode_attention_fn = None
    if cfg.tp > 1 or cfg.sp > 1:
        from ..ops.attention import make_sharded_paged_attention

        decode_attention_fn = make_sharded_paged_attention(
            mesh,
            logit_softcap=mc.attn_logit_softcap,
            use_pallas=cfg.use_pallas,
            quantized=(getattr(cfg, "kv_quant", None) == "int8"),
            scale=mc.attn_scale,
            # static: only windowed models thread the per-layer scalar
            # through (a traced window forces the gather path)
            windowed=mc.sliding_window > 0,
        )

    attention_fn = None
    if cfg.sp > 1:
        # sequence-parallel prefill: the prompt dim shards over `seq`,
        # attention runs as ring attention under shard_map (KV chunks
        # rotate via ppermute, comms overlap compute); the KV-page
        # scatter's output sharding is seq-replicated, so XLA inserts
        # the K/V allgather automatically.  Decode stays seq-replicated
        # (single-token steps have nothing to shard over seq).
        from functools import partial as _partial

        from jax.sharding import PartitionSpec as _P

        from ..parallel.sharding import shard_map

        from ..parallel.ring_attention import ring_attention

        qkv_spec = _P(None, shd.SEQ_AXIS, shd.MODEL_AXIS, None)
        ring_fn = shard_map(
            _partial(
                ring_attention,
                axis_name=shd.SEQ_AXIS,
                logit_softcap=mc.attn_logit_softcap,
            ),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, _P(None)),
            out_specs=qkv_spec,
            check_vma=False,
        )
        attention_fn = lambda q, k, v, vl, softcap: ring_fn(q, k, v, vl)  # noqa: E731

    def _pp_microbatches(B: int) -> int:
        """Largest divisor of B not above the requested microbatch
        count (pp by default) — static per compiled shape."""
        m = min(cfg.pp_microbatches or cfg.pp, B)
        while B % m:
            m -= 1
        return max(m, 1)

    def _make_prefill(with_logprobs: bool):
        def fn(params, tokens, valid_len, kv_pages, page_ids, state, rng,
               adapter_ids):
            if cfg.sp > 1:
                tokens = jax.lax.with_sharding_constraint(
                    tokens, shd.named(mesh, jax.sharding.PartitionSpec(None, shd.SEQ_AXIS))
                )
            if cfg.pp > 1:
                logits, kv_pages = llama.prefill_pp(
                    params, mc, tokens, valid_len, kv_pages, page_ids,
                    cfg.page_size, mesh,
                    _pp_microbatches(tokens.shape[0]),
                    adapter_ids=adapter_ids,
                )
            else:
                logits, kv_pages = llama.prefill(
                    params, mc, tokens, valid_len, kv_pages, page_ids, cfg.page_size,
                    attention_fn=attention_fn, adapter_ids=adapter_ids,
                )
            # vLLM-parity: repetition_penalty counts prompt tokens as
            # "seen" for the very first sampled token.  Rows with default
            # penalties are bit-identical to the unpenalized math.
            Bp, V = logits.shape
            pos_valid = (
                jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
                < valid_len[:, None]
            )
            in_prompt = (
                jnp.zeros((Bp, V), bool)
                .at[jnp.arange(Bp)[:, None], tokens]
                .max(pos_valid)
            )
            logits = apply_penalties(
                logits,
                jnp.zeros((Bp, V), jnp.int32),
                state.repetition_penalty,
                state.frequency_penalty,
                state.presence_penalty,
                in_prompt,
            )
            first = sample_tokens(logits, state, rng)
            if with_logprobs:
                lp, tv, ti = compute_logprobs(logits, first, cfg.max_logprobs)
                return first, (lp, tv, ti), kv_pages
            return first, kv_pages

        return fn

    def _make_decode(with_penalties: bool, with_logprobs: bool = False):
        """steps_per_sync decode steps on device; emits [steps, B] tokens.
        Lanes past their page capacity (or inactive) hold token/pos and
        write to the null page — a clamped page-table index would
        otherwise corrupt a neighbouring sequence's last page.

        The penalized variant additionally threads a [B, V] output-count
        carry (plus a static [B, V] prompt mask) through the scan and
        returns the updated counts; it is compiled separately so requests
        without penalties never pay the per-step [B, V] scatter/gather.
        The logprobs variant additionally emits per-step sampled-token
        logprobs and the top-k (cfg.max_logprobs) ids/values — compiled
        separately so ordinary requests never pay the per-step top_k."""

        def fn(params, tokens, pos, kv_pages, page_table, active,
               capacity, counters, state, rng, adapter_ids, *penalty_args):
            steps = cfg.steps_per_sync
            B = tokens.shape[0]

            def body(carry, step_rng):
                if with_penalties:
                    tokens, pos, counters, kv_pages, counts = carry
                else:
                    tokens, pos, counters, kv_pages = carry
                live = active & (pos < capacity)
                if cfg.pp > 1:
                    logits, kv_pages = llama.decode_step_pp(
                        params, mc, tokens, pos, kv_pages, page_table,
                        live, cfg.page_size, mesh, _pp_microbatches(B),
                        adapter_ids=adapter_ids,
                    )
                else:
                    logits, kv_pages = llama.decode_step(
                        params, mc, tokens, pos, kv_pages, page_table, live,
                        cfg.page_size, use_pallas=cfg.use_pallas,
                        adapter_ids=adapter_ids,
                        attention_fn=decode_attention_fn,
                    )
                if with_penalties:
                    logits = apply_penalties(
                        logits, counts,
                        state.repetition_penalty,
                        state.frequency_penalty,
                        state.presence_penalty,
                        penalty_args[0],
                    )
                nxt = sample_tokens(logits, state, step_rng, counters)
                nxt = jnp.where(live, nxt, tokens)
                if with_logprobs:
                    lp, tv, ti = compute_logprobs(logits, nxt, cfg.max_logprobs)
                    out_step = (nxt, lp, tv, ti)
                else:
                    out_step = nxt
                new_carry = (
                    nxt,
                    pos + live.astype(pos.dtype),
                    counters + live.astype(counters.dtype),
                    kv_pages,
                )
                if with_penalties:
                    counts = counts.at[jnp.arange(B), nxt].add(
                        live.astype(counts.dtype)
                    )
                    new_carry = new_carry + (counts,)
                return new_carry, out_step

            init = (tokens, pos, counters, kv_pages)
            if with_penalties:
                init = init + (penalty_args[1],)
            rngs = jax.random.split(rng, steps)
            carry, out = jax.lax.scan(body, init, rngs)
            if with_penalties:
                return out, carry[3], carry[4]
            return out, carry[3]

        return fn

    def _inject(kv_pages, kv_data, ids):
        """Scatter transferred KV pages (P/D transfer or tier-store
        resume) into the cache.  Padded ids point at the null page (page
        0), whose contents are never read unmasked.  pp>1: the cache is
        one stacked [L, ...] array (layer axis on pipe) and the payload
        arrives in the same layout, so one scatter covers every stage."""
        if cfg.pp > 1:
            return kv_pages.at[:, ids].set(kv_data.astype(kv_pages.dtype))
        return [
            layer.at[ids].set(kv_data[i].astype(layer.dtype))
            for i, layer in enumerate(kv_pages)
        ]

    def _inject_q(kv_pages, q, s, ids):
        """Quantized-cache variant: scatter int8 pages AND their
        scales (tier-store resume over kv_quant=int8)."""
        if cfg.pp > 1:
            pages, scales = kv_pages
            return (pages.at[:, ids].set(q.astype(pages.dtype)),
                    scales.at[:, ids].set(s.astype(scales.dtype)))
        return [
            (pages.at[ids].set(q[i].astype(pages.dtype)),
             scales.at[ids].set(s[i].astype(scales.dtype)))
            for i, (pages, scales) in enumerate(kv_pages)
        ]

    def _prefill_chunk(params, tokens, chunk_start, valid_len, kv_pages,
                       page_ids, adapter_ids):
        if cfg.pp > 1:
            # staged chunked prefill: unlocks long prompts AND prefix-
            # cache hits under pipeline parallelism
            return llama.prefill_chunk_pp(
                params, mc, tokens, chunk_start, valid_len, kv_pages,
                page_ids, cfg.page_size, mesh,
                _pp_microbatches(tokens.shape[0]),
                adapter_ids=adapter_ids,
            )
        return llama.prefill_chunk(
            params, mc, tokens, chunk_start, valid_len, kv_pages,
            page_ids, cfg.page_size, adapter_ids=adapter_ids,
        )

    def _make_sample_first(with_logprobs: bool):
        def fn(logits, state, rng, in_prompt):
            # same first-token penalty semantics as the batched prefill:
            # repetition penalty counts prompt tokens as seen
            logits = apply_penalties(
                logits,
                jnp.zeros(logits.shape, jnp.int32),
                state.repetition_penalty,
                state.frequency_penalty,
                state.presence_penalty,
                in_prompt,
            )
            first = sample_tokens(logits, state, rng)
            if with_logprobs:
                return first, compute_logprobs(logits, first, cfg.max_logprobs)
            return first

        return fn

    n_kv_args = 3  # kv_pages is arg index 3 in the prefill/decode sigs
    return CompiledPrograms(**_counted(
        prefill=jax.jit(_make_prefill(False), donate_argnums=(n_kv_args,)),
        prefill_lp=jax.jit(_make_prefill(True), donate_argnums=(n_kv_args,)),
        prefill_chunk=jax.jit(_prefill_chunk, donate_argnums=(4,)),
        sample_first=jax.jit(_make_sample_first(False)),
        sample_first_lp=jax.jit(_make_sample_first(True)),
        decode=jax.jit(_make_decode(False), donate_argnums=(n_kv_args,)),
        decode_lp=jax.jit(
            _make_decode(False, with_logprobs=True), donate_argnums=(n_kv_args,)
        ),
        # arg 11 = prompt mask (kept across chunks), arg 12 = counts (donated)
        decode_penalized=jax.jit(
            _make_decode(True), donate_argnums=(n_kv_args, 12)
        ),
        decode_penalized_lp=jax.jit(
            _make_decode(True, with_logprobs=True), donate_argnums=(n_kv_args, 12)
        ),
        inject=jax.jit(_inject, donate_argnums=(0,)),
        inject_q=jax.jit(_inject_q, donate_argnums=(0,)),
    ))
