"""The engine's compiled device programs (VERDICT r4 weak #8: split from
the scheduler/loop module).

`build_compiled(model_config, engine_config, mesh)` jits every program the
serving loop dispatches: batched + chunked prefill, multi-step decode (the
penalized and logprob-emitting variants compiled separately so ordinary
requests never pay their per-step cost), first-token sampling for chunked
admission, and the P/D KV injection scatters.  All sharding-aware pieces
(TP decode attention under shard_map, SP ring-attention prefill, PP staged
execution) are chosen here from the engine config.
"""

from __future__ import annotations

import hashlib
import logging
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..metrics import XLA_COMPILES
from ..models import llama
from ..parallel import sharding as shd
from .sampling import apply_penalties, compute_logprobs, sample_tokens

_log = logging.getLogger(__name__)

#: per-program compile events, appended by _CompileCounting (jit path) and
#: AOTProgram._compile (AOT path).  Each event is a dict with the argument
#: signature that triggered the compile — the jit cache key's observable
#: spelling (shape/dtype/weak-type/sharding per leaf) — plus a short
#: digest of it, so a retrace-budget failure can name WHICH spelling
#: drifted between call N and call N+1 instead of just reporting a count.
_COMPILE_FINGERPRINTS: Dict[str, List[dict]] = {}


def _leaf_spelling(leaf) -> str:
    """One leaf's jit-cache-relevant spelling: dtype[shape]@spec, with a
    ``~w`` suffix for weak types (the classic invisible retrace source)."""
    aval = getattr(leaf, "aval", None)
    shape = getattr(aval, "shape", getattr(leaf, "shape", ()))
    dtype = getattr(aval, "dtype", getattr(leaf, "dtype", type(leaf).__name__))
    weak = bool(getattr(aval, "weak_type", getattr(leaf, "weak_type", False)))
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    s = f"{dtype}[{','.join(str(d) for d in shape)}]"
    if spec is not None:
        s += f"@{spec}"
    if weak:
        s += "~w"
    return s


def _args_signature(args, kwargs) -> str:
    """Compact per-argument signature of a dispatch: big pytrees (params,
    kv caches) collapse to ``<N leaves:digest8>`` so the string stays
    log-line sized while still changing whenever any leaf's spelling does."""
    parts = []
    for arg in list(args) + [v for _, v in sorted(kwargs.items())]:
        leaves = jax.tree_util.tree_leaves(arg)
        spellings = [_leaf_spelling(leaf) for leaf in leaves]
        if len(spellings) > 4:
            digest = hashlib.sha256(
                "|".join(spellings).encode()).hexdigest()[:8]
            parts.append(f"<{len(spellings)} leaves:{digest}>")
        elif len(spellings) == 1:
            parts.append(spellings[0])
        else:
            parts.append("(" + ",".join(spellings) + ")")
    return ", ".join(parts)


def record_compile_fingerprint(program: str, signature: str,
                               hlo_hash: str = "") -> None:
    """Append one compile event for `program`.  `signature` is the arg
    spelling that keyed the compile; `hlo_hash` (optional) is a digest of
    the lowered module when the recorder has it (the AOT path does)."""
    _COMPILE_FINGERPRINTS.setdefault(program, []).append({
        "signature": signature,
        "fingerprint": hashlib.sha256(
            f"{program}:{signature}".encode()).hexdigest()[:12],
        "hlo_hash": hlo_hash,
    })


def compile_fingerprints(program: Optional[str] = None):
    """Recorded compile events: a list for one `program`, else the whole
    {program: [events]} map (live view — copy before mutating)."""
    if program is not None:
        return list(_COMPILE_FINGERPRINTS.get(program, ()))
    return {k: list(v) for k, v in _COMPILE_FINGERPRINTS.items()}


def reset_compile_fingerprints() -> None:
    _COMPILE_FINGERPRINTS.clear()


class _CompileCounting:
    """Wrap a jitted program and count its jit-cache misses (compiles AND
    retraces) into the engine_xla_compiles_total counter, labeled by the
    program's fixed name.  A growing count at steady state is the recompile
    alarm ROADMAP item 2's perf oracle needs (shape-bucket drift, weak-type
    wobble, donation mismatch all show up here before they show up as tail
    latency).  Each counted miss also records the dispatch's argument
    signature via record_compile_fingerprint, so the retrace-budget test
    can diff the spellings of compile N and N+1.  The signature is built
    from avals (which survive donation) AFTER the dispatch — cost is one
    tree-flatten per compile event, nothing per steady-state call."""

    __slots__ = ("_name", "_fn", "_seen")

    def __init__(self, name: str, fn: Callable):
        self._name = name
        self._fn = fn
        self._seen = 0

    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        try:
            n = self._fn._cache_size()
        except AttributeError:  # older jax: counting degrades to a no-op
            return out
        if n > self._seen:
            XLA_COMPILES.labels(program=self._name).inc(n - self._seen)
            self._seen = n
            try:
                record_compile_fingerprint(
                    self._name, _args_signature(args, kwargs))
            except Exception:  # diagnostics must never fail a dispatch
                _log.debug("compile fingerprint failed for %s",
                           self._name, exc_info=True)
        return out


def _counted(**named) -> dict:
    return {k: _CompileCounting(k, v) for k, v in named.items()}


@dataclass(frozen=True)
class CompiledPrograms:
    """The engine's device programs.  `mixed` is the unified ragged
    prefill+decode program (docs/kernels.md) the engine dispatches by
    default; the remaining entries are the legacy per-path programs, kept
    as the fallback behind EngineConfig.use_ragged=False (and for the
    feature corners mixed doesn't cover yet: per-step logprobs, penalties,
    P/D detached prefill, pp>1/sp>1).  jit is lazy, so unused legacy
    programs cost nothing at steady state."""

    prefill: Callable
    prefill_lp: Callable
    prefill_chunk: Callable
    sample_first: Callable
    sample_first_lp: Callable
    decode: Callable
    decode_lp: Callable
    decode_penalized: Callable
    decode_penalized_lp: Callable
    inject: Callable
    inject_q: Callable
    mixed: Callable = None  # None when the config can't build it (pp>1)
    # dense decode packing + self-drafting speculative verify
    # (docs/kernels.md): built only when spec_decode_k is configured —
    # the pure-decode fast path the engine chains depth-2
    mixed_decode: Callable = None


def program_defs(model_config, engine_config, mesh, spec_k=None) -> dict:
    """The engine's program-definition table: ``{name: (python_fn,
    donate_argnums)}`` for every program this config builds.  This is the
    single source of truth `build_compiled` jits (or AOT-compiles) from —
    and the seam the HLO perf oracle (analysis/hlo_oracle) re-enters to
    lower the SAME programs standalone, so its budgets audit exactly what
    the engine dispatches.  The aot-cache-key-drift lint audits the
    engine-config reads in here (same scope as build_compiled).

    `spec_k` (EngineConfig.spec_decode_k, passed EXPLICITLY so the
    aot-cache-key-drift lint stays honest: the field is deliberately NOT
    in the AOT key until hardware-validated, and the engine disables the
    AOT cache whenever it is set) builds the `mixed_decode` dense/
    speculative program: K draft tokens per decode lane verified as one
    ragged multi-token chunk per round."""
    cfg = engine_config
    mc = model_config

    from jax.sharding import PartitionSpec as _P

    _quantized = getattr(cfg, "kv_quant", None) == "int8"

    def _kv_pin(kv_pages):
        """Constrain returned kv_pages to the canonical cache sharding.

        Without this, XLA is free to return the donated cache with a
        differently-SPELLED (equivalent) sharding — observed on CPU: the
        init arrays carry PartitionSpec(None, None, 'model', None, None)
        but the program output comes back as PartitionSpec(), so the
        SECOND dispatch sees a new input signature and recompiles once
        per program ("the donated kv_pages layout settles", PR 6/7 note).
        Pinning the output spec makes call 2's signature identical to
        call 1's: every program compiles exactly once per shape bucket
        (pinned by tests/test_retrace_budget.py)."""
        if cfg.pp > 1:
            # no constraint under pp: the staged shard_map is manual over
            # `pipe`, and adding a GSPMD constraint to its output makes
            # this jax's partitioner reject the module (PartitionId under
            # SPMD).  pp keeps the benign one-time settle retrace instead.
            return kv_pages
        page_s = shd.named(mesh, shd.kv_pages_pspec())
        scale_s = shd.named(mesh, _P(None, None, shd.MODEL_AXIS, None))
        if _quantized:
            return [
                (jax.lax.with_sharding_constraint(p, page_s),
                 jax.lax.with_sharding_constraint(s, scale_s))
                for p, s in kv_pages
            ]
        return [
            jax.lax.with_sharding_constraint(p, page_s) for p in kv_pages
        ]

    # the pallas kernel has no GSPMD partitioning rule; under tp/sp>1
    # decode attention runs under shard_map over the model axis instead
    # (each device: its LOCAL heads — q and KV heads shard together so
    # GQA groups stay intact; no collectives) so the kernel's
    # auto-dispatch stays available on the multi-chip path
    decode_attention_fn = None
    if cfg.tp > 1 or cfg.sp > 1:
        from ..ops.attention import make_sharded_paged_attention

        decode_attention_fn = make_sharded_paged_attention(
            mesh,
            logit_softcap=mc.attn_logit_softcap,
            use_pallas=cfg.use_pallas,
            quantized=_quantized,
            scale=mc.attn_scale,
            # static: only windowed models thread the per-layer scalar
            # through (a traced window forces the gather path)
            windowed=mc.sliding_window > 0,
        )

    # same shard_map seam for the RAGGED attention in the mixed program:
    # q heads and KV heads shard together over the model axis, packing
    # metadata is replicated (ops/attention.make_sharded_ragged_attention)
    ragged_attention_fn = None
    if cfg.tp > 1 or cfg.sp > 1:
        from ..ops.attention import make_sharded_ragged_attention

        ragged_attention_fn = make_sharded_ragged_attention(
            mesh,
            logit_softcap=mc.attn_logit_softcap,
            use_pallas=cfg.use_pallas,
            quantized=_quantized,
            scale=mc.attn_scale,
        )

    attention_fn = None
    if cfg.sp > 1:
        # sequence-parallel prefill: the prompt dim shards over `seq`,
        # attention runs as ring attention under shard_map (KV chunks
        # rotate via ppermute, comms overlap compute); the KV-page
        # scatter's output sharding is seq-replicated, so XLA inserts
        # the K/V allgather automatically.  Decode stays seq-replicated
        # (single-token steps have nothing to shard over seq).
        from functools import partial as _partial

        from jax.sharding import PartitionSpec as _P

        from ..parallel.sharding import shard_map

        from ..parallel.ring_attention import ring_attention

        qkv_spec = _P(None, shd.SEQ_AXIS, shd.MODEL_AXIS, None)
        ring_fn = shard_map(
            _partial(
                ring_attention,
                axis_name=shd.SEQ_AXIS,
                logit_softcap=mc.attn_logit_softcap,
            ),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, _P(None)),
            out_specs=qkv_spec,
            check_vma=False,
        )
        attention_fn = lambda q, k, v, vl, softcap: ring_fn(q, k, v, vl)  # noqa: E731

    def _pp_microbatches(B: int) -> int:
        """Largest divisor of B not above the requested microbatch
        count (pp by default) — static per compiled shape."""
        m = min(cfg.pp_microbatches or cfg.pp, B)
        while B % m:
            m -= 1
        return max(m, 1)

    def _make_prefill(with_logprobs: bool):
        def fn(params, tokens, valid_len, kv_pages, page_ids, state, rng,
               adapter_ids):
            if cfg.sp > 1:
                tokens = jax.lax.with_sharding_constraint(
                    tokens, shd.named(mesh, jax.sharding.PartitionSpec(None, shd.SEQ_AXIS))
                )
            if cfg.pp > 1:
                logits, kv_pages = llama.prefill_pp(
                    params, mc, tokens, valid_len, kv_pages, page_ids,
                    cfg.page_size, mesh,
                    _pp_microbatches(tokens.shape[0]),
                    adapter_ids=adapter_ids,
                )
            else:
                logits, kv_pages = llama.prefill(
                    params, mc, tokens, valid_len, kv_pages, page_ids, cfg.page_size,
                    attention_fn=attention_fn, adapter_ids=adapter_ids,
                )
            # vLLM-parity: repetition_penalty counts prompt tokens as
            # "seen" for the very first sampled token.  Rows with default
            # penalties are bit-identical to the unpenalized math.
            Bp, V = logits.shape
            pos_valid = (
                jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
                < valid_len[:, None]
            )
            in_prompt = (
                jnp.zeros((Bp, V), bool)
                .at[jnp.arange(Bp)[:, None], tokens]
                .max(pos_valid)
            )
            logits = apply_penalties(
                logits,
                jnp.zeros((Bp, V), jnp.int32),
                state.repetition_penalty,
                state.frequency_penalty,
                state.presence_penalty,
                in_prompt,
            )
            first = sample_tokens(logits, state, rng)
            kv_pages = _kv_pin(kv_pages)
            if with_logprobs:
                lp, tv, ti = compute_logprobs(logits, first, cfg.max_logprobs)
                return first, (lp, tv, ti), kv_pages
            return first, kv_pages

        return fn

    def _make_decode(with_penalties: bool, with_logprobs: bool = False):
        """steps_per_sync decode steps on device; emits [steps, B] tokens.
        Lanes past their page capacity (or inactive) hold token/pos and
        write to the null page — a clamped page-table index would
        otherwise corrupt a neighbouring sequence's last page.

        The penalized variant additionally threads a [B, V] output-count
        carry (plus a static [B, V] prompt mask) through the scan and
        returns the updated counts; it is compiled separately so requests
        without penalties never pay the per-step [B, V] scatter/gather.
        The logprobs variant additionally emits per-step sampled-token
        logprobs and the top-k (cfg.max_logprobs) ids/values — compiled
        separately so ordinary requests never pay the per-step top_k."""

        def fn(params, tokens, pos, kv_pages, page_table, active,
               capacity, counters, state, rng, adapter_ids, *penalty_args):
            steps = cfg.steps_per_sync
            B = tokens.shape[0]

            def body(carry, step_rng):
                if with_penalties:
                    tokens, pos, counters, kv_pages, counts = carry
                else:
                    tokens, pos, counters, kv_pages = carry
                live = active & (pos < capacity)
                if cfg.pp > 1:
                    logits, kv_pages = llama.decode_step_pp(
                        params, mc, tokens, pos, kv_pages, page_table,
                        live, cfg.page_size, mesh, _pp_microbatches(B),
                        adapter_ids=adapter_ids,
                    )
                else:
                    logits, kv_pages = llama.decode_step(
                        params, mc, tokens, pos, kv_pages, page_table, live,
                        cfg.page_size, use_pallas=cfg.use_pallas,
                        adapter_ids=adapter_ids,
                        attention_fn=decode_attention_fn,
                    )
                if with_penalties:
                    logits = apply_penalties(
                        logits, counts,
                        state.repetition_penalty,
                        state.frequency_penalty,
                        state.presence_penalty,
                        penalty_args[0],
                    )
                nxt = sample_tokens(logits, state, step_rng, counters)
                nxt = jnp.where(live, nxt, tokens)
                if with_logprobs:
                    lp, tv, ti = compute_logprobs(logits, nxt, cfg.max_logprobs)
                    out_step = (nxt, lp, tv, ti)
                else:
                    out_step = nxt
                new_carry = (
                    nxt,
                    pos + live.astype(pos.dtype),
                    counters + live.astype(counters.dtype),
                    kv_pages,
                )
                if with_penalties:
                    counts = counts.at[jnp.arange(B), nxt].add(
                        live.astype(counts.dtype)
                    )
                    new_carry = new_carry + (counts,)
                return new_carry, out_step

            init = (tokens, pos, counters, kv_pages)
            if with_penalties:
                init = init + (penalty_args[1],)
            rngs = jax.random.split(rng, steps)
            carry, out = jax.lax.scan(body, init, rngs)
            if with_penalties:
                return out, _kv_pin(carry[3]), carry[4]
            return out, _kv_pin(carry[3])

        return fn

    def _inject(kv_pages, kv_data, ids):
        """Scatter transferred KV pages (P/D transfer or tier-store
        resume) into the cache.  Padded ids point at the null page (page
        0), whose contents are never read unmasked.  pp>1: the cache is
        one stacked [L, ...] array (layer axis on pipe) and the payload
        arrives in the same layout, so one scatter covers every stage."""
        if cfg.pp > 1:
            return _kv_pin(
                kv_pages.at[:, ids].set(kv_data.astype(kv_pages.dtype)))
        return _kv_pin([
            layer.at[ids].set(kv_data[i].astype(layer.dtype))
            for i, layer in enumerate(kv_pages)
        ])

    def _inject_q(kv_pages, q, s, ids):
        """Quantized-cache variant: scatter int8 pages AND their
        scales (tier-store resume over kv_quant=int8)."""
        if cfg.pp > 1:
            pages, scales = kv_pages
            return _kv_pin((pages.at[:, ids].set(q.astype(pages.dtype)),
                            scales.at[:, ids].set(s.astype(scales.dtype))))
        return _kv_pin([
            (pages.at[ids].set(q[i].astype(pages.dtype)),
             scales.at[ids].set(s[i].astype(scales.dtype)))
            for i, (pages, scales) in enumerate(kv_pages)
        ])

    def _prefill_chunk(params, tokens, chunk_start, valid_len, kv_pages,
                       page_ids, adapter_ids):
        if cfg.pp > 1:
            # staged chunked prefill: unlocks long prompts AND prefix-
            # cache hits under pipeline parallelism
            logits, kv_pages = llama.prefill_chunk_pp(
                params, mc, tokens, chunk_start, valid_len, kv_pages,
                page_ids, cfg.page_size, mesh,
                _pp_microbatches(tokens.shape[0]),
                adapter_ids=adapter_ids,
            )
        else:
            logits, kv_pages = llama.prefill_chunk(
                params, mc, tokens, chunk_start, valid_len, kv_pages,
                page_ids, cfg.page_size, adapter_ids=adapter_ids,
            )
        return logits, _kv_pin(kv_pages)

    def _make_mixed():
        """THE unified ragged program (docs/kernels.md): one dispatch
        serves an arbitrary mix of prompt chunks and decode lanes.

        Step 0 runs llama.forward_ragged over the packed token buffer —
        prompt chunks write their KV and decode lanes advance in the SAME
        causal-masked attention — then samples one token per lane (a
        finishing prompt's first token; a decode lane's next token).  The
        remaining steps_per_sync-1 steps are a standard decode scan over
        every lane host-side planning marked `joins`: decode lanes AND
        lanes whose prompt just completed, so a short request can prefill
        and decode its whole budget in one dispatch.  Lanes mid-chunk sit
        the scan out (joins=False); resumes override the scan's first
        token with their last generated token (scan_tok0 >= 0) since the
        ragged sample at a re-prefill boundary is discarded.

        Emits [steps, B] tokens like the legacy decode program; the host
        consumes per-lane windows (engine._route_mixed)."""

        def fn(params, q_tokens, token_seq, token_pos, q_start, q_len,
               kv_start, last_idx, kv_pages, page_table, joins, scan_tok0,
               scan_pos0, step0_emits, capacity, counters, state, rng,
               adapter_ids):
            steps = cfg.steps_per_sync
            rngs = jax.random.split(rng, steps)
            logits, kv_pages = llama.forward_ragged(
                params, mc, q_tokens, token_seq, token_pos,
                q_start, q_len, kv_start, kv_pages, page_table,
                cfg.page_size, last_idx,
                adapter_ids=adapter_ids,
                attention_fn=ragged_attention_fn,
                use_pallas=cfg.use_pallas,
            )
            sampled0 = sample_tokens(logits, state, rngs[0], counters)
            tokens0 = jnp.where(scan_tok0 >= 0, scan_tok0, sampled0)
            counters0 = counters + step0_emits

            def body(carry, step_rng):
                tokens, pos, counters, kv_pages = carry
                live = joins & (pos < capacity)
                logits, kv_pages = llama.decode_step(
                    params, mc, tokens, pos, kv_pages, page_table, live,
                    cfg.page_size, use_pallas=cfg.use_pallas,
                    adapter_ids=adapter_ids,
                    attention_fn=decode_attention_fn,
                )
                nxt = sample_tokens(logits, state, step_rng, counters)
                nxt = jnp.where(live, nxt, tokens)
                return (
                    nxt,
                    pos + live.astype(pos.dtype),
                    counters + live.astype(counters.dtype),
                    kv_pages,
                ), nxt

            if steps > 1:
                init = (tokens0, scan_pos0, counters0, kv_pages)
                carry, scan_out = jax.lax.scan(body, init, rngs[1:])
                out = jnp.concatenate([sampled0[None], scan_out], axis=0)
                kv_pages = carry[3]
            else:
                out = sampled0[None]
            return out, _kv_pin(kv_pages)

        return fn

    def _make_mixed_decode(k_drafts: int):
        """Dense decode packing + self-drafting speculative verify
        (docs/kernels.md): the decode-only companion of `mixed`, chained
        depth-2 by the engine on pure-decode steps.

        Every round, each live lane packs a (K+1)-token slice — its last
        accepted token plus K drafts walked out of a per-lane bigram
        `draft_table` — at a STATIC stride, writes the slice's KV, runs
        the ragged forward once, and samples a target token at every
        slice position.  Acceptance is the vectorized longest prefix of
        drafts matching the target samples; the lane emits acc+1 tokens
        (accepted drafts ARE the target's samples there, plus the bonus
        sample at the rejection/acceptance frontier) and advances kv_len
        by the same amount.  Rollback costs nothing: rejected-draft KV
        sits past every causal horizon (never read) and the lane's next
        slice overwrites it in place.  Emitted tokens are ALWAYS samples
        from the target distribution — greedy lanes are token-identical
        to sequential decode, and seeded lanes are too (the per-row rng
        folds the same (seed, generated-count) pairs sequential decode
        folds).  K=0 degenerates to dense-packed plain decode: one token
        per lane per round, no drafts, no table reads.

        Returns ([rounds, B, K+1] target samples, [rounds, B] emit
        counts, pinned kv_pages, updated draft_table, and the final
        (token, pos, counters) device carry the engine feeds a chained
        dispatch without a host round-trip)."""
        from ..ops.attention import (
            _should_use_ragged_pallas,
            dense_stride_for,
        )
        from ..ops.pallas_paged_attention import RAGGED_BQ

        Kp = k_drafts + 1
        kernel_possible = cfg.use_pallas or (
            cfg.use_pallas is None
            and _should_use_ragged_pallas(mc.head_dim, jax.default_backend())
        )
        align = RAGGED_BQ if kernel_possible else 1
        sp = dense_stride_for(Kp, align)  # padded slice stride
        dense_stride = sp if (kernel_possible and sp < RAGGED_BQ) else None
        dense_attention_fn = None
        if cfg.tp > 1 or cfg.sp > 1:
            from ..ops.attention import make_sharded_ragged_attention

            dense_attention_fn = make_sharded_ragged_attention(
                mesh,
                logit_softcap=mc.attn_logit_softcap,
                use_pallas=cfg.use_pallas,
                quantized=_quantized,
                scale=mc.attn_scale,
                dense_stride=dense_stride,
            )

        def fn(params, tokens, pos, kv_pages, page_table, live, capacity,
               counters, draft_table, state, rng, adapter_ids):
            B = tokens.shape[0]
            rounds = cfg.steps_per_sync
            T = B * sp
            lane_of = jnp.repeat(jnp.arange(B, dtype=jnp.int32), sp)  # [T]
            off = jnp.tile(jnp.arange(sp, dtype=jnp.int32), B)  # [T]
            in_slice = off < Kp  # rows beyond K+1 are stride padding
            q_start = jnp.arange(B, dtype=jnp.int32) * sp
            # packed indices of the real (non-padding) slice rows, in
            # (lane, offset) order — the verify logits gather
            logits_at = (
                jnp.arange(B, dtype=jnp.int32)[:, None] * sp
                + jnp.arange(Kp, dtype=jnp.int32)[None, :]
            ).reshape(-1)
            # per-ROW sampling state: lane i's params replicated over its
            # K+1 slice rows, so every verify position samples with the
            # lane's own temperature/top-k/top-p/seed
            row_state = jax.tree.map(lambda a: jnp.repeat(a, Kp), state)
            rngs = jax.random.split(rng, rounds)
            lane_ix = jnp.arange(B)

            def body(carry, step_rng):
                tok, p, cnt, table, kv_pages = carry
                # a lane runs a round only when its pages cover the whole
                # K+1-token write window; starved lanes sit the round out
                # (the host grows pages between dispatches) — mirrors the
                # capacity freeze of the plain decode scan
                ok = live & (p + Kp <= capacity)
                drafts = []
                prev = tok
                for _ in range(k_drafts):
                    nxt = table[lane_ix, prev]
                    # unseen bigram: draft the token itself (repetition is
                    # the cheapest guess; wrong drafts only cost
                    # acceptance, never correctness)
                    nxt = jnp.where(nxt >= 0, nxt, prev)
                    drafts.append(nxt)
                    prev = nxt
                slice_toks = jnp.stack([tok] + drafts, axis=1)  # [B, Kp]
                pad = jnp.zeros((B, sp - Kp), jnp.int32)
                q_tokens = jnp.concatenate(
                    [slice_toks, pad], axis=1).reshape(T)
                token_seq = jnp.where(
                    ok[lane_of] & in_slice, lane_of, -1)
                token_pos = p[lane_of] + off
                q_len = jnp.where(ok, Kp, 0).astype(jnp.int32)
                logits, kv_pages = llama.forward_ragged(
                    params, mc, q_tokens, token_seq, token_pos,
                    q_start, q_len, p, kv_pages, page_table,
                    cfg.page_size, q_start,  # last_idx unused (logits_at)
                    adapter_ids=adapter_ids,
                    attention_fn=dense_attention_fn,
                    use_pallas=cfg.use_pallas,
                    logits_at=logits_at,
                    dense_stride=dense_stride,
                )  # [B*Kp, V]
                row_counters = (
                    cnt[:, None] + jnp.arange(Kp, dtype=cnt.dtype)[None, :]
                ).reshape(-1)
                sampled = sample_tokens(
                    logits, row_state, step_rng, row_counters
                ).reshape(B, Kp)
                if k_drafts > 0:
                    match = (slice_toks[:, 1:] == sampled[:, :-1])
                    acc = jnp.cumprod(
                        match.astype(jnp.int32), axis=1).sum(axis=1)
                else:
                    acc = jnp.zeros((B,), jnp.int32)
                n_emit = jnp.where(ok, acc + 1, 0)
                new_tok = jnp.where(ok, sampled[lane_ix, acc], tok)
                new_p = p + n_emit
                new_cnt = cnt + n_emit
                if k_drafts > 0:
                    # learn the ACCEPTED chain's bigrams on device:
                    # (chain[j] -> chain[j+1]) for the emitted prefix —
                    # masked pairs scatter to a dropped out-of-range lane
                    chain = jnp.concatenate(
                        [tok[:, None], sampled], axis=1)  # [B, Kp+1]
                    srcs = chain[:, :-1].reshape(-1)
                    dsts = chain[:, 1:].reshape(-1)
                    pair_off = jnp.tile(jnp.arange(Kp), B)
                    pair_ok = (
                        ok[jnp.repeat(lane_ix, Kp)]
                        & (pair_off <= jnp.repeat(acc, Kp))
                    )
                    pair_lane = jnp.where(
                        pair_ok, jnp.repeat(lane_ix, Kp), B)
                    table = table.at[pair_lane, srcs].set(
                        dsts, mode="drop")
                new_carry = (new_tok, new_p, new_cnt, table, kv_pages)
                return new_carry, (sampled, n_emit)

            init = (tokens, pos, counters, draft_table, kv_pages)
            (tok, p, cnt, table, kv_pages), (toks_out, n_out) = (
                jax.lax.scan(body, init, rngs))
            # pin the device carries to canonical spellings — the same
            # settle hazard _kv_pin exists for: the table carry (and
            # the chained tok/pos/cnt) would otherwise come back with a
            # differently-SPELLED sharding and buy one retrace on the
            # next dispatch (tests/test_retrace_budget.py pins the spec
            # steady state at {mixed: 1, mixed_decode: 1}).  The table
            # pins to draft_table_pspec — the spelling GSPMD propagates
            # from the embedding it gathers against (a replicated
            # constraint is treated as unconstrained and re-spelled);
            # the engine commits refresh-built tables to the same.
            rep = shd.named(mesh, _P())
            pin = lambda a: jax.lax.with_sharding_constraint(a, rep)  # noqa: E731
            table = jax.lax.with_sharding_constraint(
                table, shd.named(mesh, shd.draft_table_pspec()))
            return (toks_out, n_out, _kv_pin(kv_pages), table,
                    pin(tok), pin(p), pin(cnt))

        return fn

    def _make_sample_first(with_logprobs: bool):
        def fn(logits, state, rng, in_prompt):
            # same first-token penalty semantics as the batched prefill:
            # repetition penalty counts prompt tokens as seen
            logits = apply_penalties(
                logits,
                jnp.zeros(logits.shape, jnp.int32),
                state.repetition_penalty,
                state.frequency_penalty,
                state.presence_penalty,
                in_prompt,
            )
            first = sample_tokens(logits, state, rng)
            if with_logprobs:
                return first, compute_logprobs(logits, first, cfg.max_logprobs)
            return first

        return fn

    n_kv_args = 3  # kv_pages is arg index 3 in the prefill/decode sigs
    # program name -> (python fn, donated arg indices): the one
    # definition table every consumer (jit, AOT, hlo_oracle) builds from.
    defs = {
        "prefill": (_make_prefill(False), (n_kv_args,)),
        "prefill_lp": (_make_prefill(True), (n_kv_args,)),
        "prefill_chunk": (_prefill_chunk, (4,)),
        "sample_first": (_make_sample_first(False), ()),
        "sample_first_lp": (_make_sample_first(True), ()),
        "decode": (_make_decode(False), (n_kv_args,)),
        "decode_lp": (_make_decode(False, with_logprobs=True), (n_kv_args,)),
        # arg 11 = prompt mask (kept across chunks), arg 12 = counts (donated)
        "decode_penalized": (_make_decode(True), (n_kv_args, 12)),
        "decode_penalized_lp": (
            _make_decode(True, with_logprobs=True), (n_kv_args, 12)),
        "inject": (_inject, (0,)),
        "inject_q": (_inject_q, (0,)),
    }
    if cfg.pp == 1:
        # the mixed program runs the flat per-layer forward; pp>1 engines
        # keep the staged legacy programs (use_ragged forces off there)
        defs["mixed"] = (_make_mixed(), (8,))
        if spec_k is not None:
            # kv_pages (3) is the device-resident carry the engine threads
            # dispatch to dispatch.  The draft table (8) is deliberately
            # NOT donated: on jaxlib 0.4.36's CPU runtime, donating a
            # buffer that the program updates in place via scatter inside
            # a scan corrupts the heap (nondeterministic segfault/abort at
            # later allocation sites — reproduced at 50-100% per
            # tests/test_spec_decode.py run, in-bounds indices included,
            # while kv_pages-only donation is clean under the same loop).
            # The copy this buys back is one
            # [B, V] int32 per dispatch; re-donate after a jaxlib upgrade
            # proves clean under the same stress loop.
            defs["mixed_decode"] = (_make_mixed_decode(int(spec_k)), (3,))
    return defs


def build_compiled(model_config, engine_config, mesh,
                   aot_cache=None, spec_k=None) -> CompiledPrograms:
    """`aot_cache` (an engine/aot_cache.AOTExecutableCache) switches the
    program set from lazy ``jax.jit`` to persistent per-signature AOT
    executables — same call surface, zero compiles on a warm start.  The
    program table itself comes from `program_defs` (one definition table
    serves both dispatch modes AND the hlo_oracle's standalone lowering,
    so a program cannot exist jitted but be missing from the AOT-cached
    build or the perf budgets)."""
    defs = program_defs(model_config, engine_config, mesh, spec_k=spec_k)
    if aot_cache is not None:
        # persistent AOT path (engine/aot_cache.py): per-signature
        # executables lowered once and serialized to disk, so a warm
        # replica start dispatches without a single trace or XLA compile
        from .aot_cache import AOTProgram

        return CompiledPrograms(**{
            name: AOTProgram(name, fn, aot_cache, donate_argnums=donate)
            for name, (fn, donate) in defs.items()
        })
    return CompiledPrograms(**_counted(**{
        name: jax.jit(fn, donate_argnums=donate)
        for name, (fn, donate) in defs.items()
    }))
