"""Engine runtime types: configuration, wire/output dataclasses, slot and
queue bookkeeping, and the deadline-guarded device fetcher.

Split out of engine.py (VERDICT r4 weak #8) so the scheduler/loop module
carries only scheduling logic; these types have no behavior coupling to
the loop.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import List, Optional, Tuple


class _LoopNotify:
    """threading.Event-shaped completion signal for fetch_async: the worker
    thread's set() marshals back onto the event loop via
    call_soon_threadsafe instead of waking a blocked loop thread.  Module
    level (not a per-call closure) — fetch_async runs once per decode
    chunk, the hottest path in the engine."""

    __slots__ = ("_loop", "_event")

    def __init__(self, loop: asyncio.AbstractEventLoop, event: asyncio.Event):
        self._loop = loop
        self._event = event

    def set(self) -> None:
        try:
            self._loop.call_soon_threadsafe(self._event.set)
        except RuntimeError:
            pass  # loop closed mid-shutdown; nobody awaits this

@dataclass
class EngineConfig:
    max_batch_size: int = 8
    page_size: int = 16
    num_pages: int = 2048
    # wedge detection (VERDICT round-2 weak #6): a device fetch exceeding
    # this deadline marks the engine wedged — /v2/health/live goes red so
    # the pod restarts instead of hanging forever.  Must exceed the worst
    # first-call compile (~40s on chip); 300s is 3x slack over that.
    step_deadline_s: float = 300.0
    max_pages_per_seq: int = 128
    max_prefill_len: int = 1024
    prefill_buckets: Tuple[int, ...] = (32, 64, 128, 256, 512, 1024)
    tp: int = 1
    dp: int = 1
    # sequence-parallel mesh axis (ring-attention prefill shards the prompt
    # over it; decode state is replicated across it)
    sp: int = 1
    dtype: str = "bfloat16"
    # tiered KV offload (kv_tiers.py; parity: KVCacheOffloadingSpec,
    # llm_inference_service_types.go:188-260): "none" re-prefills preempted
    # sequences on resume; "host" spills their KV pages to a host-RAM tier
    # (within kv_offload_gib) fronted over an optional disk tier
    # (kv_offload_disk_gib > 0) with lru/arc eviction between them, and
    # re-injects on resume — no recompute.  Entries dropped under pressure
    # re-prefill (performance event, not an error).
    kv_offload: str = "none"
    kv_offload_gib: float = 0.0
    kv_offload_disk_gib: float = 0.0
    kv_offload_dir: str = "/tmp/kserve-tpu-kv"
    kv_offload_policy: str = "lru"  # lru | arc
    # content-addressed persistent prefix store (kvstore/persist.py,
    # docs/kv_hierarchy.md): evicted/reused prefix-cache pages are written
    # as digest-named files under this directory (env KSERVE_TPU_KV_PERSIST;
    # the llmisvc reconciler points it at a subdir of the AOT-cache
    # hostPath), and a restarted/woken replica indexes them at construction
    # and pages hot prefixes back into HBM on first use — shared-system-
    # prompt traffic gets prefix hits from request one.  None = disabled.
    # Enabling it (or kv_offload="host") also turns prefix-cache evictions
    # into tier demotions and admission into a tier-aware page-in path.
    # Host-side only: deliberately NOT part of the AOT cache key.
    kv_persist_dir: Optional[str] = None
    # int8 KV quantization (kvcache.py): halves decode KV traffic and
    # doubles capacity; per-row absmax scales ride a parallel array.
    # Composes with tiered offload (tuple payloads spill/inject both
    # tensors); still incompatible with the pallas kernel and the P/D wire.
    kv_quant: str = "none"  # none | int8
    # int8 weight-only quantization (models/quant.py): halves weight HBM
    # traffic per decode step and the resident footprint — the knob that
    # fits an 8B model on one 16-GB v5e chip.  Orthogonal to kv_quant.
    weight_quant: str = "none"  # none | int8
    # pipeline parallelism (parallel/pipeline.py): layers shard over the
    # `pipe` mesh axis; prefill/decode stream GPipe microbatches through
    # the stages (parity: Parallelism.Pipeline,
    # llm_inference_service_types.go:679-700).  For models that exceed one
    # slice's HBM — within a slice prefer tp.  pp>1 composes with tp>1
    # (each stage's layers keep their megatron shardings; the staged
    # shard_map is manual over `pipe` only, so XLA still inserts the TP
    # collectives inside stages), with dp (disjoint replica meshes), with
    # int8 weights, with chunked prefill (staged: long prompts + prefix
    # cache work under pp), with the host/disk KV offload tiers and int8
    # KV (the stacked cache spills/injects across stages in one op), and
    # with the bf16 P/D wire (the transfer layout is topology-agnostic,
    # so prefill and decode tiers may run different pp/tp meshes; the
    # wire stays bf16 — kv_quant on either P/D tier still raises at call
    # time) and with LoRA (adapter stacks ride the stage-sharded pytree;
    # requires uniform per-layer projection coverage).  pp excludes only
    # sp (raises at init).
    pp: int = 1
    pp_microbatches: int = 0  # 0 = auto (pp when it divides the batch)
    # None = auto (ops/attention.py): the fused Pallas kernel for
    # long-context decode (page-table width >= PALLAS_MIN_PAGES, head_dim %
    # 128 == 0), the XLA gather for short context — each where it measures
    # faster.  True forces the kernel (raises on unsupported head_dim);
    # False forces the gather.
    use_pallas: Optional[bool] = None
    # decode steps executed on-device per host round-trip (lax.scan inner
    # loop).  >1 amortizes host<->device latency — essential when the chip
    # sits behind a network tunnel; streaming granularity becomes K tokens.
    steps_per_sync: int = 8
    # waiting requests prefilled together in one compiled call (padded to the
    # largest length bucket among them; batch padded to pow2)
    prefill_batch: int = 8
    # prefix caching: full prompt pages are kept (refcounted, LRU-evicted on
    # pressure) and shared by later requests with the same page-aligned
    # prefix, which then prefill only their uncached tail (under pp the
    # hit path admits via the STAGED chunked prefill).  None = auto (on).
    prefix_cache: Optional[bool] = None
    # static top-k width for the logprob-emitting program variants (OpenAI
    # caps top_logprobs at 20); requests asking for fewer slice host-side
    max_logprobs: int = 20
    # persistent AOT executable cache (engine/aot_cache.py,
    # docs/coldstart.md): compiled engine programs are serialized to this
    # directory keyed by a config/topology/version digest, and a replica
    # start deserializes instead of tracing — warm starts perform ZERO
    # XLA compiles.  None = disabled (every start compiles).  The llmisvc
    # reconciler mounts a node-local hostPath (or warmed PVC) here via
    # the KSERVE_TPU_AOT_CACHE env.
    aot_cache_dir: Optional[str] = None
    # drive one tiny generation per prefill bucket through the serving
    # loop BEFORE the replica turns ready, so steady-state signatures are
    # compiled (cold) or loaded (warm) ahead of the first real request.
    # None = auto (on when aot_cache_dir is set).
    aot_warmup: Optional[bool] = None
    # unified ragged paged-attention program (docs/kernels.md): prompt
    # chunks and decode lanes fold into ONE `mixed` dispatch per engine
    # step, so decode lanes keep advancing while a prompt prefills and the
    # steady-state compiled-variant count drops to one per shape bucket.
    # None = auto (on wherever it applies: pp==1, sp==1, and
    # max_batch_size <= the largest prefill bucket so a pure-decode step
    # packs).  False = the legacy per-path programs (prefill /
    # prefill_chunk / decode), kept for one release as the fallback.
    # Requests needing per-step logprobs or sampling penalties fall back
    # to the legacy programs per engine iteration even when ragged is on.
    use_ragged: Optional[bool] = None
    # speculative decoding + dense decode packing (docs/kernels.md):
    # None = off (default — the mixed program alone, today's behavior).
    # An int K >= 0 enables the decode-only `mixed_decode` program: all
    # decode lanes pack DENSELY at a static (K+1)-token stride (no more
    # one-kernel-block-per-lane waste) and each of the steps_per_sync
    # rounds drafts K tokens per lane from an on-device per-lane bigram
    # table (seeded host-side from the prompt + generated tokens, updated
    # on device from accepted tokens), verifies them as ONE ragged
    # multi-token chunk through the paged cache, accepts the vectorized
    # longest-matching prefix plus the target's bonus sample, and rewinds
    # by simply not advancing kv_len — rejected draft KV sits beyond every
    # causal horizon and is overwritten in place.  K=0 is dense packing
    # alone (no drafts).  Emitted tokens are ALWAYS target-model samples;
    # greedy streams are token-identical to spec-off.  Requires the
    # unified ragged path (use_ragged); lanes needing per-step logprobs or
    # penalties fall back per iteration like the mixed path does.
    # Deliberately NOT in the AOT cache key until validated on hardware:
    # enabling it disables the persistent AOT executable cache for this
    # engine (engine._build_compiled logs the downgrade).
    spec_decode_k: Optional[int] = None
    # gray-failure watchdog (engine/watchdog.py, docs/resilience.md): a
    # clock-injectable monitor that tracks loop heartbeat, dispatch
    # progress, fetch-worker liveness and tracked-task stalls; a
    # CONFIRMED stall flips readiness and self-drains with checkpoints
    # (the PR 5 salvage path) instead of holding streams hostage until
    # the client deadline or a kubelet SIGKILL.  Off by default: a
    # cold-compiling engine legitimately pauses longer than any useful
    # stall budget — the fleet simulator enables it with tight budgets,
    # production opts in via KSERVE_TPU_WATCHDOG once the AOT cache
    # keeps steady-state dispatch pause-free.  Host-side only:
    # deliberately NOT part of the AOT cache key.
    watchdog: bool = False
    watchdog_interval_s: float = 0.5
    watchdog_suspect_s: float = 5.0
    watchdog_confirm_s: float = 5.0
    watchdog_task_stall_s: float = 30.0
    watchdog_salvage_grace_s: float = 0.0

    def __post_init__(self):
        # prefill buckets must reach max_prefill_len or long prompts would
        # overflow the bucket array
        buckets = sorted(
            {b for b in self.prefill_buckets if b <= self.max_prefill_len}
            | {self.max_prefill_len}
        )
        self.prefill_buckets = tuple(buckets)

    @property
    def max_model_len(self) -> int:
        return self.max_pages_per_seq * self.page_size

    def page_bucket(self, n_pages: int) -> int:
        """Page-table width bucket (pow2) so decode attention only gathers
        as many pages as the longest active sequence actually owns."""
        b = 8
        while b < n_pages:
            b *= 2
        return min(b, self.max_pages_per_seq)


def spec_decode_k_from_env() -> Optional[int]:
    """$KSERVE_TPU_SPEC_DECODE_K -> EngineConfig.spec_decode_k: unset or
    empty = off (None); an integer >= 0 enables speculative decoding /
    dense packing with that K.  Malformed values are logged and ignored
    rather than crash-looping the server on a typo'd env var (the same
    contract the autoscaler's wall-anchor env follows)."""
    import os

    raw = os.environ.get("KSERVE_TPU_SPEC_DECODE_K", "").strip()
    if not raw:
        return None
    try:
        k = int(raw)
        if k < 0:
            raise ValueError("negative")
        return k
    except ValueError:
        from ..logging import logger

        logger.warning(
            "ignoring malformed KSERVE_TPU_SPEC_DECODE_K=%r (want an "
            "integer >= 0)", raw)
        return None


class EngineWedgedError(RuntimeError):
    """A device fetch exceeded step_deadline_s: the device tunnel is
    assumed wedged; liveness fails until the pod restarts."""


class _DeadlineFetcher:
    """One daemon worker thread executing fetch thunks with a deadline.
    A wedged fetch leaves the worker stuck; the thread being a daemon is
    the point — it must never block interpreter shutdown."""

    def __init__(self):
        import queue as _queue
        import threading as _threading

        self._q: "_queue.Queue" = _queue.Queue()
        self._threading = _threading
        self._closed = False
        self._thread = _threading.Thread(
            target=self._run, daemon=True, name="engine-fetch")
        self._thread.start()

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, box, done = item
            try:
                box.append(("ok", fn()))
            # the exception object itself is relayed to the waiting caller
            # through box and re-raised there — nothing is swallowed
            except BaseException as exc:  # jaxlint: disable=swallowed-exception
                box.append(("err", exc))
            done.set()

    def _check_open(self) -> None:
        if self._closed:
            # a drain-path fetch after close() must fail fast, not wait a
            # full deadline on a dead worker queue (that would freeze the
            # event loop through a graceful shutdown)
            raise RuntimeError("engine stopped")

    @staticmethod
    def _unbox(box: list):
        kind, value = box[0]
        if kind == "err":
            raise value
        return value

    def fetch(self, fn, timeout_s: float):
        self._check_open()
        box: list = []
        done = self._threading.Event()
        self._q.put((fn, box, done))
        if not done.wait(timeout_s):
            raise TimeoutError(f"fetch exceeded {timeout_s}s")
        return self._unbox(box)

    async def fetch_async(self, fn, timeout_s: float):
        """fetch() for the decode hot loop: the event-loop thread must not
        sit in a threading wait for device compute — that starves every
        other coroutine (readiness probes, /admin/drain, the drain budget
        loop, admission 503s) for the full duration of the step.  The
        worker signals completion back through call_soon_threadsafe so the
        loop keeps serving while the chunk computes."""
        self._check_open()
        loop = asyncio.get_running_loop()
        event = asyncio.Event()
        box: list = []
        self._q.put((fn, box, _LoopNotify(loop, event)))
        try:
            await asyncio.wait_for(event.wait(), timeout_s)
        except asyncio.TimeoutError:
            raise TimeoutError(f"fetch exceeded {timeout_s}s") from None
        return self._unbox(box)

    def close(self):
        self._closed = True
        self._q.put(None)


@dataclass
class GenerationOutput:
    token_id: int
    text_delta: str
    finished: bool = False
    finish_reason: Optional[str] = None
    num_generated: int = 0
    num_prompt_tokens: int = 0
    cumulative_text: str = ""
    # OpenAI logprobs surface (populated only when the request asked):
    # logprob of the sampled token + [(token_id, logprob)] for the top-k
    logprob: Optional[float] = None
    top_logprobs: Optional[List[tuple]] = None


class _Slot:
    """Host-side state for one decode lane."""

    __slots__ = (
        "request_id", "prompt_len", "prompt_ids", "pages", "pos", "generated",
        "params", "queue", "detok", "stop_texts", "admitted_at", "adapter_id",
        "prefilling", "deadline", "timeline",
    )

    def __init__(self):
        self.request_id: Optional[str] = None
        # long-prompt chunked prefill in progress: {"req", "seq", "done",
        # "logits"} — the run loop advances ONE chunk per iteration so
        # in-flight decode streams keep emitting (bounded stall)
        self.prefilling: Optional[dict] = None
        # the request's propagated resilience.Deadline (None = unbounded);
        # rides the slot so drain checkpoints carry the remaining budget
        self.deadline = None
        # observability.RequestTimeline stamped by the loop (None only for
        # an unseated slot) — survives preemption via _QueuedRequest
        self.timeline = None

    def reset(self):
        self.request_id = None
        self.prefilling = None
        self.timeline = None


class _QueuedRequest:
    def __init__(self, request_id, prompt_ids, params, queue,
                 kv_data=None, first_token=None, adapter_id=-1,
                 deadline=None, timeline=None):
        self.request_id = request_id
        self.prompt_ids = prompt_ids
        self.params = params
        self.queue = queue
        self.adapter_id = adapter_id  # LoRA stack row; -1 = base model
        # resilience.Deadline captured at submit: admission drops the
        # request with DeadlineExceededError once it expires while queued
        self.deadline = deadline
        # P/D disaggregation: KV computed by a prefill-role server
        # ([L, P, 2, n_kv, ps, d] host array) plus its sampled first token —
        # admission scatters the pages instead of prefilling
        self.kv_data = kv_data
        self.first_token = first_token
        # preemption resume state: {generated, detok, stop_texts, pos,
        # admitted_at, kv (host np | None)} — with kv, admission re-injects
        # the spilled pages; without, it re-prefills prompt+generated[:-1]
        self.resume: Optional[dict] = None
        # hierarchical-store page-in state (engine._maybe_page_in): None =
        # not yet consulted, "pending" = an async tier->device upload for
        # this request's prefix is in flight (admission waits, decode
        # continues), "done" = consulted — admit on whatever the HBM
        # prefix cache now holds
        self.pagein: Optional[str] = None
        # observability.RequestTimeline: stamped received at submit, rides
        # the request across preemption/re-seat so TTFT/queue-wait measure
        # the CLIENT's experience, not the latest seat's
        self.timeline = timeline

    @property
    def kv_len(self) -> int:
        """Token positions whose KV must exist before decoding starts."""
        return self.resume["pos"] if self.resume else len(self.prompt_ids)
