"""Prefix cache: digest-chained prompt pages shared across requests.

Split out of engine.py (VERDICT r4 weak #8).  Full prompt pages are kept
after a request finishes (the cache holds its own allocator reference,
so shared pages survive the owner), LRU-ordered; later requests with the
same page-aligned prefix reuse them and prefill only their uncached
tail.  Under page pressure the engine evicts cold cached pages before
failing admission or preempting anything.

Keys are blake2b digest chains (scheduler/prefix.py) — the SAME digests
the EPP endpoint picker scores against, so routing affinity and cache
hits cannot drift apart.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List

from ..scheduler.prefix import token_prefix_digests


class PrefixCache:
    def __init__(self, page_size: int, enabled: bool, allocator):
        self.page_size = page_size
        self.enabled = enabled
        self.allocator = allocator
        # chained page key -> page id, LRU-ordered (front = coldest)
        self._pages: "OrderedDict[bytes, int]" = OrderedDict()
        self.hits = 0  # pages reused (observability/tests)

    def __len__(self) -> int:
        return len(self._pages)

    def _keys(self, seq: List[int], for_lookup: bool) -> List[bytes]:
        """Digest-chained page keys for page-aligned prefixes of `seq`
        (blake2b over prev_digest || page tokens: O(page) per key, no
        nested-tuple rehash blowup)."""
        return token_prefix_digests(seq, self.page_size, for_lookup)

    def lookup(self, seq: List[int]) -> List[int]:
        """Longest cached page run for this sequence (pages NOT yet
        shared — the caller shares on admission)."""
        if not self.enabled:
            return []
        pages = []
        for key in self._keys(seq, for_lookup=True):
            page = self._pages.get(key)
            if page is None:
                break
            self._pages.move_to_end(key)  # LRU touch
            pages.append(page)
        return pages

    def register(self, prompt_ids: List[int], pages: List[int],
                 start_page: int = 0) -> None:
        """Register full prompt pages; start_page skips already-registered
        prefixes (incremental registration during interleaved prefill)."""
        if not self.enabled:
            return
        for i, key in enumerate(self._keys(prompt_ids, for_lookup=False)):
            if i < start_page or key in self._pages:
                continue
            page = pages[i]
            self._pages[key] = page
            self.allocator.share([page])  # the cache's own reference

    def ensure_allocatable(self, n: int) -> bool:
        """can_allocate with LRU eviction as the pressure valve: cold
        cached pages are dropped (their cache ref freed) before admission
        fails or anything gets preempted."""
        while not self.allocator.can_allocate(n) and self._pages:
            _, page = self._pages.popitem(last=False)
            self.allocator.free([page])
        return self.allocator.can_allocate(n)

    def hottest_digests(self, max_digests: int) -> List[str]:
        """Hex digests, most-recently-used LAST slice (the EPP picker's
        affinity advertisement)."""
        return [k.hex() for k in list(self._pages.keys())[-max_digests:]]
